"""HLO-tier rules: contracts that only hold (or break) AFTER XLA.

The jaxpr tier sees what was written; this tier sees what will run.
XLA is free to re-fuse a ring into a monolithic all-gather, hoist a
guarded apply out of its ``conditional``, or drop input-output aliasing
when a program stops being donation-friendly — all invisible at trace
time.  These rules run over the parsed optimized-HLO module
(:func:`apex_tpu.analysis.hlo.parse_hlo`) and are gated on each
program's declared expectations (:class:`apex_tpu.analysis.program.Program`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List

from apex_tpu.analysis.findings import ERROR, Finding
from apex_tpu.analysis.hlo import HloModule, hlo_op_counts
from apex_tpu.analysis.jaxpr_tier import perm_problems
from apex_tpu.analysis.registry import register

__all__ = ["HloCtx", "run_hlo_rules"]


@dataclasses.dataclass
class HloCtx:
    """What an HLO-tier rule sees."""

    program: Any          # analysis.program.Program
    module: HloModule


def run_hlo_rules(ctx: HloCtx, rules=None) -> List[Finding]:
    from apex_tpu.analysis.registry import rules_for

    findings: List[Finding] = []
    for rule in (rules if rules is not None else rules_for("hlo")):
        findings.extend(rule.fn(ctx))
    return findings


@register("APX201", tier="hlo", title="ring-integrity",
          catches="overlap_comm ring re-fused by XLA into a monolithic "
                  "collective (>= tp-1 collective-permutes must survive; "
                  "forbidden monolithic opcodes must stay at zero)",
          motivation="PR 2: XLA's own collective-matmul pass works in "
                     "the opposite direction — a silent re-fusion makes "
                     "the overlap tests vacuously pass on values while "
                     "measuring nothing (testing/hlo.py's raison d'etre)")
def ring_integrity(ctx: HloCtx):
    tp = ctx.program.expect_ring
    if not tp:
        return
    counts = hlo_op_counts(ctx.module)
    got = counts["collective-permute"]
    if got < tp - 1:
        yield Finding(
            rule="APX201", severity=ERROR,
            location=f"{ctx.program.name}: optimized HLO",
            message=f"ring decomposition did not survive jit: "
                    f"{got} collective-permute(s) < tp-1 = {tp - 1}",
            remediation="the unrolled ring must keep one distinct "
                        "ppermute per hop (transformer/tensor_parallel/"
                        "overlap.py); check for a jax/XLA version change "
                        "re-fusing the schedule")
    for op in ctx.program.forbid_ops:
        n = counts[op]
        if n:
            yield Finding(
                rule="APX201", severity=ERROR,
                location=f"{ctx.program.name}: optimized HLO",
                message=f"monolithic {op} reappeared on the decomposed "
                        f"path ({n} occurrence(s))",
                remediation="XLA re-fused the ring into the collective "
                            "the decomposition exists to avoid; the "
                            "overlap is measuring nothing")


@register("APX202", tier="hlo", title="collective-permute-pairs",
          catches="collective-permute whose source_target_pairs is not "
                  "a valid partial permutation (duplicate source or "
                  "target)",
          motivation="PR 2: a mismatched ring permutation is a deadlock "
                     "on real ICI — two senders into one receiver, or "
                     "one rank sending twice, wedges the chip-to-chip "
                     "transfer engine")
def collective_permute_pairs(ctx: HloCtx):
    for inst in ctx.module.instructions():
        if inst.base_opcode != "collective-permute":
            continue
        pairs = inst.source_target_pairs()
        if not pairs:
            continue
        problems = perm_problems(pairs)
        if not problems:
            continue
        yield Finding(
            rule="APX202", severity=ERROR,
            location=f"{ctx.program.name}: %{inst.name} in "
                     f"{inst.computation or 'entry'} "
                     f"(line {inst.line_no + 1})",
            message=f"malformed source_target_pairs {pairs}: "
                    + "; ".join(problems),
            remediation="each rank at most once as source and once as "
                        "target; ring hops are [(i, (i±1) % n)]")


@register("APX203", tier="hlo", title="conditional-survival",
          catches="sentinel-guarded optimizer apply optimized away: no "
                  "`conditional` left in the compiled program",
          motivation="PR 3: 'a skipped step moves no collective bytes' "
                     "— the lax.cond guard must survive as ONE compiled "
                     "conditional (no host round-trip, params/state "
                     "bit-unchanged on skip); previously one hand-rolled "
                     "string assert per test")
def conditional_survival(ctx: HloCtx):
    if not ctx.program.expect_conditional:
        return
    n = hlo_op_counts(ctx.module)["conditional"]
    if n < 1:
        yield Finding(
            rule="APX203", severity=ERROR,
            location=f"{ctx.program.name}: optimized HLO",
            message="no `conditional` survived optimization — the "
                    "sentinel's lax.cond-guarded apply was flattened "
                    "(both branches would execute, a skipped step would "
                    "still move collective bytes) or hoisted to a host "
                    "round-trip",
            remediation="guard the WHOLE optimizer apply in one lax.cond "
                        "on a traced predicate "
                        "(resilience.guarded_optimizer_step); do not "
                        "pre-evaluate the flag on host")


@register("APX204", tier="hlo", title="donation-aliasing",
          catches="donated inputs (ZeRO flat buckets, optimizer state) "
                  "that lost input-output aliasing — a silent 2x HBM "
                  "cost",
          motivation="PR 1: the flat-bucket ZeRO state and master "
                     "weights are the largest buffers in the job; "
                     "losing donation doubles their footprint without "
                     "any failing test (cf. tests/test_wgrad_accum.py)")
def donation_aliasing(ctx: HloCtx):
    expect = ctx.program.expect_donation
    if not expect:
        return
    aliased = ctx.module.aliased_parameters()
    if len(aliased) >= expect:
        return
    yield Finding(
        rule="APX204", severity=ERROR,
        location=f"{ctx.program.name}: optimized HLO module header",
        message=f"only {len(aliased)} input parameter(s) aliased to "
                f"outputs, expected >= {expect}; donated buffers are "
                "being copied (silent 2x HBM for params/optimizer "
                "state)",
        remediation="pass donate_argnums for params/opt-state, keep "
                    "donated shapes/dtypes matching their outputs, and "
                    "do not wrap an already-donating jitted step in a "
                    "fresh jax.jit (that drops donation)")
