"""Structured findings — what every rule emits and every consumer reads.

A finding is (rule id, severity, location, message, remediation): enough
for the CLI to print an actionable line, for tests to assert "exactly
rule X fired here", and for the rulebook table in ``docs/analysis.md`` to
stay the single glossary.  Severity semantics follow the usual linter
contract: only ``ERROR`` findings fail ``python -m apex_tpu.analysis``
(and therefore ``tests/test_analysis.py``); ``WARNING`` marks hazards the
analyzer could not fully resolve statically (e.g. a cond predicate whose
slice leaves the scope it can see).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Tuple

__all__ = ["ERROR", "WARNING", "INFO", "Finding", "Report"]

ERROR = "error"
WARNING = "warning"
INFO = "info"

_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation (or hazard) at one location.

    ``rule``        — rulebook id (``"APX101"``, ...; see docs/analysis.md)
    ``severity``    — :data:`ERROR` / :data:`WARNING` / :data:`INFO`
    ``location``    — where: program name + eqn/instruction path + source
                      line when the jaxpr carries one
    ``message``     — what is wrong, concretely (shapes, axes, counts)
    ``remediation`` — how to fix it (the rule's cookbook line)
    """

    rule: str
    severity: str
    location: str
    message: str
    remediation: str = ""

    def format(self) -> str:
        txt = f"{self.rule} {self.severity.upper():7s} {self.location}: " \
              f"{self.message}"
        if self.remediation:
            txt += f"\n    hint: {self.remediation}"
        return txt


class Report:
    """An ordered collection of findings with pass/fail semantics."""

    def __init__(self, findings: Iterable[Finding] = ()):
        self.findings: List[Finding] = list(findings)

    def extend(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    def by_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def ok(self) -> bool:
        """No ERROR findings (warnings do not fail a lint)."""
        return not self.errors()

    def counts(self) -> Tuple[int, int, int]:
        e = sum(1 for f in self.findings if f.severity == ERROR)
        w = sum(1 for f in self.findings if f.severity == WARNING)
        return e, w, len(self.findings) - e - w

    def format(self) -> str:
        if not self.findings:
            return "no findings"
        ordered = sorted(self.findings,
                         key=lambda f: (_ORDER.get(f.severity, 9), f.rule))
        return "\n".join(f.format() for f in ordered)

    def __iter__(self):
        return iter(self.findings)

    def __len__(self):
        return len(self.findings)

    def __repr__(self):
        e, w, i = self.counts()
        return f"Report(errors={e}, warnings={w}, info={i})"
