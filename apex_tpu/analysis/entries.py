"""Registered entry configs — the programs the whole rulebook runs over.

``python -m apex_tpu.analysis --all-entries`` lints the same staged
programs the test suite and the driver exercise, each built tiny on the
8-virtual-device CPU mesh (the ``tests/conftest.py`` environment):

- ``gpt_3d``    — the dp×pp×tp+sp 3D GPT trainer: its *loss function*
  (linted as a to-be-differentiated program — the APX101 rank-0 contract
  from the PR 2 postmortem) and its sentinel-armed, donated train step.
- ``zero_flat`` / ``zero_leaf`` — the ZeRO data-parallel train steps
  (flat-bucket and per-leaf layouts), sentinel armed, donated: the
  cond-guarded collective path (APX102/APX203) and the donation audit
  over the sharded optimizer state (APX204).
- ``dryrun``    — the MoE-enabled 3D config mirroring
  ``__graft_entry__.dryrun_multichip``'s first step (dp=2 × pp=2(×vpp=2)
  × tp=2+sp, Switch-MoE experts on the dp axis).
- ``overlap``   — the PR 2 ring-decomposed collective matmuls at tp=2:
  ring integrity (APX201) and permutation well-formedness (APX104/202).
- ``reshard``   — the ISSUE 6 restore-anywhere path: a flat-bucket ZeRO
  train state is SAVED under dp=4, reshard-restored onto the dp=8 mesh
  (``resilience.reshard.restore_resharded`` — buffers re-chunked for
  the new world), and the donated train step is linted over the
  RESTORED arrays.  The APX204 donation audit is the point: restored
  leaves arrive via ``make_array_from_callback``, and a layout/
  committed-ness regression on that path would silently drop the
  params+state aliasing that keeps ZeRO in its HBM budget.
- ``serving_decode`` — the ISSUE 9 serving runtime's jit-stable decode
  step (and, jaxpr-tier, its packed prefill) at tp=2: APX204 audits
  that both paged KV-cache arenas alias in->out through the donated
  step — a non-donated cache doubles the largest HBM tenant of a
  serving chip — with the rest of the rulebook over the tp decode
  path.

Builders construct params by *executing only initializers* — the linted
train/loss/ring programs themselves are traced and lowered, never run.
Each entry owns the global mesh for its lifetime; ``run_entry`` destroys
it afterwards so entries compose in one process (and with pytest's
``_fresh_parallel_state``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from apex_tpu.analysis.findings import Report
from apex_tpu.analysis.program import Program
from apex_tpu.analysis.runner import analyze_program

__all__ = ["ENTRIES", "run_entry"]

ENTRIES: Dict[str, Callable[[], List[Program]]] = {}


def _entry(name):
    def deco(fn):
        ENTRIES[name] = fn
        return fn

    return deco


def _leaves(*trees) -> int:
    import jax

    return sum(len(jax.tree_util.tree_leaves(t)) for t in trees)


def _build_zero(flat_bucket: bool, tag: str) -> List[Program]:
    import jax
    import jax.numpy as jnp

    from apex_tpu import parallel
    from apex_tpu.amp.scaler import DynamicLossScale
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel.distributed import (
        dp_shard_batch,
        zero_data_parallel_train_step,
        zero_init,
    )
    from apex_tpu.resilience import sentinel_init

    mesh = parallel.initialize_model_parallel()  # all dp
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (13, 7)),
              "b": jnp.zeros((7,))}

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=flat_bucket,
                               n_buckets=2)
    state = zero_init(opt, params, mesh)
    scaler = DynamicLossScale(init_scale=16.0)
    sent = sentinel_init(scaler)
    step = zero_data_parallel_train_step(
        loss_fn, opt, mesh=mesh, scaler=scaler, donate=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 13))
    y = jax.random.normal(jax.random.PRNGKey(2), (16, 7))
    batch = dp_shard_batch((x, y), mesh)
    return [Program(
        name=f"{tag}/train_step",
        fn=step, args=(params, state, batch, sent),
        expect_conditional=True,
        expect_donation=_leaves(params, state),
    )]


@_entry("zero_flat")
def _zero_flat() -> List[Program]:
    return _build_zero(True, "zero_flat")


@_entry("zero_leaf")
def _zero_leaf() -> List[Program]:
    return _build_zero(False, "zero_leaf")


def _build_gpt(tag: str, *, moe: bool) -> List[Program]:
    import jax

    from apex_tpu.amp.scaler import DynamicLossScale
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.resilience import sentinel_init
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    vpp = 2 if moe else 1
    mesh = mesh_lib.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2,
        virtual_pipeline_model_parallel_size=vpp if vpp > 1 else None)
    cfg = TransformerConfig(
        hidden_size=32, num_layers=2 * vpp, num_attention_heads=2,
        padded_vocab_size=64, max_position_embeddings=16,
        hidden_dropout=0.0, attention_dropout=0.0,
        tensor_axis="tp", sequence_parallel=True,
        num_experts=4 if moe else None,
        expert_axis="dp" if moe else None)
    init_fn, make_loss_fn, make_train_step = build_gpt_3d(
        cfg, num_chunks=vpp, num_microbatches=2, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    params, specs = init_fn(jax.random.PRNGKey(0), tokens)
    loss_fn = make_loss_fn(specs)
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    scaler = DynamicLossScale()
    sent = sentinel_init(scaler)
    step = jax.jit(make_train_step(opt, specs, scaler=scaler),
                   donate_argnums=(0, 1))
    return [
        # the program users differentiate: the APX101 rank-0 contract
        # (grad-path scalars (1,)-shaped inside, squeezed outside) is
        # enforced here, where the PR 2 _SpecError lived
        Program(name=f"{tag}/loss", fn=loss_fn, args=(params, tokens),
                differentiated=True, hlo_tier=False),
        # Donation floor: the optimizer state (m/v — the 2x-params HBM
        # the audit exists for) must stay fully aliased.  XLA declines
        # aliasing for a minority of the [vpp, pp, ...]-stacked layer
        # params on this path, so the all-leaves bound used for the ZeRO
        # entries would be flaky here; a dropped donate_argnums still
        # crashes through this floor (0 aliased).
        #
        # The dryrun (MoE) variant skips the HLO tier: its unique
        # coverage — expert-parallel all_to_alls, the vpp-stacked layer
        # params, the MoE aux slot riding the pipeline — is all visible
        # to the jaxpr rules, while the HLO contracts (conditional
        # survival, donation) are structurally identical to gpt_3d's and
        # already compiled there; skipping the second 3D XLA compile
        # keeps graph_lint inside the tier-1 window.
        Program(name=f"{tag}/train_step",
                fn=step, args=(params, state, tokens, sent),
                hlo_tier=not moe,
                expect_conditional=not moe,
                expect_donation=_leaves(state) if not moe else None),
    ]


@_entry("gpt_3d")
def _gpt_3d() -> List[Program]:
    return _build_gpt("gpt_3d", moe=False)


@_entry("dryrun")
def _dryrun() -> List[Program]:
    return _build_gpt("dryrun", moe=True)


@_entry("overlap")
def _overlap() -> List[Program]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.parallel import collectives as cc
    from apex_tpu.transformer.tensor_parallel.overlap import (
        gather_matmul,
        matmul_scatter,
    )

    tp = 2
    parallel.initialize_model_parallel(tensor_model_parallel_size=tp)
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(k1, (16, 3, 8), jnp.float32)
    w = jax.random.normal(k2, (24, 8), jnp.float32) / np.sqrt(8)

    gm = cc.shard_over(
        lambda xs, ws: gather_matmul(xs, ws, "tp"),
        in_specs=(P("tp", None, None), P("tp", None)),
        out_specs=P(None, None, "tp"))
    ms = cc.shard_over(
        lambda xs, ws: matmul_scatter(xs, ws, "tp"),
        in_specs=(P(None, None, "tp"), P(None, "tp")),
        out_specs=P("tp", None, None))
    return [
        Program(name="overlap/gather_matmul", fn=gm, args=(x, w),
                expect_ring=tp, forbid_ops=("all-gather",)),
        Program(name="overlap/matmul_scatter", fn=ms, args=(x, w),
                expect_ring=tp, forbid_ops=("reduce-scatter",)),
    ]


@_entry("reshard")
def _reshard() -> List[Program]:
    """Restored-state train step (ISSUE 6 analyzer satellite): save a
    flat-bucket ZeRO checkpoint under dp=4, reshard-restore it onto the
    full dp=8 mesh, and lint the donated train step with the restored
    arrays as inputs — so a resharded restore cannot silently drop
    buffer donation (APX204) or the sentinel conditional (APX203)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from apex_tpu import parallel
    from apex_tpu.amp.scaler import DynamicLossScale
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.parallel.distributed import (
        dp_shard_batch,
        replicate,
        zero_data_parallel_train_step,
        zero_init,
    )
    from apex_tpu.resilience import (
        CheckpointManager,
        reshard,
        sentinel_init,
    )

    host_params = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (13, 7)),
        "b": jnp.zeros((7,)),
    }
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=2)
    scaler = DynamicLossScale(init_scale=16.0)

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    def build(mesh):
        p = replicate(host_params, mesh)
        pack = {"params": p, "opt": zero_init(opt, p, mesh),
                "sent": replicate(sentinel_init(scaler), mesh)}
        spec = reshard.build_spec(pack, mesh=mesh,
                                  zero_states=[("opt", opt, p)])
        return pack, spec

    workdir = tempfile.mkdtemp(prefix="apex_reshard_entry_")
    try:
        # writer: dp=4 sub-mesh — its flat buckets are 4-way chunked
        mesh = parallel.initialize_model_parallel(
            devices=jax.devices("cpu")[:4])
        pack, spec = build(mesh)
        mgr = CheckpointManager(workdir, sharded=True, spec=spec)
        mgr.save(pack, 0)
        mesh_lib.destroy_model_parallel()

        # reader: the full dp=8 mesh — restore_latest reshards
        mesh = parallel.initialize_model_parallel()
        like, spec8 = build(mesh)
        restored, _ = CheckpointManager(
            workdir, sharded=True, spec=spec8).restore_latest(like)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    step = zero_data_parallel_train_step(
        loss_fn, opt, mesh=mesh, scaler=scaler, donate=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 13))
    y = jax.random.normal(jax.random.PRNGKey(2), (16, 7))
    batch = dp_shard_batch((x, y), mesh)
    return [Program(
        name="reshard/restored_train_step",
        fn=step,
        args=(restored["params"], restored["opt"], batch,
              restored["sent"]),
        expect_conditional=True,
        expect_donation=_leaves(restored["params"], restored["opt"]),
    )]


@_entry("serving_decode")
def _serving_decode() -> List[Program]:
    """The ISSUE 9/12/13/17 serving runtime's decode step at tp=2 (the
    jit-stable continuous-batching shape — since ISSUE 13 the
    ``[max_batch, k + 1]`` speculative verify, with per-slot draft
    counts, eviction/preemption churn AND the sampling policies all
    riding as ``[max_batch]`` data; since ISSUE 17 the LoRA-enabled
    step, with per-slot adapter indices as data and the adapter A/B
    gathers inside the same compiled program): the APX204 donation
    audit is the point — the paged KV arenas AND the paged adapter
    arena are the serving chip's resident HBM tenants and MUST alias
    in->out through the step (2 KV leaves + 8 adapter leaves, hence
    the exact floor of 10); a dropped ``donate_argnums`` or an
    aliasing regression on the scatter+Pallas-read+sampling path
    doubles cache HBM silently.  APX201/202/203 run over the same tp
    decode path (no ring / no sentinel: contracts default off), and
    the jaxpr tier (APX101/104 via lint_traced) walks the shard_map
    body including the Pallas call sites and the new adapter-delta
    kernels.  The chunked-prefill program rides along jaxpr-tier-only
    (its HLO contracts are structurally the decode step's; one XLA
    compile is enough for the tier-1 window)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.serving import (
        LoRAConfig, ServingConfig, ServingEngine, SpeculativeConfig)
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    mesh = parallel.initialize_model_parallel(tensor_model_parallel_size=2)
    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=64, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=2, num_microbatches=1,
                                 mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0), jnp.zeros((2, 4), jnp.int32))
    eng = ServingEngine(
        cfg, ServingConfig(max_batch=2, block_size=4, max_seq=16,
                           prefill_len=16,
                           speculative=SpeculativeConfig(k=2),
                           lora=LoRAConfig(rank=4, max_adapters=2)),
        params, mesh=mesh)
    b = eng.serving.max_batch
    S = eng.spec_width
    mb = eng.cache.max_blocks_per_request
    adapter_slots = np.zeros((b,), np.int32)
    sampling = (np.zeros((b,), np.float32), np.zeros((b,), np.int32),
                np.ones((b,), np.float32), np.zeros((b,), np.uint32),
                np.zeros((b,), np.int32))
    decode_args = (
        eng.arenas, eng.adapters, eng.params,
        np.zeros((b, S), np.int32), np.zeros((b,), np.int32),
        jnp.zeros((b, mb), jnp.int32), np.zeros((b,), bool),
        np.zeros((b,), np.int32), adapter_slots) + sampling
    T = eng.prefill_len
    prefill_args = (
        eng.arenas, eng.adapters, eng.params,
        np.zeros((b, T), np.int32), np.zeros((b, T), np.int32),
        jnp.zeros((b, mb), jnp.int32), np.zeros((b,), np.int32),
        np.zeros((b, T), np.int32), np.zeros((b, T), np.int32),
        np.zeros((b, T), np.int32), np.full((b,), T, np.int32),
        adapter_slots) + sampling
    return [
        Program(name="serving_decode/decode_step",
                fn=eng._decode, args=decode_args,
                expect_donation=10),
        Program(name="serving_decode/prefill",
                fn=eng._prefill, args=prefill_args,
                hlo_tier=False),
    ]


def run_entry(name: str) -> Tuple[Report, int]:
    """Build one entry, run the rulebook over each of its programs, tear
    the mesh down.  Returns (report, program_count)."""
    from apex_tpu.parallel import mesh as mesh_lib

    report = Report()
    try:
        # builders register the global mesh; keep them inside the
        # try so a failed build cannot leak it to later callers
        programs = ENTRIES[name]()
        for prog in programs:
            report.extend(analyze_program(prog))
    finally:
        mesh_lib.destroy_model_parallel()
    return report, len(programs)
