"""Run the rulebook over programs — the one analyze pipeline.

``analyze_program`` is the single code path behind the CLI, the pytest
fixture, and the ``lint_traced``/``lint_hlo`` helpers tests call
directly, so "the contract is checked by one shared implementation"
holds all the way down: a test asserting conditional-survival and
``python -m apex_tpu.analysis`` run the identical rule function.

Nothing here executes the analyzed program: the jaxpr tier stages with
``jax.make_jaxpr`` (abstract evaluation), the HLO tier stops at
``lower().compile().as_text()``.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.analysis.findings import Report
from apex_tpu.analysis.hlo import compiled_hlo, parse_hlo
from apex_tpu.analysis.hlo_rules import HloCtx, run_hlo_rules
from apex_tpu.analysis.jaxpr_tier import JaxprCtx, run_jaxpr_rules, trace
from apex_tpu.analysis.program import Program

__all__ = ["analyze_program", "lint_traced", "lint_hlo"]


def analyze_program(program: Program) -> Report:
    """Run every applicable rule over one program; returns a Report."""
    report = Report()
    if program.fn is not None and program.jaxpr_tier:
        closed, findings = trace(program.fn, *program.args,
                                 **program.kwargs)
        report.extend(findings)
        if closed is not None:
            report.extend(run_jaxpr_rules(JaxprCtx(program, closed)))
    hlo_text = program.hlo_text
    if hlo_text is None and program.fn is not None and program.hlo_tier:
        hlo_text = compiled_hlo(program.fn, *program.args,
                                **program.kwargs)
    if hlo_text is not None:
        report.extend(run_hlo_rules(HloCtx(program, parse_hlo(hlo_text))))
    return report


def lint_traced(fn, *args, name: Optional[str] = None,
                differentiated: bool = False, hlo: bool = False,
                **expect) -> Report:
    """Jaxpr-tier lint of ``fn`` at example ``args`` (``hlo=True`` also
    compiles and runs the HLO tier).  ``expect`` forwards Program
    expectation fields (``expect_conditional=...``, ``expect_ring=...``,
    ``forbid_ops=...``, ``expect_donation=...``)."""
    return analyze_program(Program(
        name=name or getattr(fn, "__name__", "traced"),
        fn=fn, args=args, differentiated=differentiated,
        hlo_tier=hlo, **expect))


def lint_hlo(hlo_text: str, name: str = "hlo", **expect) -> Report:
    """HLO-tier lint of pre-compiled optimized-HLO text."""
    return analyze_program(Program(
        name=name, hlo_text=hlo_text, jaxpr_tier=False, **expect))
