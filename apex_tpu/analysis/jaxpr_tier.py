"""Jaxpr-tier rules: lint staged programs without executing them.

``trace`` stages a function with ``jax.make_jaxpr`` (abstract values only
— nothing runs), and the walker descends every sub-jaxpr an equation
carries (``shard_map`` bodies, ``cond`` branches, ``pjit``/``scan``/
``while``/``custom_vjp`` calls), tracking the scope stack so rules know
which mesh axes are bound and which ``cond`` they sit under.

The rules mechanize this repo's prose invariants:

- **APX101** — rank-0 inexact values crossing a ``shard_map``/
  ``shard_over`` boundary of a program the caller declares it will
  differentiate.  jax 0.4.x's old-style shard_map cannot name-check
  rank-0 values crossing the boundary in the transposed program
  (``_check_names`` trips a ``_SpecError`` on scalar residual out-names
  — the exact PR 2 ``dryrun_multichip`` hunt); the repo convention is to
  keep every such scalar ``(1,)``-shaped inside the body and squeeze
  outside (``gpt_parallel_train._local_loss``).
- **APX102** — ``psum``/``ppermute``/... under a ``lax.cond`` branch
  whose predicate is not agreed over the collective's axes.  Ranks that
  disagree on the predicate take different branches and the collective
  deadlocks on real ICI; the sentinel contract (PR 3) requires the
  overflow flag to be ``pmin``-agreed over every axis the guarded
  optimizer communicates on (``resilience/sentinel.py``).
- **APX103** — collectives over axis names absent from the enclosing
  mesh.  Normally jax raises ``NameError: unbound axis name`` at trace
  time — :func:`trace` converts that into this finding — but nested
  scopes and transformed jaxprs can carry the mismatch silently, so the
  static walk checks every collective eqn too.
- **APX104** — malformed ``ppermute`` permutations: duplicate sources,
  duplicate targets (two ranks sending into one receiver — a data race
  that deadlocks a real ring), or indices outside the axis size.  jax
  does NOT validate this at trace time (probed on 0.4.37), and a
  mismatched ring is exactly the failure mode the PR 2 overlap rings
  must never regress into.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from apex_tpu.analysis.findings import ERROR, WARNING, Finding
from apex_tpu.analysis.registry import register

__all__ = ["trace", "JaxprCtx", "walk", "run_jaxpr_rules"]

# Collective primitives and where their axis names live in eqn.params.
_COLLECTIVE_AXIS_PARAM = {
    "psum": "axes",
    "pmin": "axes",
    "pmax": "axes",
    "ppermute": "axis_name",
    "all_gather": "axis_name",
    "reduce_scatter": "axis_name",
    "all_to_all": "axis_name",
    "axis_index": "axis_name",
    "pbroadcast": "axes",
}
# Collectives that move payload bytes (axis_index only reads the rank).
_TRAFFIC = frozenset(_COLLECTIVE_AXIS_PARAM) - {"axis_index"}
# Reductions that make a value identical on every rank of their axes.
_AGREEMENT = frozenset({"psum", "pmin", "pmax"})


def collective_axes(eqn) -> Tuple[str, ...]:
    """Named mesh axes a collective eqn operates over (positional ints,
    used by some primitives, are not mesh axes and are skipped)."""
    param = _COLLECTIVE_AXIS_PARAM.get(eqn.primitive.name)
    if param is None:
        return ()
    axes = eqn.params.get(param)
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


_ANALYSIS_DIR = __file__.rsplit("/", 1)[0]


def perm_problems(pairs, size: Optional[int] = None) -> List[str]:
    """Why a (source, target) pair list is not a valid partial
    permutation — shared by APX104 (jaxpr ``perm`` params) and APX202
    (HLO ``source_target_pairs``), so the two tiers can never drift."""
    sources = [s for s, _ in pairs]
    targets = [t for _, t in pairs]
    problems = []
    dup_s = sorted({s for s in sources if sources.count(s) > 1})
    dup_t = sorted({t for t in targets if targets.count(t) > 1})
    if dup_s:
        problems.append(f"duplicate sources {dup_s}")
    if dup_t:
        problems.append(f"duplicate targets {dup_t} (two ranks sending "
                        "into one receiver)")
    if size is not None:
        oob = sorted({r for r in sources + targets
                      if r < 0 or r >= size})
        if oob:
            problems.append(f"ranks {oob} outside axis size {size}")
    return problems


def _source(eqn) -> str:
    """Human-readable source location of an eqn (file:line).  The
    analyzer's own tracing frames are skipped so a shard_map staged via
    :func:`trace` reports where the user built it, not where the linter
    called ``make_jaxpr``."""
    try:
        from jax._src import source_info_util

        for frame in source_info_util.user_frames(eqn.source_info):
            if not frame.file_name.startswith(_ANALYSIS_DIR):
                return f"{frame.file_name}:{frame.start_line}"
    except Exception:
        pass
    return "<unknown source>"


def trace(fn, *args, **kwargs):
    """``jax.make_jaxpr`` without execution.  Returns ``(closed_jaxpr,
    findings)``: an unbound-axis ``NameError`` (a collective over an axis
    the enclosing mesh does not carry — APX103's trace-time form) is
    converted into a finding instead of crashing the lint."""
    import jax

    try:
        return jax.make_jaxpr(fn)(*args, **kwargs), []
    except NameError as e:
        return None, [Finding(
            rule="APX103", severity=ERROR, location=getattr(
                fn, "__name__", str(fn)),
            message=f"tracing failed with unbound axis: {e}",
            remediation="every collective's axis name must be bound by "
                        "the enclosing shard_map/shard_over mesh "
                        "(apex_tpu.parallel.mesh names the canonical "
                        "axes: dcn/dp/pp/cp/tp)")]


# --- the walker ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Scope:
    """One level of the nesting stack above an eqn."""

    kind: str            # "shard_map" | "cond_branch" | "call"
    eqn: Any             # the eqn introducing this scope
    jaxpr: Any           # the jaxpr CONTAINING that eqn
    mesh_axes: Tuple[str, ...] = ()   # shard_map only
    branch_index: int = -1            # cond_branch only


@dataclasses.dataclass(frozen=True)
class Site:
    eqn: Any
    jaxpr: Any                 # jaxpr containing the eqn
    scopes: Tuple[Scope, ...]  # outermost first

    @property
    def mesh_axes(self) -> Tuple[str, ...]:
        """Union of axis names bound by enclosing shard_maps."""
        axes: List[str] = []
        for s in self.scopes:
            if s.kind == "shard_map":
                axes += [a for a in s.mesh_axes if a not in axes]
        return tuple(axes)

    @property
    def in_shard_map(self) -> bool:
        return any(s.kind == "shard_map" for s in self.scopes)

    def shard_map_scope(self) -> Optional[Scope]:
        for s in reversed(self.scopes):
            if s.kind == "shard_map":
                return s
        return None

    def axis_size(self, axes: Sequence[str]) -> Optional[int]:
        """Product of the named axes' sizes on the innermost enclosing
        shard_map mesh (None when unknown)."""
        scope = self.shard_map_scope()
        if scope is None:
            return None
        mesh = scope.eqn.params.get("mesh")
        try:
            shape = dict(mesh.shape)
        except Exception:
            return None
        size = 1
        for a in axes:
            if a not in shape:
                return None
            size *= int(shape[a])
        return size


def _sub_jaxprs(eqn) -> Iterator[Tuple[str, int, Any]]:
    """(param_name, index, open_jaxpr) for every sub-jaxpr in an eqn's
    params — handles both open ``Jaxpr``s (shard_map bodies) and
    ``ClosedJaxpr``s (pjit/scan/cond branches/custom_vjp)."""
    for key, val in eqn.params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for i, sub in enumerate(vals):
            if hasattr(sub, "eqns") and hasattr(sub, "invars"):
                yield key, i, sub          # open Jaxpr
            else:
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield key, i, inner    # ClosedJaxpr


def walk(closed_jaxpr) -> Iterator[Site]:
    """Yield every eqn at every depth with its scope stack."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)

    def rec(jx, scopes):
        for eqn in jx.eqns:
            yield Site(eqn=eqn, jaxpr=jx, scopes=scopes)
            name = eqn.primitive.name
            for key, i, sub in _sub_jaxprs(eqn):
                if name == "shard_map":
                    mesh = eqn.params.get("mesh")
                    axes = tuple(getattr(mesh, "axis_names", ()))
                    scope = Scope(kind="shard_map", eqn=eqn, jaxpr=jx,
                                  mesh_axes=axes)
                elif name == "cond" and key == "branches":
                    scope = Scope(kind="cond_branch", eqn=eqn, jaxpr=jx,
                                  branch_index=i)
                else:
                    scope = Scope(kind="call", eqn=eqn, jaxpr=jx)
                yield from rec(sub, scopes + (scope,))

    yield from rec(jaxpr, ())


def _collectives_within(jx) -> Iterator[Any]:
    """Every payload-moving collective eqn in ``jx``, at any depth."""
    for site in walk(jx):
        if site.eqn.primitive.name in _TRAFFIC:
            yield site.eqn


def _producers(jx) -> Dict[Any, Any]:
    return {ov: eqn for eqn in jx.eqns for ov in eqn.outvars}


def backward_slice(jx, var):
    """Eqns the value of ``var`` depends on, within ``jx`` only, plus the
    indices of ``jx.invars`` the slice escapes into (``-1`` for consts or
    unknowns) — escapes mean the dependency chain continues in an
    enclosing scope this walk cannot see."""
    from jax._src import core

    producers = _producers(jx)
    invars = list(jx.invars)
    constvars = set(jx.constvars)
    seen: Set[Any] = set()
    eqns: List[Any] = []
    escaped: List[int] = []   # indices into jx.invars (or -1 for consts)
    stack = [var]
    while stack:
        v = stack.pop()
        if isinstance(v, core.Literal) or v in seen:
            continue
        seen.add(v)
        eqn = producers.get(v)
        if eqn is not None:
            eqns.append(eqn)
            stack.extend(eqn.invars)
        elif v in constvars:
            escaped.append(-1)
        else:
            try:
                escaped.append(invars.index(v))
            except ValueError:
                escaped.append(-1)
    return eqns, escaped


def _is_inexact(aval) -> bool:
    import jax.numpy as jnp

    dtype = getattr(aval, "dtype", None)
    return dtype is not None and jnp.issubdtype(dtype, jnp.inexact)


# --- rules ---------------------------------------------------------------


@dataclasses.dataclass
class JaxprCtx:
    """What a jaxpr-tier rule sees."""

    program: Any              # analysis.program.Program
    closed_jaxpr: Any


def run_jaxpr_rules(ctx: JaxprCtx, rules=None) -> List[Finding]:
    from apex_tpu.analysis.registry import rules_for

    findings: List[Finding] = []
    for rule in (rules if rules is not None else rules_for("jaxpr")):
        findings.extend(rule.fn(ctx))
    return findings


@register("APX101", tier="jaxpr", title="rank0-across-shard-map",
          catches="rank-0 inexact value crossing a shard_map boundary "
                  "of a program that will be differentiated",
          motivation="PR 2: old-jax shard_map _SpecError hunt — scalar "
                     "residuals cannot be name-checked in the transposed "
                     "program; keep grad-path scalars (1,)-shaped inside, "
                     "squeeze outside")
def rank0_across_shard_map(ctx: JaxprCtx):
    """Only programs declared ``differentiated`` are checked: a step that
    takes its gradients *inside* the shard_map never transposes the
    boundary, and its scalar loss output is legal on every jax version."""
    from jax._src import core

    if not ctx.program.differentiated:
        return
    for site in walk(ctx.closed_jaxpr):
        eqn = site.eqn
        if eqn.primitive.name != "shard_map":
            continue
        sides = (("in", eqn.invars, eqn.params.get("in_names")),
                 ("out", eqn.outvars, eqn.params.get("out_names")))
        for side, vars_, names in sides:
            for i, v in enumerate(vars_):
                if side == "in" and isinstance(v, core.Literal):
                    continue  # constants carry no cotangent
                aval = getattr(v, "aval", None)
                if aval is None or getattr(aval, "shape", None) != ():
                    continue
                if not _is_inexact(aval):
                    continue  # integer/bool scalars are not on grad paths
                spec = None
                if names is not None and i < len(names):
                    spec = names[i]
                yield Finding(
                    rule="APX101", severity=ERROR,
                    location=f"{ctx.program.name}: shard_map {side}var "
                             f"[{i}] ({aval.dtype}[], names={spec}) @ "
                             f"{_source(eqn)}",
                    message="rank-0 inexact value crosses a shard_map "
                            "boundary on a differentiated path; old-jax "
                            "(<=0.4.x) shard_map trips _SpecError "
                            "name-checking scalar residuals in the "
                            "transposed program",
                    remediation="keep the value (1,)-shaped inside the "
                                "shard_map body and squeeze it outside "
                                "(see gpt_parallel_train._local_loss and "
                                "ROADMAP's old-jax constraint)")


@register("APX102", tier="jaxpr", title="collective-under-unagreed-cond",
          catches="collective inside a lax.cond branch whose predicate "
                  "is not agreed over the collective's mesh axes",
          motivation="PR 3: the sentinel's lax.cond-guarded optimizer "
                     "apply — a rank-local overflow flag would diverge "
                     "the branch and deadlock the guarded reduce-"
                     "scatter/all-gather; sentinel_update pmin-agrees it")
def collective_under_unagreed_cond(ctx: JaxprCtx):
    for site in walk(ctx.closed_jaxpr):
        eqn = site.eqn
        if eqn.primitive.name != "cond" or not site.in_shard_map:
            continue
        branch_axes: Dict[str, List[str]] = {}
        for bi, branch in enumerate(eqn.params.get("branches", ())):
            inner = getattr(branch, "jaxpr", branch)
            for ceqn in _collectives_within(inner):
                for ax in collective_axes(ceqn):
                    branch_axes.setdefault(ax, []).append(
                        f"branch[{bi}].{ceqn.primitive.name}")
        if not branch_axes:
            continue
        agreed, resolved = _predicate_agreement(site)
        missing = {a: sites for a, sites in branch_axes.items()
                   if a not in agreed}
        if not missing:
            continue
        detail = "; ".join(f"{ax} used by {', '.join(s)}"
                           for ax, s in sorted(missing.items()))
        if resolved:
            yield Finding(
                rule="APX102", severity=ERROR,
                location=f"{ctx.program.name}: cond @ {_source(eqn)}",
                message="collective(s) under lax.cond with a predicate "
                        f"not agreed over their axes ({detail}); ranks "
                        "that disagree take different branches and the "
                        "collective deadlocks",
                remediation="agree the predicate first — "
                            "sentinel_update(axes=...) pmin-reduces the "
                            "finite flag over every axis the guarded "
                            "step communicates on "
                            "(apex_tpu.resilience.sentinel)")
        else:
            yield Finding(
                rule="APX102", severity=WARNING,
                location=f"{ctx.program.name}: cond @ {_source(eqn)}",
                message="collective(s) under lax.cond whose predicate "
                        f"originates outside the analyzable scope "
                        f"({detail} not provably agreed); verify the "
                        "predicate is identical on those ranks",
                remediation="derive the predicate from a pmin/pmax/psum "
                            "over the branch collectives' axes, or pass "
                            "it in fully replicated")


def _predicate_agreement(site: Site) -> Tuple[Set[str], bool]:
    """Axes over which a cond's predicate is provably rank-uniform, and
    whether the dependency slice fully resolved.

    Agreement sources: pmin/pmax/psum reductions in the predicate's
    backward slice (uniform over their axes), and — when the slice
    reaches the enclosing shard_map body's *inputs* — any input whose
    in_names mark it fully replicated (uniform over the whole mesh)."""
    eqn, jx = site.eqn, site.jaxpr
    pred = eqn.invars[0]
    eqns, escaped = backward_slice(jx, pred)
    agreed: Set[str] = set()
    for e in eqns:
        if e.primitive.name in _AGREEMENT:
            agreed.update(collective_axes(e))
    resolved = not escaped
    if escaped:
        scope = site.shard_map_scope()
        # The predicate (partially) comes from outside this jaxpr.  When
        # this jaxpr IS the shard_map body, the body's in_names say
        # exactly how each escaped input varies: all-replicated inputs
        # are mesh-uniform (agreement over every axis), while a SHARDED
        # input means the predicate provably depends on rank-varying
        # data — the slice is conclusive either way.  Escapes the walk
        # cannot attribute (consts, deeper call scopes) stay unresolved.
        if scope is not None and scope.eqn.params.get("jaxpr") is jx:
            in_names = scope.eqn.params.get("in_names", ())
            known = [idx for idx in escaped
                     if 0 <= idx < len(in_names)]
            if len(known) == len(escaped):
                resolved = True
                if all(not in_names[idx] for idx in known):
                    agreed.update(scope.mesh_axes)
    return agreed, resolved


@register("APX103", tier="jaxpr", title="collective-axis-not-in-mesh",
          catches="collective over an axis name the enclosing "
                  "shard_map mesh does not bind",
          motivation="mesh contract (PR 0/1): all code reduces over the "
                     "canonical dcn/dp/pp/cp/tp axes; a collective naming "
                     "an absent axis is a mis-wired reduction group")
def collective_axis_not_in_mesh(ctx: JaxprCtx):
    for site in walk(ctx.closed_jaxpr):
        name = site.eqn.primitive.name
        if name not in _COLLECTIVE_AXIS_PARAM:
            continue
        axes = collective_axes(site.eqn)
        if not axes:
            continue
        bound = site.mesh_axes
        missing = [a for a in axes if a not in bound]
        if not missing:
            continue
        where = ("no enclosing shard_map"
                 if not site.in_shard_map
                 else f"enclosing mesh axes {tuple(bound)}")
        yield Finding(
            rule="APX103", severity=ERROR,
            location=f"{ctx.program.name}: {name} @ {_source(site.eqn)}",
            message=f"collective over axis {missing} but {where}",
            remediation="bind the axis via shard_over on a mesh that "
                        "carries it (initialize_model_parallel always "
                        "names all five canonical axes)")


@register("APX104", tier="jaxpr", title="ppermute-perm-malformed",
          catches="ppermute permutation with duplicate sources/targets "
                  "or out-of-range ranks",
          motivation="PR 2: the overlap rings are chains of ppermute "
                     "hops; a mismatched permutation is a deadlock on "
                     "real ICI, and jax does not validate it at trace "
                     "time")
def ppermute_perm_malformed(ctx: JaxprCtx):
    for site in walk(ctx.closed_jaxpr):
        eqn = site.eqn
        if eqn.primitive.name != "ppermute":
            continue
        perm = eqn.params.get("perm", ())
        axes = collective_axes(eqn)
        problems = perm_problems(perm, site.axis_size(axes))
        if not problems:
            continue
        yield Finding(
            rule="APX104", severity=ERROR,
            location=f"{ctx.program.name}: ppermute(axis={axes}) @ "
                     f"{_source(eqn)}",
            message=f"malformed permutation {tuple(perm)}: "
                    + "; ".join(problems),
            remediation="each rank must appear at most once as source "
                        "and once as target; rings use "
                        "[(i, (i±1) % n) for i in range(n)] "
                        "(parallel.collectives.send_recv_next/prev)")
