"""``python -m apex_tpu.analysis`` — run the rulebook from the shell.

The CI face of the analyzer (``scripts/graph_lint.sh``): lints the
registered entry configs on the CPU mesh and exits non-zero when any
ERROR finding fires, so a regressed invariant fails fast in the same
place for every consumer.  ``tests/test_analysis.py`` calls
:func:`main` in-process as the fast-tier suite gate.

Platform: like every other standalone runner here (l1 record, crash
resume), this pins CPU and 8 virtual devices *before* backend init so a
shell invocation matches the test environment exactly; under pytest the
conftest has already done both and the calls are no-ops.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

__all__ = ["main"]


def _ensure_platform() -> None:
    from apex_tpu.utils.platform import force_host_device_count, pin_cpu

    force_host_device_count(8)
    pin_cpu()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis", description=__doc__)
    ap.add_argument("--all-entries", action="store_true",
                    help="lint every registered entry config")
    ap.add_argument("--entries", default="",
                    help="comma-separated entry names (see --list-entries)")
    ap.add_argument("--list-entries", action="store_true")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="emit one structured JSON object (findings, "
                         "per-entry counts, verdict) on stdout")
    args = ap.parse_args(argv)

    from apex_tpu.analysis.registry import RULEBOOK

    if args.list_rules:
        for r in sorted(RULEBOOK.values(), key=lambda r: r.id):
            print(f"{r.id} [{r.tier:5s}] {r.title}: {r.catches}")
        return 0

    # entry builders import jax lazily; platform must be pinned first
    _ensure_platform()
    from apex_tpu.analysis.control_plane import run_control_plane
    from apex_tpu.analysis.entries import ENTRIES, run_entry
    from apex_tpu.analysis.findings import Report
    from apex_tpu.analysis.stability import run_stability

    # the graph entries plus the two whole-tier pseudo-entries: the
    # control tier (AST lint over the serving sources) and the
    # stability tier (churn-sweep traces of the serving programs)
    runners = dict.fromkeys(ENTRIES, run_entry)
    runners["control_plane"] = lambda _name: run_control_plane()
    runners["stability"] = lambda _name: run_stability()

    if args.list_entries:
        for name in runners:
            print(name)
        return 0

    if args.all_entries:
        names = list(runners)
    elif args.entries:
        names = [n.strip() for n in args.entries.split(",") if n.strip()]
        unknown = [n for n in names if n not in runners]
        if unknown:
            print(f"unknown entries: {unknown} "
                  f"(known: {list(runners)})", file=sys.stderr)
            return 2
    else:
        ap.print_help()
        return 2

    report = Report()
    n_programs = 0
    per_entry = []
    for name in names:
        sub, n = runners[name](name)
        n_programs += n
        report.extend(sub)
        e, w, _ = sub.counts()
        per_entry.append({"name": name, "programs": n,
                          "errors": e, "warnings": w})
        if not args.json:
            status = "FAIL" if sub.errors() else "ok"
            print(f"[{status}] {name}: {n} program(s), "
                  f"{e} error(s), {w} warning(s)")

    if args.json:
        e, w, i = report.counts()
        print(json.dumps({
            "verdict": "FAIL" if e else "PASS",
            "rules": len(RULEBOOK),
            "counts": {"errors": e, "warnings": w, "info": i},
            "entries": per_entry,
            "findings": [vars(f) for f in report],
        }, indent=1))
    elif report.findings:
        print(report.format())
    e, w, _ = report.counts()
    verdict = "FAIL" if e else "PASS"
    # under --json, stdout is reserved for the machine-readable array
    print(f"apex_tpu.analysis: {len(names)} entries / {n_programs} "
          f"programs / {len(RULEBOOK)} rules -> "
          f"{e} error(s), {w} warning(s) [{verdict}]",
          file=sys.stderr if args.json else sys.stdout)
    return 1 if e else 0


if __name__ == "__main__":
    sys.exit(main())
