"""The rulebook registry — one place that knows every check.

Rules register themselves with an id, tier, one-line "what it catches",
and the postmortem that motivated them (docs/analysis.md renders this
table; the CLI's ``--list-rules`` prints it).  A rule is a function from
an analysis context to an iterable of findings:

- jaxpr-tier rules receive a :class:`~apex_tpu.analysis.jaxpr_tier.JaxprCtx`
  (closed jaxpr + the program's declared intent);
- HLO-tier rules receive an :class:`~apex_tpu.analysis.hlo_rules.HloCtx`
  (parsed :class:`~apex_tpu.analysis.hlo.HloModule` + expectations);
- control-tier rules receive a
  :class:`~apex_tpu.analysis.control_plane.ControlCtx` (parsed ASTs of the
  serving/observability sources + the docs catalog text);
- stability-tier rules receive a
  :class:`~apex_tpu.analysis.stability.StabilityCtx` (the traced jaxprs of
  one serving program at every churn configuration).

Rules must be *total*: they skip silently (no findings) when their
precondition is absent — e.g. the conditional-survival rule only applies
to programs that declare ``expect_conditional`` — so the full rulebook
can always run over every program.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List

__all__ = ["Rule", "RULEBOOK", "register", "rules_for"]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    tier: str          # "jaxpr" | "hlo" | "control" | "stability"
    title: str         # short name (kebab-case)
    catches: str       # one line: what bug class this detects
    motivation: str    # which PR's postmortem mechanized into this rule
    fn: Callable       # ctx -> Iterable[Finding]


RULEBOOK: Dict[str, Rule] = {}


def register(rule_id: str, *, tier: str, title: str, catches: str,
             motivation: str):
    """Decorator: add a rule function to the rulebook."""
    if tier not in ("jaxpr", "hlo", "control", "stability"):
        raise ValueError(f"unknown tier {tier!r}")

    def deco(fn):
        if rule_id in RULEBOOK:
            raise ValueError(f"duplicate rule id {rule_id}")
        RULEBOOK[rule_id] = Rule(id=rule_id, tier=tier, title=title,
                                 catches=catches, motivation=motivation,
                                 fn=fn)
        return fn

    return deco


def rules_for(tier: str) -> List[Rule]:
    return [r for r in RULEBOOK.values() if r.tier == tier]
