"""Static analysis of staged programs — the mesh-correctness linter.

Three PRs in, the repo's hardest-won invariants lived only as prose and
ad-hoc asserts: the old-jax rank-0/shard_map tracing footgun (the PR 2
``_SpecError`` hunt) was a ROADMAP paragraph, the ring-decomposition
survival proof was a per-test opcode count, and the sentinel's
"a skipped step moves no collective bytes" contract was one hand-rolled
HLO string assert.  veScale (PAPERS.md) argues SPMD tensor programs need
*consistency checking as a first-class pass*; this package is that layer
for apex_tpu — every prose rule mechanized as a registered, documented
check emitting structured findings.

Two inspection tiers (``docs/analysis.md`` has the full rulebook):

- **jaxpr tier** (:mod:`~apex_tpu.analysis.jaxpr_tier`): trace a function
  *without executing it* and walk the closed jaxpr — rank-0 differentiated
  values crossing ``shard_map``/``shard_over`` boundaries (APX101),
  collectives under a ``lax.cond`` whose predicate is not axis-agreed
  (APX102), collectives over axis names absent from the enclosing mesh
  (APX103), malformed ``ppermute`` permutations (APX104).
- **HLO tier** (:mod:`~apex_tpu.analysis.hlo`): rule-based checks on
  *optimized* HLO — ring integrity for ``overlap_comm`` (APX201),
  ``collective-permute`` pair well-formedness (APX202), ``conditional``
  survival for the sentinel-guarded apply (APX203), and the
  donation/aliasing audit (APX204).
- **control tier** (:mod:`~apex_tpu.analysis.control_plane`): AST lint
  over the jax-free serving control plane — wire-protocol completeness
  across both transports (APX301), timeline event-schema closure
  (APX302), metric-catalog drift against the docs tables (APX303), and
  cross-thread lock discipline (APX304).
- **stability tier** (:mod:`~apex_tpu.analysis.stability`): the APX305
  jit-stability lint — each registered serving program traced at N
  churn configurations must produce one identical jaxpr structure hash
  ("churn is data, not shape" as a gated invariant).

Entry points:

- :func:`lint_traced` / :func:`lint_hlo` — lint one function / one
  compiled-HLO text; both return a :class:`~apex_tpu.analysis.findings.Report`.
- ``python -m apex_tpu.analysis --all-entries`` — run the whole rulebook
  over the registered entry configs (3D GPT trainer, ZeRO train steps,
  dryrun MoE config, overlap rings) on the CPU mesh
  (``scripts/graph_lint.sh``; ``tests/test_analysis.py`` gates the suite).
- the ``graph_lint`` pytest fixture
  (:mod:`~apex_tpu.analysis.fixtures`) — lint any model a test already
  traces.

:mod:`apex_tpu.testing.hlo` remains as a back-compat re-export of the
HLO helpers that were hoisted into :mod:`apex_tpu.analysis.hlo`.
"""

from apex_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    Finding,
    INFO,
    Report,
    WARNING,
)
from apex_tpu.analysis.registry import RULEBOOK, Rule, rules_for  # noqa: F401
from apex_tpu.analysis.program import Program  # noqa: F401
from apex_tpu.analysis.hlo import (  # noqa: F401
    compiled_hlo,
    count_hlo_ops,
    hlo_op_counts,
    parse_hlo,
)
from apex_tpu.analysis.runner import (  # noqa: F401
    analyze_program,
    lint_hlo,
    lint_traced,
)
from apex_tpu.analysis.control_plane import (  # noqa: F401
    ControlCtx,
    run_control_plane,
)
from apex_tpu.analysis.stability import (  # noqa: F401
    StabilityCtx,
    run_stability,
    structure_hash,
)

__all__ = [
    "ERROR",
    "WARNING",
    "INFO",
    "Finding",
    "Report",
    "Rule",
    "RULEBOOK",
    "rules_for",
    "Program",
    "compiled_hlo",
    "hlo_op_counts",
    "count_hlo_ops",
    "parse_hlo",
    "analyze_program",
    "lint_traced",
    "lint_hlo",
    "ControlCtx",
    "run_control_plane",
    "StabilityCtx",
    "run_stability",
    "structure_hash",
]
