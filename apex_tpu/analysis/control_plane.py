"""APX3xx — the control-plane tier: AST lint over the serving fleet.

The jaxpr/HLO tiers guard the *graph*; every recent production-class bug
lived in the jax-free half of the system instead — the PR 15 wire drift
(one transport's submit tuple grew a 6th element, the other's did not),
the PR 16 false-DOWN, the PR 18 ``_producer`` teardown race.  These
rules mechanize those postmortems the same way APX1xx/2xx mechanized the
shard_map ones: parse the serving/observability sources (and the docs
catalog tables) and check the cross-file contracts no unit test owns.

- **APX301** wire-protocol completeness: every command tuple a client
  transport sends has exactly one ``_replica_worker`` handler, and BOTH
  transports (socket and in-proc) carry the same command set at the
  same tuple arity.
- **APX302** event-schema closure: every timeline event kind emitted
  anywhere is consumed by the trace/goodput mergers or explicitly
  listed in ``trace.TRACE_UNATTRIBUTED_KINDS`` (and that allowlist
  cannot go stale); the autopilot's decision events form exactly the
  observe/decide/act/verdict set, stamped with a ``decision_id``.
- **APX303** metric-catalog drift: every ``serving/*`` / ``fleet/*``
  metric name flushed by the engine/router/autopilot appears in the
  docs catalog tables, and every catalog row names a metric the code
  actually emits — both directions, so the docs cannot rot.
- **APX304** lock/teardown discipline: an attribute mutated from more
  than one thread domain (a ``threading.Thread`` target's call graph
  vs. everything else) must be written under the object's lock or be
  single-assignment.

All rules are *total*: a rule skips silently when the sources it needs
are absent from the :class:`ControlCtx`, so red-fixture tests can feed
one rule an injected violation without tripping its neighbours.
``run_control_plane()`` (the ``control_plane`` pseudo-entry of
``python -m apex_tpu.analysis``) runs the tier over the real tree.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from apex_tpu.analysis.findings import ERROR, Finding, Report
from apex_tpu.analysis.registry import register, rules_for

__all__ = ["ControlCtx", "run_control_plane"]

_PKG = Path(__file__).resolve().parents[1]       # apex_tpu/
_ROOT = _PKG.parent                              # repo root (docs/ lives here)

# The logical file set each rule keys on.  ControlCtx.sources maps these
# names to source text; a missing name makes the rules that need it skip.
_WIRE_CLIENT_SOCKET = "serving/transport.py"
_WIRE_CLIENT_INPROC = "serving/replica.py"
_EVENT_EMITTERS = (
    "serving/engine.py", "serving/fleet.py", "serving/autopilot.py",
    "serving/scheduler.py", "serving/replica.py", "data/prefetch.py",
    "resilience/manager.py", "observability/timeline.py",
    "observability/slo.py",
)
_EVENT_CONSUMERS = ("observability/trace.py", "observability/goodput.py")
_METRIC_EMITTERS = (
    "serving/engine.py", "serving/fleet.py", "serving/autopilot.py",
)
_THREAD_FILES = (
    "serving/transport.py", "data/_producer.py", "data/prefetch.py",
)
_METRIC_DOCS = ("docs/serving.md", "docs/observability.md")

_SOURCE_FILES = sorted({
    _WIRE_CLIENT_SOCKET, _WIRE_CLIENT_INPROC,
    *_EVENT_EMITTERS, *_EVENT_CONSUMERS, *_METRIC_EMITTERS, *_THREAD_FILES,
})


@dataclasses.dataclass
class ControlCtx:
    """Parsed inputs for the control tier: python sources keyed by their
    ``apex_tpu``-relative path and markdown docs keyed repo-relative.
    Tests inject violation fixtures by building one with only the files
    a single rule reads."""

    sources: Dict[str, str]
    docs: Dict[str, str]

    def __post_init__(self):
        self._trees: Dict[str, ast.Module] = {}

    @classmethod
    def default(cls) -> "ControlCtx":
        sources = {}
        for rel in _SOURCE_FILES:
            p = _PKG / rel
            if p.exists():
                sources[rel] = p.read_text()
        docs = {}
        for rel in _METRIC_DOCS:
            p = _ROOT / rel
            if p.exists():
                docs[rel] = p.read_text()
        return cls(sources=sources, docs=docs)

    def tree(self, name: str) -> Optional[ast.Module]:
        if name not in self.sources:
            return None
        if name not in self._trees:
            self._trees[name] = ast.parse(self.sources[name], filename=name)
        return self._trees[name]


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------

def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fstring_pattern(node: ast.AST) -> Optional[str]:
    """A str constant or f-string as a segment pattern: every
    ``{interpolation}`` becomes a ``*`` wildcard segment piece."""
    s = _const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.JoinedStr):
        out = []
        for part in node.values:
            if isinstance(part, ast.Constant):
                out.append(str(part.value))
            else:
                out.append("*")
        return "".join(out)
    return None


def _class_defs(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    return {n.name: n for n in tree.body if isinstance(n, ast.ClassDef)}


def _non_docstrings(tree: ast.AST) -> Iterable[ast.Constant]:
    """Every string constant that is not a docstring/bare-expression."""
    doc_pos = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Constant):
            doc_pos.add(id(node.value))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and id(node) not in doc_pos):
            yield node


# --------------------------------------------------------------------------
# APX301 — wire-protocol completeness
# --------------------------------------------------------------------------

def _sent_socket(cls: ast.ClassDef) -> Dict[str, Set[int]]:
    """Commands the socket client sends: ``self._send_cmd((name, ...))``
    plus raw ``("cmd", seq, (name, ...))`` frame literals (the stop
    path, which bypasses ``_send_cmd`` to pin its own sequence)."""
    out: Dict[str, Set[int]] = {}
    for node in ast.walk(cls):
        if (isinstance(node, ast.Call)
                and _is_self_attr(node.func, "_send_cmd")
                and node.args and isinstance(node.args[0], ast.Tuple)):
            tup = node.args[0]
            name = _const_str(tup.elts[0]) if tup.elts else None
            if name is not None:
                out.setdefault(name, set()).add(len(tup.elts))
        if isinstance(node, ast.Tuple) and len(node.elts) == 3 \
                and _const_str(node.elts[0]) == "cmd" \
                and isinstance(node.elts[2], ast.Tuple):
            tup = node.elts[2]
            name = _const_str(tup.elts[0]) if tup.elts else None
            if name is not None:
                out.setdefault(name, set()).add(len(tup.elts))
    return out


def _sent_inproc(cls: ast.ClassDef) -> Dict[str, Set[int]]:
    """Commands the in-proc client sends: ``self._cmd.put[_nowait](
    (name, ...))``."""
    out: Dict[str, Set[int]] = {}
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("put", "put_nowait")
                and _is_self_attr(node.func.value, "_cmd")):
            continue
        if node.args and isinstance(node.args[0], ast.Tuple):
            tup = node.args[0]
            name = _const_str(tup.elts[0]) if tup.elts else None
            if name is not None:
                out.setdefault(name, set()).add(len(tup.elts))
    return out


def _worker_handlers(fn: ast.FunctionDef) -> Dict[str, int]:
    """``cmd[0] == "name"`` dispatch arms in the worker, with counts."""
    out: Dict[str, int] = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], ast.Eq)):
            continue
        left = node.left
        if not (isinstance(left, ast.Subscript)
                and isinstance(left.value, ast.Name)):
            continue
        idx = left.slice
        if not (isinstance(idx, ast.Constant) and idx.value == 0):
            continue
        name = _const_str(node.comparators[0])
        if name is not None:
            out[name] = out.get(name, 0) + 1
    return out


@register("APX301", tier="control", title="wire-protocol-completeness",
          catches="a transport command with no worker handler, a dead "
                  "handler, or the two transports drifting in command "
                  "set / tuple arity",
          motivation="PR 15: the socket submit tuple grew a 6th element "
                     "the in-proc transport (and a stale worker) never "
                     "learned about — caught in integration, not lint")
def _apx301(ctx: ControlCtx):
    t_tree = ctx.tree(_WIRE_CLIENT_SOCKET)
    r_tree = ctx.tree(_WIRE_CLIENT_INPROC)
    if t_tree is None or r_tree is None:
        return
    sock_cls = _class_defs(t_tree).get("SocketTransport")
    proc_cls = _class_defs(r_tree).get("ReplicaProcess")
    worker = next((n for n in ast.walk(r_tree)
                   if isinstance(n, ast.FunctionDef)
                   and n.name == "_replica_worker"), None)
    if sock_cls is None or proc_cls is None or worker is None:
        return

    sock = _sent_socket(sock_cls)
    proc = _sent_inproc(proc_cls)
    handlers = _worker_handlers(worker)
    loc_w = f"{_WIRE_CLIENT_INPROC}:_replica_worker"

    for name, count in sorted(handlers.items()):
        if count > 1:
            yield Finding(
                rule="APX301", severity=ERROR, location=loc_w,
                message=f"command {name!r} has {count} dispatch arms — "
                        "exactly one handler per command",
                remediation="collapse the duplicate arm; the first match "
                            "shadows the rest silently")
    sent = set(sock) | set(proc)
    for name in sorted(sent - set(handlers)):
        senders = [k for k, d in (("socket", sock), ("in-proc", proc))
                   if name in d]
        yield Finding(
            rule="APX301", severity=ERROR, location=loc_w,
            message=f"command {name!r} is sent by the {'/'.join(senders)} "
                    "transport but has no _replica_worker handler",
            remediation="add the dispatch arm (or delete the dead send); "
                        "an unhandled command is dropped on the floor at "
                        "the replica")
    for name in sorted(set(handlers) - sent):
        yield Finding(
            rule="APX301", severity=ERROR, location=loc_w,
            message=f"handler for {name!r} is dead: no transport sends it",
            remediation="delete the arm or wire the missing client send — "
                        "a one-sided protocol change is exactly the PR 15 "
                        "drift")
    for name in sorted(set(sock) & set(proc)):
        if sock[name] != proc[name]:
            yield Finding(
                rule="APX301", severity=ERROR,
                location=f"{_WIRE_CLIENT_SOCKET}:SocketTransport",
                message=f"command {name!r} arity drift: socket sends "
                        f"{sorted(sock[name])} elements, in-proc sends "
                        f"{sorted(proc[name])}",
                remediation="grow BOTH client tuples (and the worker "
                            "unpack) in the same change")
    for name in sorted(set(sock) ^ set(proc)):
        have = "socket" if name in sock else "in-proc"
        lack = "in-proc" if name in sock else "socket"
        yield Finding(
            rule="APX301", severity=ERROR,
            location=f"{_WIRE_CLIENT_SOCKET}:SocketTransport",
            message=f"command {name!r} exists on the {have} transport "
                    f"only — the {lack} transport cannot express it",
            remediation="both transports must carry the same command set "
                        "so a fleet can swap transports without losing "
                        "protocol surface")


# --------------------------------------------------------------------------
# APX302 — event-schema closure
# --------------------------------------------------------------------------

_EMIT_ATTRS = ("emit", "scope", "_emit")
_DECISION_KINDS = frozenset({
    "autopilot_observe", "autopilot_decide",
    "autopilot_act", "autopilot_verdict",
})


def _emitted_kinds(ctx: ControlCtx) -> Dict[str, str]:
    """kind -> "file:line" of one emission site, over every emitter."""
    out: Dict[str, str] = {}
    for fname in _EVENT_EMITTERS:
        tree = ctx.tree(fname)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMIT_ATTRS and node.args):
                continue
            kind = _const_str(node.args[0])
            if kind is not None:
                out.setdefault(kind, f"{fname}:{node.lineno}")
    return out


def _consumed_strings(ctx: ControlCtx) -> Tuple[Set[str], Set[str]]:
    """(string constants, startswith prefixes) over the consumers."""
    consts: Set[str] = set()
    prefixes: Set[str] = set()
    for fname in _EVENT_CONSUMERS:
        tree = ctx.tree(fname)
        if tree is None:
            continue
        for node in _non_docstrings(tree):
            consts.add(node.value)
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "startswith" and node.args):
                p = _const_str(node.args[0])
                if p is not None:
                    prefixes.add(p)
    return consts, prefixes


def _unattributed_allowlist(ctx: ControlCtx) -> Optional[Dict[str, str]]:
    tree = ctx.tree("observability/trace.py")
    if tree is None:
        return None
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "TRACE_UNATTRIBUTED_KINDS"
                and isinstance(node.value, ast.Dict)):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                ks = _const_str(k)
                if ks is not None:
                    out[ks] = _const_str(v) or ""
            return out
    return {}


@register("APX302", tier="control", title="event-schema-closure",
          catches="a timeline event kind no consumer attributes (or a "
                  "stale unattributed allowlist entry); an autopilot "
                  "decision record missing its observe/decide/act/"
                  "verdict closure or its decision_id stamp",
          motivation="PR 15/18: trace merge and autopilot verdicts only "
                     "work if every emitted kind lands in a consumer "
                     "bucket — a typo'd kind silently vanishes from "
                     "every report")
def _apx302(ctx: ControlCtx):
    emitted = _emitted_kinds(ctx)
    if not emitted or not any(ctx.tree(f) is not None
                              for f in _EVENT_CONSUMERS):
        return
    consts, prefixes = _consumed_strings(ctx)
    allow = _unattributed_allowlist(ctx) or {}

    for kind, loc in sorted(emitted.items()):
        if kind in consts or kind in allow:
            continue
        if any(kind.startswith(p) for p in prefixes):
            continue
        yield Finding(
            rule="APX302", severity=ERROR, location=loc,
            message=f"timeline kind {kind!r} is emitted but no consumer "
                    "(trace merge / goodput attribution) references it",
            remediation="attribute it in trace.py/goodput.py, or list it "
                        "in trace.TRACE_UNATTRIBUTED_KINDS with the "
                        "reason it is a marker, not an interval")
    for kind in sorted(allow):
        if kind not in emitted:
            yield Finding(
                rule="APX302", severity=ERROR,
                location="observability/trace.py:TRACE_UNATTRIBUTED_KINDS",
                message=f"allowlist entry {kind!r} names a kind nothing "
                        "emits — the allowlist has gone stale",
                remediation="delete the entry (or restore the emission it "
                            "documented)")

    ap_tree = ctx.tree("serving/autopilot.py")
    if ap_tree is not None:
        ap_kinds = {k for k in emitted if k.startswith("autopilot_")}
        missing = _DECISION_KINDS - ap_kinds
        extra = ap_kinds - _DECISION_KINDS
        if missing:
            yield Finding(
                rule="APX302", severity=ERROR,
                location="serving/autopilot.py",
                message="decision schema is not closed: "
                        f"{sorted(missing)} never emitted — every "
                        "decision must reach observe/decide/act/verdict",
                remediation="emit the missing leg(s) with the shared "
                            "decision_id")
        for k in sorted(extra):
            yield Finding(
                rule="APX302", severity=ERROR,
                location=emitted[k],
                message=f"unknown decision kind {k!r} outside the "
                        "observe/decide/act/verdict schema",
                remediation="fold it into the 4-event schema (the docs "
                            "table and collect_decisions key on it)")
        emit_fn = next((n for n in ast.walk(ap_tree)
                        if isinstance(n, ast.FunctionDef)
                        and n.name == "_emit"), None)
        if emit_fn is not None:
            argnames = [a.arg for a in emit_fn.args.args]
            if "decision_id" not in argnames:
                yield Finding(
                    rule="APX302", severity=ERROR,
                    location=f"serving/autopilot.py:{emit_fn.lineno}",
                    message="_emit does not take a decision_id — decision "
                            "events can no longer be stitched into one "
                            "record",
                    remediation="every decision event carries the shared "
                                "decision_id (docs/observability.md "
                                "schema table)")


# --------------------------------------------------------------------------
# APX303 — metric-catalog drift
# --------------------------------------------------------------------------

_METRIC_FACTORIES = ("counter", "gauge", "histogram")
_METRIC_PREFIXES = ("serving/", "fleet/")
_CODE_SPAN = re.compile(r"`([^`]+)`")


def _wrapper_templates(tree: ast.Module) -> Dict[str, Tuple[str, str]]:
    """Functions that forward a parameter into a metric-factory name
    (``def _count(self, name): ...counter(f"fleet/autopilot/{name}")``,
    ``def _slo_hist(self, name): ...histogram(name, ...)``) mapped to
    their (prefix, suffix) template around the forwarded parameter."""
    out: Dict[str, Tuple[str, str]] = {}
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        params = {a.arg for a in fn.args.args}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            callee = node.func
            name = callee.attr if isinstance(callee, ast.Attribute) else \
                callee.id if isinstance(callee, ast.Name) else None
            if name not in _METRIC_FACTORIES:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in params:
                out[fn.name] = ("", "")
            elif isinstance(arg, ast.JoinedStr):
                interp = [p for p in arg.values
                          if isinstance(p, ast.FormattedValue)]
                if (len(interp) == 1 and isinstance(interp[0].value, ast.Name)
                        and interp[0].value.id in params):
                    pre, post, seen = [], [], False
                    for p in arg.values:
                        if isinstance(p, ast.FormattedValue):
                            seen = True
                        elif not seen:
                            pre.append(str(p.value))
                        else:
                            post.append(str(p.value))
                    out[fn.name] = ("".join(pre), "".join(post))
    return out


def _emitted_metrics(ctx: ControlCtx) -> Dict[str, str]:
    """metric-name pattern (``*`` = one interpolated segment piece) ->
    one "file:line" emission site."""
    out: Dict[str, str] = {}
    for fname in _METRIC_EMITTERS:
        tree = ctx.tree(fname)
        if tree is None:
            continue
        wrappers = _wrapper_templates(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            callee = node.func
            cname = callee.attr if isinstance(callee, ast.Attribute) else \
                callee.id if isinstance(callee, ast.Name) else None
            if cname in _METRIC_FACTORIES:
                pat = _fstring_pattern(node.args[0])
            elif cname in wrappers:
                inner = _fstring_pattern(node.args[0])
                if inner is None:
                    continue
                pre, post = wrappers[cname]
                pat = f"{pre}{inner}{post}"
            else:
                continue
            if pat is not None and pat.startswith(_METRIC_PREFIXES):
                out.setdefault(pat, f"{fname}:{node.lineno}")
    return out


def _doc_metric_rows(ctx: ControlCtx) -> Dict[str, str]:
    """Catalog rows: first-cell code spans of every markdown table row,
    ``<var>`` placeholders normalized to ``*``, ``.../suffix``
    continuations resolved against the previous span in the cell.
    Two-segment pure-family rows (``fleet/*`` in the prefix-family
    table) are not catalog entries and are skipped."""
    out: Dict[str, str] = {}
    for fname, text in ctx.docs.items():
        for ln, line in enumerate(text.splitlines(), 1):
            if not line.lstrip().startswith("|"):
                continue
            cells = line.split("|")
            if len(cells) < 3 or set(cells[1].strip()) <= {"-", " ", ":"}:
                continue
            prev = None
            for span in _CODE_SPAN.findall(cells[1]):
                name = span.strip()
                if name.startswith(".../") and prev is not None:
                    name = prev.rsplit("/", 1)[0] + name[3:]
                if not name.startswith(_METRIC_PREFIXES):
                    continue
                name = re.sub(r"<[^>]+>", "*", name)
                prev = name
                if name.count("/") == 1 and name.endswith("/*"):
                    continue  # prefix-family row, not a catalog entry
                out.setdefault(name, f"{fname}:{ln}")
    return out


def _patterns_match(a: str, b: str) -> bool:
    sa, sb = a.split("/"), b.split("/")
    if len(sa) != len(sb):
        return False
    return all(x == y or x == "*" or y == "*" for x, y in zip(sa, sb))


@register("APX303", tier="control", title="metric-catalog-drift",
          catches="a serving/fleet metric flushed in code but missing "
                  "from the docs catalog tables, or a catalog row whose "
                  "metric nothing emits",
          motivation="PR 16/17 grew the fleet metric surface faster than "
                     "docs/serving.md; an uncatalogued counter is "
                     "invisible to dashboards and a stale row debugs a "
                     "metric that does not exist")
def _apx303(ctx: ControlCtx):
    emitted = _emitted_metrics(ctx)
    docs = _doc_metric_rows(ctx)
    if not emitted or not docs:
        return
    for pat, loc in sorted(emitted.items()):
        if not any(_patterns_match(pat, d) for d in docs):
            yield Finding(
                rule="APX303", severity=ERROR, location=loc,
                message=f"metric {pat!r} is emitted but has no row in "
                        "the docs catalog tables "
                        f"({', '.join(_METRIC_DOCS)})",
                remediation="add the catalog row (name / type / meaning) "
                            "in docs/serving.md")
    for pat, loc in sorted(docs.items()):
        if not any(_patterns_match(pat, e) for e in emitted):
            yield Finding(
                rule="APX303", severity=ERROR, location=loc,
                message=f"catalog row {pat!r} names a metric nothing in "
                        "the serving/fleet/autopilot code emits",
                remediation="delete the stale row (or restore the "
                            "emission it documented)")


# --------------------------------------------------------------------------
# APX304 — lock/teardown discipline
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _Write:
    attr: str
    method: str
    lineno: int
    locked: bool


def _self_calls(fn: ast.FunctionDef) -> Set[str]:
    out = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and _is_self_attr(node.func)):
            out.add(node.func.attr)
    return out


def _collect_writes(fn: ast.FunctionDef) -> List[_Write]:
    """``self.x = / += ...`` sites in one method, each tagged with
    whether an enclosing ``with self.<...lock...>:`` guards it."""
    writes: List[_Write] = []

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.With):
            has_lock = any(
                isinstance(item.context_expr, ast.Attribute)
                and _is_self_attr(item.context_expr)
                and "lock" in item.context_expr.attr.lower()
                for item in node.items)
            locked = locked or has_lock
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t]):
                if _is_self_attr(el):
                    writes.append(_Write(el.attr, fn.name,
                                         node.lineno, locked))
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    visit(fn, False)
    return writes


def _thread_targets(cls: ast.ClassDef, methods: Dict[str, ast.FunctionDef],
                    ) -> Set[str]:
    out = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        cname = callee.attr if isinstance(callee, ast.Attribute) else \
            callee.id if isinstance(callee, ast.Name) else None
        if cname != "Thread":
            continue
        for kw in node.keywords:
            if kw.arg == "target" and _is_self_attr(kw.value) \
                    and kw.value.attr in methods:
                out.add(kw.value.attr)
    return out


def _reach(entries: Set[str], graph: Dict[str, Set[str]]) -> Set[str]:
    seen, stack = set(), list(entries)
    while stack:
        m = stack.pop()
        if m in seen:
            continue
        seen.add(m)
        stack.extend(graph.get(m, ()))
    return seen


@register("APX304", tier="control", title="lock-teardown-discipline",
          catches="an attribute written from more than one thread domain "
                  "(a Thread target's call graph vs. the main-thread "
                  "methods) without the object's lock",
          motivation="PR 18: the _producer teardown race — a stop flag "
                     "and queue rewind mutated from both the consumer "
                     "and a competing __iter__ outside the lock")
def _apx304(ctx: ControlCtx):
    for fname in _THREAD_FILES:
        tree = ctx.tree(fname)
        if tree is None:
            continue
        for cname, cls in _class_defs(tree).items():
            methods = {n.name: n for n in cls.body
                       if isinstance(n, ast.FunctionDef)}
            targets = _thread_targets(cls, methods)
            if not targets:
                continue
            graph = {m: _self_calls(fn) & set(methods)
                     for m, fn in methods.items()}
            thread_reach = _reach(targets, graph)
            main_entries = {m for m in methods
                            if m not in thread_reach and m != "__init__"}
            main_reach = _reach(main_entries, graph)

            by_attr: Dict[str, List[_Write]] = {}
            for m, fn in methods.items():
                if m == "__init__":
                    continue  # Thread.start() is the publication barrier
                for w in _collect_writes(fn):
                    by_attr.setdefault(w.attr, []).append(w)

            for attr, writes in sorted(by_attr.items()):
                domains = set()
                for w in writes:
                    if w.method in thread_reach:
                        domains.add("thread")
                    if w.method in main_reach:
                        domains.add("main")
                if len(domains) < 2 or len(writes) == 1:
                    continue  # single-domain or single-assignment
                for w in writes:
                    if not w.locked:
                        yield Finding(
                            rule="APX304", severity=ERROR,
                            location=f"{fname}:{w.lineno} "
                                     f"({cname}.{w.method})",
                            message=f"self.{attr} is written from both "
                                    "the worker-thread and main-thread "
                                    "call graphs, and this write is not "
                                    "under the object's lock",
                            remediation="guard every cross-domain write "
                                        "with the lock (or make the "
                                        "field single-assignment)")


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------

def run_control_plane(ctx: Optional[ControlCtx] = None,
                      ) -> Tuple[Report, int]:
    """Run every control-tier rule over ``ctx`` (default: the live
    tree).  Returns ``(report, files_scanned)`` — the pseudo-entry
    contract ``cli.py`` shares with :func:`entries.run_entry`."""
    ctx = ctx if ctx is not None else ControlCtx.default()
    report = Report()
    for rule in rules_for("control"):
        report.extend(rule.fn(ctx))
    return report, len(ctx.sources) + len(ctx.docs)
