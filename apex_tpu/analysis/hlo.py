"""Compiled-HLO inspection: prove an optimization survived jit.

Hoisted from ``apex_tpu/testing/hlo.py`` (which re-exports these names
for back-compat) and grown from a line-regex opcode counter into a
structured parse, because the HLO-tier rules need *attribution*, not just
totals:

- the collective-matmul rings
  (:mod:`apex_tpu.transformer.tensor_parallel.overlap`) are only worth
  their code if the compiled program still contains the decomposed
  ``collective-permute`` chain — XLA is free to pattern-match a ring back
  into one monolithic ``all-gather`` (rule APX201 counts opcodes exactly
  as the PR 2 tests did);
- the sentinel contract ("a skipped step moves no collective bytes")
  is about which *computation* an op lives in: a collective inside a
  ``conditional`` branch body is conditional traffic, one at entry level
  is not — so instructions are parsed per-computation
  (:func:`parse_hlo`), and ops inside ``fusion``/``to_apply``/branch
  computation bodies are attributed to *their* computation instead of
  being folded into one flat count (the old regex counted every
  ``word(`` after an ``=`` anywhere in the text, including comment
  lines; ``tests/test_analysis.py`` pins the fixed behavior).

The ``lower().compile().as_text()`` pipeline is stable across the jax
versions the shims support (0.4.x–0.7.x), so assertions written against
these helpers hold on every container.

Async collective pairs (``all-gather-start``/``-done``,
``collective-permute-start``/``-done``) count as ONE op under their base
opcode: the start/done split is a backend scheduling detail, not an extra
collective on the wire.
"""

from __future__ import annotations

import collections
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

__all__ = [
    "compiled_hlo",
    "hlo_op_counts",
    "count_hlo_ops",
    "parse_hlo",
    "HloInstruction",
    "HloComputation",
    "HloModule",
]


def compiled_hlo(fn, *args, **kwargs) -> str:
    """Optimized HLO text of ``jit(fn)`` at these arguments.

    ``fn`` is compiled exactly as it would execute (same shapes, same
    shardings if the arguments carry them) but never run.  An
    already-jitted ``fn`` is lowered directly — this preserves its
    ``donate_argnums``, which wrapping in a fresh ``jax.jit`` would
    silently drop (the donation-audit rule APX204 depends on this).
    """
    import jax

    lower = fn.lower if hasattr(fn, "lower") else jax.jit(fn).lower
    return lower(*args, **kwargs).compile().as_text()


# --- structured parse ----------------------------------------------------

# `%name = shape opcode(operands...), attrs` — opcode extraction must skip
# the shape first: tuple shapes `(f32[4]{0}, u32[])` are parenthesized and
# layouts may nest tile annotations, so "first word-paren after the =" is
# only safe once the shape token has been consumed.
_INSTR = re.compile(r"^\s*(?P<root>ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
                    r"(?P<rest>.*)$")
_OPCODE = re.compile(r"\s*(?P<op>[a-zA-Z][\w\-]*)\(")
# `%comp_name (params...) -> shape {` / `ENTRY %main (...) -> ... {`
_COMP = re.compile(r"^\s*(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*"
                   r"(\([^=]*\))?\s*(->\s*[^{]+)?\{\s*$")
_BLOCK_COMMENT = re.compile(r"/\*.*?\*/")


@dataclasses.dataclass(frozen=True)
class HloInstruction:
    name: str
    opcode: str          # raw opcode, async halves NOT folded here
    shape: str
    computation: str     # "" for instructions outside any computation block
    line_no: int         # 0-based line in the parsed text
    is_root: bool
    raw: str             # the full instruction line (comments stripped)

    @property
    def base_opcode(self) -> Optional[str]:
        """Opcode with the async ``-start`` half folded to its base and the
        ``-done`` half dropped (``None``): the pair is one collective."""
        if self.opcode.endswith("-done"):
            return None
        if self.opcode.endswith("-start"):
            return self.opcode[: -len("-start")]
        return self.opcode

    def source_target_pairs(self) -> Optional[List[Tuple[int, int]]]:
        """Parsed ``source_target_pairs`` of a collective-permute."""
        m = re.search(r"source_target_pairs=\{(.*?)\}\}", self.raw)
        if m is None:
            return None
        return [(int(a), int(b)) for a, b in
                re.findall(r"\{\s*(\d+)\s*,\s*(\d+)\s*\}", m.group(1) + "}")]


@dataclasses.dataclass
class HloComputation:
    name: str
    is_entry: bool
    instructions: List[HloInstruction]


class HloModule:
    """Parsed HLO text: header attributes + per-computation instructions."""

    def __init__(self, text: str):
        self.text = text
        self.header = ""
        self.computations: Dict[str, HloComputation] = {}
        self._parse()

    # -- queries ----------------------------------------------------------

    @property
    def entry(self) -> Optional[HloComputation]:
        for c in self.computations.values():
            if c.is_entry:
                return c
        return None

    def instructions(self, computation: Optional[str] = None
                     ) -> Iterator[HloInstruction]:
        """All instructions, or those of one computation (``"entry"`` maps
        to the ENTRY computation)."""
        if computation is None:
            for c in self.computations.values():
                yield from c.instructions
            return
        if computation == "entry" and computation not in self.computations:
            c = self.entry
            yield from (c.instructions if c else ())
            return
        c = self.computations.get(computation)
        yield from (c.instructions if c else ())

    def aliased_parameters(self) -> Set[int]:
        """Parameter indices appearing in the module's
        ``input_output_alias`` header attribute (donated inputs XLA
        actually writes outputs into)."""
        m = re.search(r"input_output_alias=\{(.*?)\}\s*,\s*\w+=",
                      self.header)
        if m is None:
            m = re.search(r"input_output_alias=\{(.*?)\}\s*$", self.header)
        if m is None:
            return set()
        return {int(p) for p in re.findall(r"\(\s*(\d+)\s*,", m.group(1))}

    # -- parsing ----------------------------------------------------------

    def _parse(self) -> None:
        current: Optional[str] = None
        for line_no, raw_line in enumerate(self.text.splitlines()):
            line = _BLOCK_COMMENT.sub("", raw_line)
            stripped = line.strip()
            if not stripped or stripped.startswith("//"):
                continue
            if stripped.startswith("HloModule"):
                self.header = stripped
                continue
            if stripped == "}":
                current = None
                continue
            m = _COMP.match(line)
            if m and " = " not in line:
                current = m.group("name")
                self.computations[current] = HloComputation(
                    name=current, is_entry=bool(m.group("entry")),
                    instructions=[])
                continue
            m = _INSTR.match(line)
            if m is None:
                continue
            rest = m.group("rest")
            shape, remainder = _split_shape(rest)
            op = _OPCODE.match(remainder)
            if op is None:
                continue
            comp = current if current is not None else ""
            if comp not in self.computations:
                # bare fragments (tests, snippets) parse as one unnamed
                # computation treated as the entry
                self.computations[comp] = HloComputation(
                    name=comp, is_entry=True, instructions=[])
            self.computations[comp].instructions.append(HloInstruction(
                name=m.group("name"), opcode=op.group("op"), shape=shape,
                computation=comp, line_no=line_no,
                is_root=bool(m.group("root")), raw=stripped))


def _split_shape(rest: str) -> Tuple[str, str]:
    """Split ``"shape opcode(...)"`` into (shape, remainder).  Tuple
    shapes are parenthesized and may nest; scalar/array shapes are one
    whitespace-delimited token."""
    rest = rest.lstrip()
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return rest[: i + 1], rest[i + 1:]
        return rest, ""
    i = rest.find(" ")
    if i < 0:
        return rest, ""
    return rest[:i], rest[i:]


def parse_hlo(hlo_text: str) -> HloModule:
    """Parse HLO text (a full module or a bare instruction fragment) into
    computations of :class:`HloInstruction`."""
    return HloModule(hlo_text)


def hlo_op_counts(hlo_text, computation: Optional[str] = None
                  ) -> "collections.Counter[str]":
    """Opcode -> occurrence count, async ``-start``/``-done`` halves folded
    into their base opcode (the pair is one collective; counting both
    would double it).

    ``computation=None`` counts over every computation in the module —
    note ops inside ``fusion``/``to_apply``/branch bodies count toward
    *their* computation's instructions, so e.g. the ``add`` inside an
    ``all-reduce`` combiner still appears in the total; pass
    ``computation="entry"`` (or a computation name) to scope the count.
    Comment and metadata text never counts (``tests/test_analysis.py``
    pins this).
    """
    module = hlo_text if isinstance(hlo_text, HloModule) \
        else parse_hlo(hlo_text)
    counts: collections.Counter = collections.Counter()
    for inst in module.instructions(computation):
        base = inst.base_opcode
        if base is not None:
            counts[base] += 1
    return counts


def count_hlo_ops(hlo_text, opcode: str,
                  computation: Optional[str] = None) -> int:
    """Occurrences of ``opcode`` (e.g. ``"collective-permute"``,
    ``"all-gather"``) in compiled HLO, async pairs counted once."""
    return hlo_op_counts(hlo_text, computation)[opcode]
