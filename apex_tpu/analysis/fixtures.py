"""Pytest integration: lint any model a test already traces.

``tests/conftest.py`` imports :func:`graph_lint`, so every test can ask
for the fixture and run the shared rulebook over a function or a
compiled-HLO text it already has in hand — the same rule
implementations the CLI runs, never a re-derived assert::

    def test_my_step_keeps_its_guard(graph_lint):
        hlo = compiled_hlo(step, params, state, batch, sent)
        graph_lint(hlo=hlo, expect_conditional=True)

    def test_my_loss_is_old_jax_safe(graph_lint):
        graph_lint(loss_fn, params, tokens, differentiated=True)

The fixture raises ``AssertionError`` with the formatted findings when
any ERROR fires, and returns the full Report otherwise (so tests can
additionally assert on warnings or specific rules).
"""

from __future__ import annotations

import pytest

__all__ = ["graph_lint"]


@pytest.fixture
def graph_lint():
    from apex_tpu.analysis import lint_hlo, lint_traced

    def _lint(fn=None, *args, hlo=None, name=None, differentiated=False,
              **expect):
        if fn is not None:
            report = lint_traced(fn, *args, name=name,
                                 differentiated=differentiated,
                                 hlo=hlo is True, **expect)
            if isinstance(hlo, str):
                hlo_report = lint_hlo(hlo, name=name or "hlo", **expect)
                report.extend(hlo_report.findings)
        elif isinstance(hlo, str):
            report = lint_hlo(hlo, name=name or "hlo", **expect)
        else:
            raise TypeError("graph_lint needs a function or hlo text")
        assert report.ok, (
            "graph lint found errors:\n" + report.format())
        return report

    return _lint
