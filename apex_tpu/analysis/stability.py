"""APX305 — jit-stability lint: churn is data, not shape.

The serving engine's central contract is that request churn — slot
occupancy, adapter mix, per-slot draft counts, sampling policies, block
tables — rides through the compiled decode/prefill programs as *data*,
so the compile count stays 1 for the life of the server (the
``decode_compile_count()`` pins scattered through the suite).  The
failure mode is silent: a churn knob leaking into static/python land (a
scalar baked as a constant, a shape derived from occupancy, a dtype/
weak-type drift from a python literal) retraces cleanly and only shows
up as a recompile storm in production.

This tier gates the invariant structurally: each registered serving
program (``decode``, ``prefill``, ``speculative``, ``lora``) is traced
with :func:`jax.make_jaxpr` at N *distinct* churn configurations and the
canonical jaxpr structure hash — primitives, avals (shape/dtype/
weak-type), literal values, nested sub-jaxprs — must be identical across
all of them.  Tracing is abstract (no XLA compile), so the whole sweep
is cheap enough for the fast tier.

``run_stability()`` is the ``stability`` pseudo-entry of
``python -m apex_tpu.analysis``; tests inject a shape-varying fixture
through :func:`trace_hash` + :class:`StabilityCtx` directly.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Tuple

from apex_tpu.analysis.findings import ERROR, Finding, Report
from apex_tpu.analysis.registry import register, rules_for

__all__ = ["StabilityCtx", "structure_hash", "trace_hash",
           "check_hashes", "run_stability", "STABILITY_PROGRAMS"]


@dataclasses.dataclass
class StabilityCtx:
    """One serving program's trace sweep: ``hashes`` is the ordered
    ``(churn-config label, structure hash)`` list the rule compares."""

    program: str
    hashes: List[Tuple[str, str]]


# --------------------------------------------------------------------------
# canonical structure hash
# --------------------------------------------------------------------------

def _aval_sig(v) -> str:
    a = getattr(v, "aval", None)
    return (f"{getattr(a, 'shape', '?')}:{getattr(a, 'dtype', '?')}"
            f":{getattr(a, 'weak_type', False)}")


def _atom_sig(v) -> str:
    # a Literal carries a baked value: include it, so a python scalar
    # knob turned into a constant changes the hash even at fixed aval
    if hasattr(v, "val"):
        return f"lit[{v.val!r}]{_aval_sig(v)}"
    return _aval_sig(v)


def _param_sig(v, lines: List[str]) -> str:
    if hasattr(v, "eqns") and hasattr(v, "invars"):        # Jaxpr
        _canon(v, lines)
        return "<jaxpr>"
    if hasattr(v, "jaxpr") and hasattr(v, "consts"):       # ClosedJaxpr
        _canon(v.jaxpr, lines)
        return "<closed-jaxpr>"
    if isinstance(v, (tuple, list)):
        return "(" + ",".join(_param_sig(x, lines) for x in v) + ")"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k}:{_param_sig(v[k], lines)}" for k in sorted(v, key=str)
        ) + "}"
    if isinstance(v, (str, int, float, bool, complex, bytes, type(None))):
        return repr(v)
    # meshes, dtypes, effects, shardings: their str() is stable; bare
    # functions/objects are reduced to their type so id()s never leak in
    s = str(v)
    return s if "0x" not in s else type(v).__name__


def _canon(jaxpr, lines: List[str]) -> None:
    lines.append("in:" + ",".join(_aval_sig(v) for v in jaxpr.invars))
    lines.append("const:" + ",".join(_aval_sig(v)
                                     for v in jaxpr.constvars))
    for eqn in jaxpr.eqns:
        lines.append(
            f"eqn:{eqn.primitive.name}"
            f"({','.join(_atom_sig(v) for v in eqn.invars)})"
            f"->({','.join(_aval_sig(v) for v in eqn.outvars)})")
        for k in sorted(eqn.params):
            lines.append(f"  {k}={_param_sig(eqn.params[k], lines)}")
    lines.append("out:" + ",".join(_atom_sig(v) for v in jaxpr.outvars))


def structure_hash(jaxpr) -> str:
    """Canonical sha256 of a (Closed)Jaxpr's structure: primitive
    sequence, in/out avals (shape/dtype/weak-type), literal values, and
    every nested sub-jaxpr — but never buffer contents, so two traces
    differ exactly when the *program* differs."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    lines: List[str] = []
    _canon(jaxpr, lines)
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()


def trace_hash(fn, args) -> str:
    """Abstractly trace ``fn(*args)`` and hash the jaxpr structure."""
    import jax

    return structure_hash(jax.make_jaxpr(fn)(*args))


# --------------------------------------------------------------------------
# the rule
# --------------------------------------------------------------------------

@register("APX305", tier="stability", title="jit-stability",
          catches="a serving program whose traced structure varies with "
                  "request churn (slot/adapter/draft/sampling mix) — a "
                  "knob leaked from data into shape/static land",
          motivation="PR 16: one uncompiled decode variant crossing the "
                     "heartbeat window marked a healthy replica DOWN "
                     "fleet-wide; the zero-recompile contract was only "
                     "pinned per-suite, never as lint")
def _apx305(ctx: StabilityCtx):
    buckets: Dict[str, List[str]] = {}
    for label, h in ctx.hashes:
        buckets.setdefault(h, []).append(label)
    if len(buckets) <= 1:
        return
    detail = "; ".join(
        f"{h[:12]}… <- {', '.join(labels)}"
        for h, labels in sorted(buckets.items(), key=lambda kv: kv[1]))
    yield Finding(
        rule="APX305", severity=ERROR,
        location=f"stability:{ctx.program}",
        message=f"jaxpr structure hash differs across churn configs "
                f"({len(buckets)} variants over {len(ctx.hashes)} "
                f"configs): {detail}",
        remediation="every churn knob must ride as array data at a "
                    "fixed aval — no python-scalar bake-in, no "
                    "occupancy-derived shapes (docs/serving.md, the "
                    "zero-recompile contract)")


def check_hashes(program: str,
                 hashes: List[Tuple[str, str]]) -> Report:
    """Run the stability rulebook over one program's trace sweep."""
    report = Report()
    ctx = StabilityCtx(program=program, hashes=hashes)
    for rule in rules_for("stability"):
        report.extend(rule.fn(ctx))
    return report


# --------------------------------------------------------------------------
# the registered serving programs and their churn sweeps
# --------------------------------------------------------------------------

def _sampling(r, b):
    import numpy as np

    return (r.uniform(0.0, 1.5, b).astype(np.float32),       # temperature
            r.randint(0, 8, b).astype(np.int32),              # top_k
            r.uniform(0.5, 1.0, b).astype(np.float32),        # top_p
            r.randint(0, 2**31, b).astype(np.uint32),         # seeds
            r.randint(0, 16, b).astype(np.int32))             # steps


def _decode_args(eng, i: int):
    """One churn configuration of the decode step: config 0 is the cold
    all-zeros baseline (the analyzer entry's shape), later configs mix
    occupancy, draft counts, adapter slots, block tables and sampling —
    all at the same avals."""
    import numpy as np

    b, S = eng.serving.max_batch, eng.spec_width
    mb = eng.cache.max_blocks_per_request
    r = np.random.RandomState(1000 + i)
    if i == 0:
        tokens = np.zeros((b, S), np.int32)
        active = np.zeros((b,), bool)
        n_draft = np.zeros((b,), np.int32)
        tables = np.zeros((b, mb), np.int32)
        positions = np.zeros((b,), np.int32)
        sampling = (np.zeros((b,), np.float32), np.zeros((b,), np.int32),
                    np.ones((b,), np.float32), np.zeros((b,), np.uint32),
                    np.zeros((b,), np.int32))
    else:
        tokens = r.randint(0, 64, (b, S)).astype(np.int32)
        active = r.rand(b) < (0.3 + 0.4 * (i % 2))
        n_draft = r.randint(0, S, b).astype(np.int32)
        tables = r.randint(0, mb, (b, mb)).astype(np.int32)
        positions = r.randint(0, eng.serving.max_seq, b).astype(np.int32)
        sampling = _sampling(r, b)
    core = (tokens, positions, tables, active, n_draft)
    if eng.lora is not None:
        slots = (np.zeros((b,), np.int32) if i == 0
                 else r.randint(0, eng.lora.max_adapters, b)
                 .astype(np.int32))
        return ((eng.arenas, eng.adapters, eng.params)
                + core + (slots,) + sampling)
    return (eng.arenas, eng.params) + core + sampling


def _prefill_args(eng, i: int):
    import numpy as np

    b = eng.serving.max_batch
    T = eng.prefill_len
    mb = eng.cache.max_blocks_per_request
    r = np.random.RandomState(2000 + i)
    if i == 0:
        grids = [np.zeros((b, T), np.int32) for _ in range(5)]
        lengths = np.zeros((b,), np.int32)
        tables = np.zeros((b, mb), np.int32)
        sample_index = np.full((b,), T, np.int32)
        sampling = (np.zeros((b,), np.float32), np.zeros((b,), np.int32),
                    np.ones((b,), np.float32), np.zeros((b,), np.uint32),
                    np.zeros((b,), np.int32))
    else:
        grids = [r.randint(0, 64, (b, T)).astype(np.int32)
                 for _ in range(5)]
        lengths = r.randint(0, T + 1, b).astype(np.int32)
        tables = r.randint(0, mb, (b, mb)).astype(np.int32)
        sample_index = r.randint(0, T + 1, b).astype(np.int32)
        sampling = _sampling(r, b)
    tokens, position_ids, limits, dest_blocks, dest_offsets = grids
    core = (tokens, position_ids, tables, lengths, limits,
            dest_blocks, dest_offsets, sample_index)
    if eng.lora is not None:
        slots = (np.zeros((b,), np.int32) if i == 0
                 else r.randint(0, eng.lora.max_adapters, b)
                 .astype(np.int32))
        return ((eng.arenas, eng.adapters, eng.params)
                + core + (slots,) + sampling)
    return (eng.arenas, eng.params) + core + sampling


# program name -> (engine flavour, step attr, churn-args builder)
STABILITY_PROGRAMS = {
    "decode": ("plain", "_decode", _decode_args),
    "prefill": ("plain", "_prefill", _prefill_args),
    "speculative": ("spec", "_decode", _decode_args),
    "lora": ("lora", "_decode", _decode_args),
}


def _build_engine(flavour: str, cfg, params, mesh):
    from apex_tpu.serving import (
        LoRAConfig, ServingConfig, ServingEngine, SpeculativeConfig)

    serving = ServingConfig(
        max_batch=2, block_size=4, max_seq=16, prefill_len=16,
        speculative=SpeculativeConfig(k=2) if flavour == "spec" else None,
        lora=(LoRAConfig(rank=4, max_adapters=2)
              if flavour == "lora" else None))
    return ServingEngine(cfg, serving, params, mesh=mesh)


def run_stability(programs: Optional[List[str]] = None,
                  n_configs: int = 3) -> Tuple[Report, int]:
    """Trace each registered serving program at ``n_configs`` churn
    configurations and run the stability rulebook over the hashes.
    Returns ``(report, program_count)`` — the pseudo-entry contract
    ``cli.py`` shares with :func:`entries.run_entry`."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import parallel
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    names = list(STABILITY_PROGRAMS) if programs is None else list(programs)
    unknown = [n for n in names if n not in STABILITY_PROGRAMS]
    if unknown:
        raise ValueError(f"unknown stability programs {unknown} "
                         f"(known: {list(STABILITY_PROGRAMS)})")

    report = Report()
    try:
        mesh = parallel.initialize_model_parallel(
            tensor_model_parallel_size=2)
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            padded_vocab_size=64, max_position_embeddings=32,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
            use_flash_attention=True)
        init_fn, _, _ = build_gpt_3d(cfg, num_chunks=2,
                                     num_microbatches=1, mesh=mesh)
        params, _ = init_fn(jax.random.PRNGKey(0),
                            jnp.zeros((2, 4), jnp.int32))
        engines: Dict[str, object] = {}
        for name in names:
            flavour, step_attr, make_args = STABILITY_PROGRAMS[name]
            if flavour not in engines:
                engines[flavour] = _build_engine(flavour, cfg, params,
                                                 mesh)
            eng = engines[flavour]
            fn = getattr(eng, step_attr)
            hashes = [(f"churn{i}", trace_hash(fn, make_args(eng, i)))
                      for i in range(n_configs)]
            report.extend(check_hashes(name, hashes))
    finally:
        mesh_lib.destroy_model_parallel()
    return report, len(names)
