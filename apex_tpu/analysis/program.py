"""The unit of analysis: one staged program plus its declared contract.

A :class:`Program` bundles what the analyzer needs to inspect a function
without running it — the callable and example arguments (for tracing and
lowering) or a pre-compiled HLO text — together with the *expectations*
that parameterize the contract rules: does the sentinel guard this step
(``expect_conditional``), is it an ``overlap_comm`` ring of a given tp
size (``expect_ring`` / ``forbid_ops``), how many donated buffers must
stay aliased (``expect_donation``), and will the caller differentiate
across its ``shard_map`` boundaries (``differentiated`` — the old-jax
rank-0 rule APX101 only applies to programs that declare this intent;
a train step that takes its gradients *inside* the boundary never
transposes the boundary and is exempt).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

__all__ = ["Program"]


@dataclasses.dataclass
class Program:
    """One lintable program.

    ``fn``/``args``/``kwargs`` — the callable at concrete example
    arguments.  Tracing (jaxpr tier) uses ``jax.make_jaxpr``; lowering
    (HLO tier) uses ``fn.lower`` when ``fn`` is already jitted (which
    preserves ``donate_argnums``) and ``jax.jit(fn).lower`` otherwise.
    Neither executes the program.

    ``hlo_text`` — alternatively (or additionally), a pre-compiled
    optimized-HLO text to run the HLO tier on directly.

    Tier selection: the jaxpr tier runs when ``fn`` is set and ``jaxpr_tier``
    is true; the HLO tier runs when ``hlo_text`` is set or (``fn`` set and
    ``hlo_tier`` true).
    """

    name: str
    fn: Any = None
    args: Tuple = ()
    kwargs: Optional[dict] = None
    hlo_text: Optional[str] = None
    jaxpr_tier: bool = True
    hlo_tier: bool = True

    # --- declared contract -------------------------------------------
    # APX101: the caller will differentiate across this program's
    # shard_map boundaries (loss functions; NOT already-guarded steps).
    differentiated: bool = False
    # APX203: sentinel-guarded apply must survive as >= 1 `conditional`.
    expect_conditional: bool = False
    # APX201: overlap_comm ring of this tp size must survive as
    # >= tp-1 collective-permutes ...
    expect_ring: Optional[int] = None
    # ... with zero occurrences of these monolithic opcodes.
    forbid_ops: Tuple[str, ...] = ()
    # APX204: at least this many donated input buffers must appear in
    # input_output_alias (0/None = rule skipped).
    expect_donation: Optional[int] = None

    def __post_init__(self):
        if self.kwargs is None:
            self.kwargs = {}
