"""RNN layer family (apex.RNN parity, closing SURVEY row 19).

Reference: ``apex/RNN/`` — ``RNNBackend.py:25`` (bidirectionalRNN),
``:90`` (stackedRNN), ``:232`` (RNNCell), ``models.py:21-56``
(LSTM/GRU/ReLU/Tanh/mLSTM factories), ``cells.py:55`` (mLSTMCell).
The reference is deprecated upstream but kept here for a clean sweep of
the component inventory, rebuilt the TPU way: ``lax.scan`` over time
(one compiled step, no per-timestep dispatch), gate projections fused
into single GEMMs, bidirectional as a reversed scan, and the whole
stack differentiable through the scan (no fusedBackend autograd glue).
"""

from apex_tpu.rnn.rnn import GRU, LSTM, RNN, ReLU, Tanh, mLSTM

__all__ = ["RNN", "LSTM", "GRU", "ReLU", "Tanh", "mLSTM"]
