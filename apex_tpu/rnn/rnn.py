"""Scan-based stacked/bidirectional RNNs with torch-compatible weights.

Behavioral spec: ``apex/RNN`` — the ``RNNCell`` gate math
(``RNNBackend.py:232-365``), ``stackedRNN`` layer stacking with
inter-layer dropout (``:90-196``), ``bidirectionalRNN`` forward/reverse
fusion (``:25-88``), and the ``models.py:21-56`` factory surface
(LSTM/GRU/ReLU/Tanh/mLSTM).  Weights use the torch layout
(``w_ih: [gates*h, in]``, ``y = x @ w.T``; gate order i,f,g,o for LSTM
and r,z,n for GRU) so ``torch.nn.LSTM``/``GRU`` checkpoints transfer
leaf-for-leaf (verified against torch in ``tests/test_rnn.py``).

TPU-first design:

- time iteration is one ``lax.scan`` — a single compiled step body
  instead of the reference's per-timestep Python loop over autograd
  cells; the input-to-hidden projection for *all* timesteps is hoisted
  out of the scan into one big ``[T*B, in] @ [in, gates*h]`` GEMM
  (MXU-friendly), leaving only the recurrent ``[B, h] @ [h, gates*h]``
  GEMM inside the scan;
- the reference's fused pointwise LSTM epilogue
  (``csrc/fused_dense*``-style ``fusedBackend``) dissolves: XLA fuses
  the gate nonlinearities into the scan body;
- bidirectional runs the same scan on the time-reversed sequence and
  concatenates features (``bidirectionalRNN.forward``), all under one
  jit.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["RNN", "LSTM", "GRU", "ReLU", "Tanh", "mLSTM"]

_GATE_MULT = {"lstm": 4, "mlstm": 4, "gru": 3, "relu": 1, "tanh": 1}
_N_STATES = {"lstm": 2, "mlstm": 2, "gru": 1, "relu": 1, "tanh": 1}


def _lstm_pointwise(gates, c):
    """i,f,g,o gate order (``RNNBackend.py`` LSTMCell /
    ``cells.py:66-74``)."""
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    return o * jnp.tanh(c_new), c_new


class RNN(nn.Module):
    """Stacked (optionally bidirectional) recurrent network.

    ``__call__(x, hidden=None, deterministic=True)`` returns
    ``(output, hidden)``:

    - ``x``: ``[T, B, input]`` (or ``[B, T, input]`` with
      ``batch_first``);
    - ``output``: per-step features of the last layer,
      ``[T, B, dirs*out]``;
    - ``hidden``: tuple of final states, each
      ``[num_layers*dirs, B, h]`` — ``(h,)`` for GRU/ReLU/Tanh,
      ``(h, c)`` for LSTM/mLSTM (torch's return contract).

    Inter-layer dropout uses the flax ``"dropout"`` rng
    (``stackedRNN.forward``'s ``F.dropout`` between layers).
    """

    cell: str
    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    batch_first: bool = False
    dropout: float = 0.0
    bidirectional: bool = False
    output_size: Optional[int] = None  # per-direction w_ho projection
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def _cell_params(self, name: str, in_size: int):
        gm = _GATE_MULT[self.cell]
        h, out = self.hidden_size, self.output_size or self.hidden_size
        # reset_parameters: uniform(-1/sqrt(h), 1/sqrt(h))
        # (RNNBackend.py:291-298)
        init = nn.initializers.uniform(scale=2.0 / jnp.sqrt(h))

        def u(key, shape, dtype):
            return init(key, shape, dtype) - 1.0 / jnp.sqrt(h)

        p = {
            "w_ih": self.param(f"{name}_w_ih", u, (gm * h, in_size),
                               self.param_dtype),
            "w_hh": self.param(f"{name}_w_hh", u, (gm * h, out),
                               self.param_dtype),
        }
        if self.bias:
            p["b_ih"] = self.param(f"{name}_b_ih", u, (gm * h,),
                                   self.param_dtype)
            p["b_hh"] = self.param(f"{name}_b_hh", u, (gm * h,),
                                   self.param_dtype)
        if self.cell == "mlstm":
            # cells.py:20-22: w_mih [out, in], w_mhh [out, out] — the
            # multiplicative intermediate m is *output_size*-dimensional
            p["w_mih"] = self.param(f"{name}_w_mih", u, (out, in_size),
                                    self.param_dtype)
            p["w_mhh"] = self.param(f"{name}_w_mhh", u, (out, out),
                                    self.param_dtype)
        if self.output_size is not None and self.output_size != h:
            p["w_ho"] = self.param(f"{name}_w_ho", u, (self.output_size, h),
                                   self.param_dtype)
        return p

    def _scan_direction(self, p, x, h0, reverse: bool):
        """One (layer, direction) scan.  ``x: [T, B, in]`` ->
        ``(outputs [T, B, out], final_states)``."""
        dt = self.dtype
        w_ih = jnp.asarray(p["w_ih"], dt)
        w_hh = jnp.asarray(p["w_hh"], dt)
        b = 0.0
        if self.bias:
            b = (jnp.asarray(p["b_ih"], dt) + jnp.asarray(p["b_hh"], dt))
        x = jnp.flip(x, axis=0) if reverse else x

        # The whole input projection in one hoisted GEMM; per-cell bias
        # placement: GRU keeps b_ih separate from b_hh (the reset gate
        # multiplies b_hh's n-slice but not b_ih's), mLSTM folds both
        # into the gate sum later, the rest fold the combined bias here.
        xm = None
        if self.cell == "mlstm":
            w_mih = jnp.asarray(p["w_mih"], dt)
            w_mhh = jnp.asarray(p["w_mhh"], dt)
            xm = x @ w_mih.T        # hoisted: [T, B, out]
            xg = x @ w_ih.T         # hoisted input gates
        elif self.cell == "gru":
            xg = x @ w_ih.T + (jnp.asarray(p["b_ih"], dt)
                               if self.bias else 0.0)
        else:
            xg = x @ w_ih.T + b

        w_ho = p.get("w_ho")
        if w_ho is not None:
            w_ho = jnp.asarray(w_ho, dt)

        def project(h):
            return h if w_ho is None else h @ w_ho.T

        cell = self.cell

        def step(carry, inp):
            if cell in ("lstm", "mlstm"):
                h, c = carry
                if cell == "mlstm":
                    xg_t, xm_t = inp
                    m = xm_t * (h @ w_mhh.T)
                    gates = xg_t + m @ w_hh.T + b
                else:
                    gates = inp + h @ w_hh.T
                h_raw, c = _lstm_pointwise(gates, c)
                out = project(h_raw)
                return (out, c), out
            (h,) = carry
            if cell == "gru":
                # r,z,n order (torch/GRUCell parity; RNNBackend GRUCell)
                gh = h @ w_hh.T + (jnp.asarray(p["b_hh"], dt)
                                   if self.bias else 0.0)
                ir, iz, in_ = jnp.split(inp, 3, axis=-1)
                hr, hz, hn = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                n = jnp.tanh(in_ + r * hn)
                h = (1.0 - z) * n + z * h
            else:
                act = jnp.tanh if cell == "tanh" else nn.relu
                h = act(inp + h @ w_hh.T)
            out = project(h)
            return (out,), out

        xs = (xg, xm) if cell == "mlstm" else xg
        carry, ys = lax.scan(step, h0, xs)
        ys = jnp.flip(ys, axis=0) if reverse else ys
        return ys, carry

    @nn.compact
    def __call__(self, x, hidden=None, deterministic: bool = True):
        if self.cell not in _GATE_MULT:
            raise ValueError(f"unknown cell {self.cell!r}; one of "
                             f"{sorted(_GATE_MULT)}")
        if (self.cell == "gru" and self.output_size is not None
                and self.output_size != self.hidden_size):
            # The GRU update h' = (1-z)*n + z*h convex-combines the
            # hidden-width candidate n with the carried state; a projected
            # (output_size-width) carry makes that ill-defined — the
            # reference's GRUCell would crash on the same shapes.
            raise ValueError(
                "GRU does not support output_size != hidden_size (the "
                "update gate mixes the hidden-width candidate with the "
                "carried state); use LSTM/mLSTM/ReLU/Tanh for w_ho "
                "recurrent projection")
        if self.batch_first:
            x = jnp.swapaxes(x, 0, 1)
        x = jnp.asarray(x, self.dtype)
        T, B = x.shape[0], x.shape[1]
        dirs = 2 if self.bidirectional else 1
        out_size = self.output_size or self.hidden_size
        n_states = _N_STATES[self.cell]

        if hidden is None:
            hidden = tuple(
                jnp.zeros((self.num_layers * dirs, B,
                           out_size if i == 0 else self.hidden_size),
                          self.dtype)
                for i in range(n_states))

        finals = [[] for _ in range(n_states)]
        inp = x
        for layer in range(self.num_layers):
            in_size = self.input_size if layer == 0 else out_size * dirs
            outs = []
            for d in range(dirs):
                idx = layer * dirs + d
                p = self._cell_params(f"l{layer}{'_rev' if d else ''}",
                                      in_size)
                h0 = tuple(h[idx] for h in hidden)
                ys, carry = self._scan_direction(p, inp, h0, reverse=d == 1)
                outs.append(ys)
                for i, c in enumerate(carry):
                    finals[i].append(c)
            inp = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
            if (self.dropout > 0.0 and not deterministic
                    and layer + 1 < self.num_layers):
                inp = nn.Dropout(self.dropout, deterministic=False)(
                    inp, rng=self.make_rng("dropout"))

        out = jnp.swapaxes(inp, 0, 1) if self.batch_first else inp
        return out, tuple(jnp.stack(f) for f in finals)


def _factory(cell):
    def make(input_size, hidden_size, num_layers, bias=True,
             batch_first=False, dropout=0.0, bidirectional=False,
             output_size=None, **kw):
        return RNN(cell=cell, input_size=input_size,
                   hidden_size=hidden_size, num_layers=num_layers,
                   bias=bias, batch_first=batch_first, dropout=dropout,
                   bidirectional=bidirectional, output_size=output_size,
                   **kw)

    make.__name__ = cell.upper()
    make.__doc__ = (f"apex.RNN.models.{cell} factory surface "
                    f"(models.py:21-56), returning :class:`RNN`.")
    return make


LSTM = _factory("lstm")
GRU = _factory("gru")
ReLU = _factory("relu")
Tanh = _factory("tanh")
mLSTM = _factory("mlstm")
