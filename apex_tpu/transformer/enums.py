"""Transformer enums — reference ``apex/transformer/enums.py``.

``AttnMaskType`` is defined once in :mod:`apex_tpu.ops.softmax` (the fused
softmax family consumes it) and re-exported here at the reference's path.
"""

import enum

from apex_tpu.ops.softmax import AttnMaskType

__all__ = ["LayerType", "AttnType", "AttnMaskType", "ModelType"]


class LayerType(enum.Enum):
    """``apex/transformer/enums.py`` LayerType."""

    encoder = 1
    decoder = 2


class AttnType(enum.Enum):
    """``apex/transformer/enums.py`` AttnType."""

    self_attn = 1
    cross_attn = 2


class ModelType(enum.Enum):
    """``apex/transformer/enums.py`` ModelType (encoder/decoder split for
    T5-style pipelines, ``parallel_state.py`` split_rank)."""

    encoder_or_decoder = 1
    encoder_and_decoder = 2
