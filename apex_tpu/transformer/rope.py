"""Rotary position embeddings (RoPE), TPU-native.

Parity-plus beyond the reference: apex's testing GPT uses learned absolute
positions only (``apex/transformer/testing/standalone_transformer_lm.py``
Embedding), while its production lineage (Megatron-LM
``rotary_pos_embedding``) moved to RoPE; this module brings the framework's
transformer stack to that modern baseline.  Selected via
``TransformerConfig(position_embedding_type="rope")``.

Design notes (TPU/XLA):

- The cos/sin tables are built inside the traced function from a
  ``positions`` vector — no host-side cache to invalidate, XLA constant-
  folds them for static shapes and fuses the rotation into the
  surrounding elementwise region of the QKV projection.
- Half-rotation ("NeoX"/Megatron) layout: the first ``rotary_dim``
  channels are rotated as two contiguous halves — contiguous lane slices,
  which vectorize on the VPU, unlike the interleaved even/odd ("GPT-J")
  layout which would gather alternating lanes.
- Context parallelism composes by construction: callers pass this rank's
  *global* ``positions`` (shard offset + local arange — see
  ``ParallelAttention``), and each rank rotates its local q/k shard
  before ring/all-to-all exchange, so rotated keys travel the ring
  already position-stamped.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

__all__ = ["rotary_cos_sin", "apply_rotary", "apply_rotary_decode",
           "apply_rotary_packed"]


def rotary_cos_sin(positions, rotary_dim: int, base: float = 10000.0,
                   dtype=jnp.float32) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for :func:`apply_rotary`.

    ``positions`` ``[s]`` (ints; global token indices), ``rotary_dim`` the
    even number of leading head channels to rotate -> ``(cos, sin)`` each
    ``[s, rotary_dim/2]``.  Computed in fp32 regardless of ``dtype``
    (bf16 angles visibly wobble at long context), then cast.
    """
    if rotary_dim % 2:
        raise ValueError(f"rotary_dim must be even, got {rotary_dim}")
    inv_freq = 1.0 / (
        base ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32)
                 / rotary_dim))
    angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def _rotate(x, cos, sin):
    """Half-rotation with pre-broadcast cos/sin (shaped to x's rank)."""
    half = cos.shape[-1]
    rotary_dim = 2 * half
    x1 = x[..., :half]
    x2 = x[..., half:rotary_dim]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    if rotary_dim == x.shape[-1]:
        return rotated
    return jnp.concatenate([rotated, x[..., rotary_dim:]], axis=-1)


def apply_rotary(x, cos, sin):
    """Rotate the leading ``2 * cos.shape[-1]`` channels of ``x``
    ``[s, b, n, d]`` (Megatron's ``[sq, b, np, hn]`` layout); channels
    past ``rotary_dim`` pass through (``rotary_percent < 1``)."""
    # cos/sin [s, half]: broadcast over [b, n]
    return _rotate(x, cos[:, None, None, :], sin[:, None, None, :])


def apply_rotary_packed(x, cos, sin):
    """Chunked-prefill rotation: ``x [s, b, n, d]`` where every
    ``(position, slot)`` pair sits at its own sequence index —
    ``cos``/``sin`` ``[s, b, half]`` from
    ``rotary_cos_sin(positions.reshape(-1), ...)`` reshaped back.  The
    serving runtime's batched-chunk prefill form: each slot's chunk
    starts at that request's own absolute offset, so the tables vary
    along both the position and the batch dim and broadcast only over
    heads."""
    return _rotate(x, cos[:, :, None, :], sin[:, :, None, :])


def apply_rotary_decode(x, cos, sin):
    """Decode-step rotation: ``x [1, b, n, d]`` (one token per batch
    slot) with **per-slot** positions — ``cos``/``sin`` ``[b, half]``
    from ``rotary_cos_sin(positions[b], ...)``.  The serving runtime's
    form of the same half-rotation: in a continuously-batched decode
    step every slot sits at a different sequence position, so the
    tables broadcast over the head dim but vary along batch."""
    return _rotate(x, cos[None, :, None, :], sin[None, :, None, :])
