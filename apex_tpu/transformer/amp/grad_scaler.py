"""Model-parallel-aware gradient scaler.

Behavioral spec: ``apex/transformer/amp/grad_scaler.py:21-125`` —
``GradScaler`` subclasses the native scaler only to **all-reduce found_inf
across the model-parallel group** in ``_maybe_opt_step:44-55`` and
``update:57-125``: with tensor/pipeline parallelism, an overflow on any model
shard must skip the step on *all* shards, or the replicas diverge.

Under SPMD the same guarantee needs one MAX-reduction of the local overflow
flag over every model-parallel mesh axis before the scale update — done in
:meth:`GradScaler.all_finite` (when called inside ``shard_map`` with those
axes bound) or implicitly (global-array grads already see every shard's
values, so plain ``all_finite`` is already model-parallel correct — the
common pjit path needs no reduction at all).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.scaler import DynamicLossScale, LossScaleState, all_finite
from apex_tpu.parallel.collectives import bound_axis_size
from apex_tpu.parallel.mesh import PIPELINE_AXIS, TENSOR_AXIS

__all__ = ["GradScaler"]


@dataclasses.dataclass(frozen=True)
class GradScaler(DynamicLossScale):
    """``DynamicLossScale`` with model-parallel overflow agreement.

    ``model_parallel_axes`` are reduced over in :meth:`all_finite`; pass the
    axes bound by the enclosing ``shard_map`` (default: tensor + pipeline,
    the reference's "model-parallel group" ``parallel_state.py:448-456``).
    Constructor defaults mirror ``torch.cuda.amp.GradScaler`` as the
    reference subclasses it (init 2**16, growth 2, backoff 0.5,
    interval 2000, hysteresis 2 — ``grad_scaler.py:21-43``).
    """

    hysteresis: int = 2
    model_parallel_axes: Tuple[str, ...] = (TENSOR_AXIS, PIPELINE_AXIS)

    def all_finite(self, grads, *, axes: Optional[Sequence[str]] = None):
        """Local overflow check + MAX-agreement over model-parallel axes.

        The SPMD analog of ``all_reduce(found_inf, MAX, model_parallel_group)``
        (``grad_scaler.py:44-55``).  ``axes`` defaults to
        ``model_parallel_axes`` filtered to those actually bound (so the same
        code runs under tp-only or tp+pp shard_maps and under plain jit,
        where no axis is bound and grads are global arrays).
        """
        finite = all_finite(grads)
        use = self.model_parallel_axes if axes is None else tuple(axes)
        bound = [ax for ax in use if bound_axis_size(ax) > 1]
        if bound:
            finite = lax.pmin(finite.astype(jnp.int32), tuple(bound)) > 0
        return finite
