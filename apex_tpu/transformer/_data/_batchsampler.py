"""Megatron pretraining batch samplers.

Behavioral spec: ``apex/transformer/_data/_batchsampler.py`` —
``MegatronPretrainingSampler:38`` (contiguous: walk sample indices from
``consumed_samples``, carve each global minibatch into per-dp-rank slices)
and ``MegatronPretrainingRandomSampler:102`` (per-rank bucket of
``total // (local_mb * dp) * local_mb`` indices, epoch-seeded permutation,
``consumed_samples``-resumable mid-epoch).

TPU notes: yielded index lists feed any indexable dataset; under SPMD one
process may host several dp shards — instantiate one sampler per dp rank
(``data_parallel_rank``) exactly as the reference does per process, then
stack the per-rank minibatches into the global batch that
``dp_shard_batch`` lays onto the mesh.  The random permutation uses
``numpy.random.RandomState(epoch)`` rather than ``torch.Generator`` — the
sequence differs from the reference's but the contract (deterministic per
epoch, disjoint equal shards, mid-epoch resume) is identical.
"""

from __future__ import annotations

import numpy as np

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]


class _Base:
    def __len__(self) -> int:
        return self.total_samples

    @property
    def local_minibatch_size(self) -> int:
        return self._local_minibatch_size

    @local_minibatch_size.setter
    def local_minibatch_size(self, new: int) -> None:
        self._local_minibatch_size = new
        self.local_minibatch_times_data_parallel_size = (
            new * self.data_parallel_size)

    @staticmethod
    def _check(total_samples, consumed_samples, local_minibatch_size,
               data_parallel_rank, data_parallel_size):
        if total_samples <= 0:
            raise ValueError(f"no sample to consume: {total_samples}")
        if consumed_samples >= total_samples:
            raise ValueError(
                f"no samples left to consume: {consumed_samples}, "
                f"{total_samples}")
        if local_minibatch_size <= 0:
            raise ValueError(
                f"local minibatch size must be greater than 0: "
                f"{local_minibatch_size}")
        if data_parallel_size <= 0:
            raise ValueError(
                f"data parallel size must be greater than 0: "
                f"{data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(
                f"data_parallel_rank should be smaller than data parallel "
                f"size: {data_parallel_rank} < {data_parallel_size}")


class MegatronPretrainingSampler(_Base):
    """Contiguous DP-sharded sampler (reference ``:38-100``).

    Deliberate fix vs the reference: its ``__iter__`` accumulates only
    ``local_minibatch_size`` indices before slicing ``[rank*lmb :
    (rank+1)*lmb]``, which returns an empty list for every rank > 0 (an
    upstream bug — Megatron-core accumulates ``lmb * dp``).  This
    implementation accumulates the full global minibatch and slices each
    rank's disjoint window, which is the documented contract.
    """

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, drop_last: bool = True):
        self._check(total_samples, consumed_samples, local_minibatch_size,
                    data_parallel_rank, data_parallel_size)
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.drop_last = drop_last

    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.local_minibatch_size
        return start, start + self.local_minibatch_size

    def __iter__(self):
        batch = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.local_minibatch_times_data_parallel_size:
                start, end = self.get_start_end_idx()
                yield batch[start:end]
                batch = []
        if batch and not self.drop_last:
            start, end = self.get_start_end_idx()
            yield batch[start:end]


class MegatronPretrainingRandomSampler(_Base):
    """Randomized DP-sharded sampler (reference ``:102-180``): each rank
    owns a contiguous bucket, permuted with an epoch-seeded generator;
    ``consumed_samples`` resumes mid-epoch."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int):
        self._check(total_samples, max(consumed_samples, 0) % max(
            total_samples, 1), local_minibatch_size, data_parallel_rank,
            data_parallel_size)
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.last_batch_size = (
            total_samples % self.local_minibatch_times_data_parallel_size)
        if total_samples < self.local_minibatch_times_data_parallel_size:
            raise ValueError(
                f"total_samples ({total_samples}) smaller than one global "
                f"minibatch (local_minibatch_size*data_parallel_size = "
                f"{self.local_minibatch_times_data_parallel_size})")

    def __iter__(self):
        active = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active
        current_epoch_samples = self.consumed_samples % active

        bucket_size = (self.total_samples
                       // self.local_minibatch_times_data_parallel_size
                       ) * self.local_minibatch_size
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        rng = np.random.RandomState(self.epoch)
        random_idx = rng.permutation(bucket_size).tolist()
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch = []
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.local_minibatch_size:
                self.consumed_samples += (
                    self.local_minibatch_times_data_parallel_size)
                yield batch
                batch = []
