"""Data-pipeline utilities (reference ``apex/transformer/_data``)."""

from apex_tpu.transformer._data._batchsampler import (  # noqa: F401
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)
