"""Microbatch calculators — number of microbatches per global step.

Behavioral spec: ``apex/transformer/microbatches.py`` — factory
``build_num_microbatches_calculator:26``, ``ConstantNumMicroBatches:93``,
``RampupBatchsizeNumMicroBatches:112``.  Pure host-side arithmetic (no device
state in the reference either); reproduced 1:1 because the ramp-up semantics
(batch size grows linearly in ``batch_size_increment`` steps over
``ramup_samples`` consumed samples) are part of the training recipe.
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "build_num_microbatches_calculator",
    "NumMicroBatchesCalculator",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
]


class NumMicroBatchesCalculator:
    """Base interface (``microbatches.py:78-91``)."""

    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        raise NotImplementedError


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """Fixed ``global // (micro * dp)`` microbatches (``microbatches.py:93-110``)."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_dp != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel "
                f"size ({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        if self.num_micro_batches < 1:
            raise ValueError("number of microbatches must be at least 1")
        self.current_global_batch_size = global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear batch-size ramp-up (``microbatches.py:112-194``).

    Batch size starts at ``start_batch_size`` and increases by
    ``batch_size_increment`` every
    ``ramup_samples / ((global - start) / increment)`` consumed samples until
    it reaches ``global_batch_size``.
    """

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        if start_batch_size <= 0 or batch_size_increment <= 0:
            raise ValueError("start batch size and increment must be positive")
        if ramup_samples < 0:
            raise ValueError("ramp-up samples must be non-negative")
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )

        diff_batch_size = global_batch_size - start_batch_size
        if diff_batch_size < 0:
            raise ValueError(
                "expected global batch size to be greater than or equal to "
                "start batch size"
            )
        if diff_batch_size % batch_size_increment != 0:
            raise ValueError(
                f"expected global batch size interval ({diff_batch_size}) to "
                f"be divisible by global batch size increment "
                f"({batch_size_increment})"
            )
        num_increments = diff_batch_size // batch_size_increment
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments > 0 else 0
        )

        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool):
        if (consumed_samples > self.ramup_samples
                or self.rampup_samples_per_increment == 0):
            # Past ramp-up, or degenerate ramp (start == global or zero
            # ramp-up samples): jump straight to the full batch size.
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment
            )
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size
            )
        if consistency_check:
            if (self.current_global_batch_size
                    % self.micro_batch_times_data_parallel_size != 0):
                raise ValueError(
                    f"current global batch size "
                    f"({self.current_global_batch_size}) is not divisible by "
                    f"micro-batch-size ({self.micro_batch_size}) times data "
                    f"parallel size ({self.data_parallel_size})"
                )
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size
        )


def build_num_microbatches_calculator(
    rank: int = 0,
    rampup_batch_size=None,
    global_batch_size: int = 1,
    micro_batch_size: int = 1,
    data_parallel_size: int = 1,
) -> NumMicroBatchesCalculator:
    """Factory, ``microbatches.py:26-76``.  ``rampup_batch_size`` is the
    reference's 3-element list ``[start, increment, ramup_samples]``."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "expected the following format: --rampup-batch-size <start batch "
            "size> <batch size increment> <ramp-up samples>"
        )
    start_batch_size = int(rampup_batch_size[0])
    batch_size_increment = int(rampup_batch_size[1])
    ramup_samples = int(rampup_batch_size[2])
    return RampupBatchsizeNumMicroBatches(
        start_batch_size, batch_size_increment, ramup_samples,
        global_batch_size, micro_batch_size, data_parallel_size,
    )
