"""Pipeline parallelism — schedules, microbatch bookkeeping, utilities.

TPU-native rebuild of ``apex/transformer/pipeline_parallel`` (reference
``__init__.py`` exports ``get_forward_backward_func`` and ``build_model``).
The p2p layer (``p2p_communication.py``) has no separate module here: stage
transfer is the ``lax.ppermute`` inside the rotation schedule — see
:mod:`apex_tpu.transformer.pipeline_parallel.schedules`.
"""

from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    pipeline_apply,
    split_into_microbatches,
    stack_stage_params,
)
from apex_tpu.transformer.pipeline_parallel import p2p_communication  # noqa: F401
from apex_tpu.transformer.pipeline_parallel import utils

__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "pipeline_apply",
    "split_into_microbatches",
    "stack_stage_params",
    "utils",
]
