"""Stage-to-stage p2p surface for custom pipeline schedules.

Behavioral spec: ``apex/transformer/pipeline_parallel/p2p_communication.py``
— ``_communicate:168`` (batched isend/irecv pairs) and the nine public
wrappers ``recv_forward:385`` … ``send_forward_backward_recv_forward_
backward:655`` that the reference's schedules compose.  The built-in
rotation schedule (:mod:`.schedules`) does not need this module — its one
``ppermute`` per tick is the whole protocol — but users writing *custom*
schedules get the same building blocks here (round-1 VERDICT row 31).

SPMD semantics vs the reference:
- every wrapper is a **collective permute** executed by all pp ranks, not
  a per-rank point-to-point call: "send" means my payload moves to the
  neighbor, "recv" is the permute's output on my rank;
- the reference returns ``None`` on pipeline edges (first stage has no
  forward peer, ``recv_forward:385-398``); under SPMD shapes must be
  static, so edges receive **zeros** by default (``lax.ppermute`` fills
  missing sources) or wrap around when ``ring=True`` (the rotation
  schedule's circular transfer, used by interleaved chunking);
- async overlap (``FutureTensor``, ``:34``) needs no analog: XLA
  schedules the permute DMA concurrently with independent compute
  automatically;
- the reference's scatter-gather optimization (chunk the p2p payload over
  the tp group, ``:262-270``) is likewise XLA's job — under shard_map the
  payload is already only the local tp shard.

Every function takes/returns *pytrees* (the reference moves single
tensors of a negotiated ``tensor_shape``; pytrees subsume the
shape-protocol handshake ``:29-86``).
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

from apex_tpu.parallel import collectives as cc

from apex_tpu.parallel.mesh import PIPELINE_AXIS

__all__ = [
    "recv_forward",
    "recv_backward",
    "send_forward",
    "send_backward",
    "send_forward_recv_backward",
    "send_backward_recv_forward",
    "send_forward_recv_forward",
    "send_backward_recv_backward",
    "send_forward_backward_recv_forward_backward",
]


def _perm_next(n: int, ring: bool):
    pairs = [(i, i + 1) for i in range(n - 1)]
    if ring:
        pairs.append((n - 1, 0))
    return pairs


def _perm_prev(n: int, ring: bool):
    pairs = [(i + 1, i) for i in range(n - 1)]
    if ring:
        pairs.append((0, n - 1))
    return pairs


def _shift(tree: Any, axis: str, forward: bool, ring: bool):
    n = cc.axis_size(axis)
    perm = _perm_next(n, ring) if forward else _perm_prev(n, ring)
    return jax.tree_util.tree_map(
        lambda l: lax.ppermute(l, axis, perm), tree)


def send_forward_recv_forward(output_tensor, axis: str = PIPELINE_AXIS,
                              *, ring: bool = False):
    """Ship activations one stage down; return what arrived from upstream
    (reference ``:577``).  The first stage receives zeros unless ``ring``.
    """
    return _shift(output_tensor, axis, forward=True, ring=ring)


def send_backward_recv_backward(input_tensor_grad, axis: str = PIPELINE_AXIS,
                                *, ring: bool = False):
    """Ship gradients one stage up; return what arrived from downstream
    (reference ``:616``)."""
    return _shift(input_tensor_grad, axis, forward=False, ring=ring)


# The remaining reference wrappers are the same two permutes with edge
# masking conventions; they exist so ported schedule code reads 1:1.

def recv_forward(output_tensor, axis: str = PIPELINE_AXIS, *,
                 ring: bool = False):
    """Receive the upstream stage's activations (reference ``:385``).
    SPMD form: every rank must contribute its payload — identical to
    :func:`send_forward_recv_forward`."""
    return send_forward_recv_forward(output_tensor, axis, ring=ring)


def recv_backward(input_tensor_grad, axis: str = PIPELINE_AXIS, *,
                  ring: bool = False):
    """Receive the downstream stage's gradient (reference ``:410``)."""
    return send_backward_recv_backward(input_tensor_grad, axis, ring=ring)


def send_forward(output_tensor, axis: str = PIPELINE_AXIS, *,
                 ring: bool = False):
    """Reference ``:445``; the return value is the received activation
    (discard it on the first stage, which the reference models as None)."""
    return send_forward_recv_forward(output_tensor, axis, ring=ring)


def send_backward(input_tensor_grad, axis: str = PIPELINE_AXIS, *,
                  ring: bool = False):
    """Reference ``:469``."""
    return send_backward_recv_backward(input_tensor_grad, axis, ring=ring)


def send_forward_recv_backward(output_tensor, input_tensor_grad,
                               axis: str = PIPELINE_AXIS, *,
                               ring: bool = False):
    """The steady-state 1F1B pair (reference ``:494``): activations go
    down while gradients come up.  XLA runs the two permutes
    concurrently — the batched ``P2POp`` list of the reference."""
    recv_grad = _shift(input_tensor_grad, axis, forward=False, ring=ring)
    _shift_out = _shift(output_tensor, axis, forward=True, ring=ring)
    return _shift_out, recv_grad


def send_backward_recv_forward(input_tensor_grad, output_tensor,
                               axis: str = PIPELINE_AXIS, *,
                               ring: bool = False):
    """Reference ``:532``."""
    recv_act = _shift(output_tensor, axis, forward=True, ring=ring)
    _shift_grad = _shift(input_tensor_grad, axis, forward=False, ring=ring)
    return _shift_grad, recv_act


def send_forward_backward_recv_forward_backward(
        output_tensor, input_tensor_grad, axis: str = PIPELINE_AXIS, *,
        ring: bool = False):
    """Both directions at once (reference ``:655``)."""
    return (_shift(output_tensor, axis, forward=True, ring=ring),
            _shift(input_tensor_grad, axis, forward=False, ring=ring))
