"""Pipeline-parallel utilities.

Behavioral spec: ``apex/transformer/pipeline_parallel/utils.py`` — global
microbatch-calculator setup (``setup_microbatch_calculator:58``,
``get_num_microbatches:92``), loss averaging
(``average_losses_across_data_parallel_group:242``), params L2 norm
(``calc_params_l2_norm:213``), LM masks/position-ids
(``get_ltor_masks_and_position_ids:303``), memory reporting
(``report_memory:253``), rank-print helpers (``:159-177``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import DATA_AXIS
from apex_tpu.transformer.microbatches import (
    build_num_microbatches_calculator,
)
from apex_tpu.utils.tree import tree_l2_norm

__all__ = [
    "setup_microbatch_calculator",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "average_losses_across_data_parallel_group",
    "calc_params_l2_norm",
    "get_ltor_masks_and_position_ids",
    "report_memory",
    "print_rank_0",
    "print_rank_last",
]

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def setup_microbatch_calculator(
    rank: int = 0,
    rampup_batch_size=None,
    global_batch_size: int = 1,
    micro_batch_size: int = 1,
    data_parallel_size: int = 1,
) -> None:
    """``pipeline_parallel/utils.py:58-78`` — build the global calculator."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("num microbatches calculator is already initialized")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )


def _destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def get_num_microbatches() -> int:
    """``pipeline_parallel/utils.py:92-94``."""
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size() -> int:
    """``pipeline_parallel/utils.py:97-99``."""
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    """``pipeline_parallel/utils.py:88-90``."""
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples,
                                               consistency_check)


def average_losses_across_data_parallel_group(losses,
                                              axis: Optional[str] = None):
    """Mean of stacked losses, reduced over the data-parallel axis.

    Reference: ``pipeline_parallel/utils.py:242-250`` (all_reduce / dp world
    size).  Under SPMD, pass ``axis=DATA_AXIS`` when called inside a bound
    ``shard_map``; with pjit-style global arrays the dp mean is already
    implicit and ``axis=None`` just stacks and averages.
    """
    averaged = jnp.stack([jnp.mean(l) for l in losses])
    if axis is not None:
        averaged = lax.pmean(averaged, axis)
    return averaged


def calc_params_l2_norm(params, per_tensor: bool = False):
    """Global (or per-tensor) L2 norm of parameters.

    Reference: ``pipeline_parallel/utils.py:213-239`` — a
    ``multi_tensor_l2norm`` launch with TP-duplicate filtering.  Under SPMD
    parameters are stored exactly once per shard, so no duplicate filtering
    is needed; the flat reduction fuses in XLA.
    """
    if per_tensor:
        return jax.tree_util.tree_map(
            lambda p: jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32)))),
            params,
        )
    return tree_l2_norm(params)


def get_ltor_masks_and_position_ids(
    data,
    eod_token: Optional[int] = None,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Left-to-right (causal) masks and position ids for LM batches.

    Reference: ``pipeline_parallel/utils.py:303-355``.  Returns
    ``(attention_mask, loss_mask, position_ids)`` with the reference's
    conventions: attention mask is boolean with **True = masked out** (the
    ``< 0.5`` inversion at ``:353``), loss mask zeroes EOD positions when
    ``eod_mask_loss``.

    The per-document reset variants (``reset_position_ids`` /
    ``reset_attention_mask``) rebuild positions/visibility after each EOD
    token (``:327-351``) — implemented with cumulative document ids instead
    of the reference's per-row host loop so the whole batch stays on device.
    """
    micro_batch_size, seq_length = data.shape

    att_mask_batch = (
        micro_batch_size if reset_attention_mask else 1
    )
    causal = ~jnp.tril(
        jnp.ones((seq_length, seq_length), dtype=bool)
    )  # True above diagonal = masked
    attention_mask = jnp.broadcast_to(
        causal, (att_mask_batch, 1, seq_length, seq_length)
    )

    loss_mask = jnp.ones(data.shape, dtype=jnp.float32)
    if eod_mask_loss:
        if eod_token is None:
            raise ValueError("eod_mask_loss requires eod_token")
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(
        jnp.arange(seq_length, dtype=jnp.int32), data.shape
    )

    if reset_position_ids or reset_attention_mask:
        if eod_token is None:
            raise ValueError("document reset requires eod_token")
        # Document id of each position: number of EODs strictly before it.
        is_eod = (data == eod_token).astype(jnp.int32)
        doc_id = jnp.cumsum(is_eod, axis=1) - is_eod  # EOD belongs to its doc
        if reset_position_ids:
            # Position within document: global pos minus the position just
            # after the previous EOD (utils.py:344-350).
            pos = jnp.arange(seq_length, dtype=jnp.int32)[None, :]
            # The reference resets positions only *after* the EOD
            # (utils.py:344-350): the EOD keeps its in-document position, so
            # the document start is the cummax over strictly-earlier EODs.
            prev_eod_pos = jnp.where(is_eod == 1, pos + 1, 0)
            shifted = jnp.pad(prev_eod_pos[:, :-1], ((0, 0), (1, 0)))
            doc_start = jax.lax.cummax(shifted, axis=1)
            position_ids = pos - doc_start
        if reset_attention_mask:
            same_doc = doc_id[:, None, :] == doc_id[:, :, None]
            attention_mask = attention_mask | ~same_doc[:, None, :, :]

    return attention_mask, loss_mask, position_ids


def report_memory(name: str = "") -> str:
    """Device-memory summary, analog of ``report_memory``
    (``pipeline_parallel/utils.py:253-263``) over ``jax.local_devices()``
    memory stats instead of the CUDA caching allocator."""
    lines = []
    for d in jax.local_devices():
        stats = d.memory_stats() or {}
        in_use = stats.get("bytes_in_use", 0) / 2**20
        limit = stats.get("bytes_limit", 0) / 2**20
        peak = stats.get("peak_bytes_in_use", 0) / 2**20
        lines.append(
            f"[{name}] {d.platform}:{d.id} memory (MB) | in-use: {in_use:.1f}"
            f" | peak: {peak:.1f} | limit: {limit:.1f}"
        )
    report = "\n".join(lines)
    print_rank_last(report)
    return report


def print_rank_0(message: str) -> None:
    """``pipeline_parallel/utils.py:159-166`` (process 0 under multi-host)."""
    if jax.process_index() == 0:
        print(message, flush=True)


def print_rank_last(message: str) -> None:
    """``pipeline_parallel/utils.py:169-177``."""
    if jax.process_index() == jax.process_count() - 1:
        print(message, flush=True)
