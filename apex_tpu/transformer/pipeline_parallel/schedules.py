"""Pipeline-parallel forward/backward schedules — SPMD rotation design.

Behavioral spec: ``apex/transformer/pipeline_parallel/schedules/`` —
dispatcher ``get_forward_backward_func`` (``schedules/__init__.py:22-35``),
no-pipelining (``fwd_bwd_no_pipelining.py:23``), 1F1B
(``fwd_bwd_pipelining_without_interleaving.py:241-597``) and interleaved
virtual-pipeline (``fwd_bwd_pipelining_with_interleaving.py:27-744``), with
stage transfer in ``p2p_communication.py:168``.

TPU-first design
----------------
The reference schedules are *host Python* state machines: each rank walks its
own warmup/steady/cooldown sequence and posts NCCL isend/irecv per microbatch.
Under XLA SPMD every device traces the same program, so the schedule is
expressed instead as a **rotation pipeline** inside one ``shard_map`` over the
``pp`` mesh axis:

- stage parameters live sharded over ``pp`` (leading virtual-stage dim);
- one ``lax.scan`` over "ticks"; each tick every stage applies its (chunk's)
  computation to the activation in its slot and ``lax.ppermute``-shifts the
  result to the next stage (the ``send_forward``/``recv_forward`` pair,
  ``p2p_communication.py:385-470``, becomes a single collective-permute that
  rides ICI);
- microbatch ``j`` enters stage 0 at tick ``e_j`` and exits the last stage
  ``pp*vpp`` ticks later; with ``vpp > 1`` the wrap-around edge of the same
  ppermute carries the chunk-to-chunk transition of the **interleaved
  (circular) schedule**, whose bubble is ``(pp-1)`` ticks versus the
  non-interleaved ``(pp-1)*vpp`` — the same ``1/vpp`` bubble reduction as the
  reference's interleaved schedule;
- the backward pipeline is **not hand-written**: differentiating the scan
  transposes every ``ppermute`` into its reverse permutation and replays the
  ticks in reverse order, which *is* the cooldown/steady/warmup backward walk
  of the reference (``backward_step`` ``schedules/common.py:325``).  XLA
  overlaps the permute DMA with the next tick's compute — the latency hiding
  the reference implements by hand with side streams and ``FutureTensor``.

1F1B's reason to exist is bounding live activations to ``pp`` microbatches
(vs GPipe's ``m``).  The JAX analog here is rematerialisation: with
``remat=True`` (default) each stage recomputes its tick's internals in
backward, so the per-tick *residuals* are not stored.  The scan backward
does still store one carried boundary activation per tick (~``m*vpp``
ticks), so the live-activation footprint is **O(m*vpp) boundary tensors +
one tick's recomputed internals** — GPipe-with-remat behavior, smaller
than storing full per-layer residuals but not 1F1B's O(pp) bound.  The
trade buys SPMD-friendly homogeneous control flow (SURVEY.md §7 hard
part (a)).  When the 1F1B-class bound *is* required, pass
``remat_ticks=G``: ticks are scanned in checkpointed groups of ``G``
whose only saved residual is the one carried boundary activation per
group — O(T/G) stored rows + O(G) recomputed per backward group, i.e.
O(sqrt(T)) at ``G≈sqrt(T)`` or the 1F1B-flavored O(m/pp + pp*vpp) at the
default ``G = pp*vpp`` — for one extra rotation-forward of recompute per
step (the standard remat FLOP/memory trade).

Schedule math (static, host-side): with ``period = pp*vpp``, microbatch ``j``
enters at ``e_j = (j // pp) * period + (j % pp)``; its stream occupies slot
``(stage = v % pp, tick = e_j + v)`` for virtual stage ``v = 0..period-1``;
the chunk applied by stage ``s`` at tick ``t`` is ``((t - s) // pp) % vpp``.
Distinct entry ticks occupy disjoint slot streams, so bubble slots compute
garbage that is never read (the reference's warmup/cooldown bubbles) and
contribute zero gradient.

Stage homogeneity: every virtual stage runs the same ``stage_fn`` with its
own parameter slice, and the activation pytree entering stage 0 must have the
stage output's structure/shape — the reference has the very same contract in
its fixed ``tensor_shape`` p2p protocol
(``fwd_bwd_pipelining_without_interleaving.py:29-86``).  Embedding and loss
head therefore live *outside* the pipelined region (computed replicated over
``pp``, negligible vs the stage GEMMs) — see
``apex_tpu.transformer.testing.standalone_gpt`` for the worked pattern.
"""

from __future__ import annotations

import functools
import operator
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.observability.spans import named_span
from apex_tpu.parallel import collectives as cc

from apex_tpu.parallel.mesh import PIPELINE_AXIS, get_mesh

__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "pipeline_apply",
    "pipeline_bubble_fraction",
    "pipeline_total_ticks",
    "split_into_microbatches",
    "stack_stage_params",
]

StageFn = Callable[[Any, Any], Any]   # (stage_params, activation) -> activation
LossFn = Callable[[Any, Any], jnp.ndarray]  # (output, target) -> scalar

# Jitted grouped-remat pipelines, memoized so repeated *eager* calls of
# pipeline_apply(remat_ticks=...) don't recompile (see pipeline_apply
# tail).  Keyed on stage_fn *identity* — deliberately conservative (keying
# on code would alias closures over different captured models); callers
# wanting cache hits must pass a stable stage_fn object, not a fresh
# lambda per call.  LRU: hits move to the back, eviction pops the front.
_GROUPED_JIT_CACHE: dict = {}
_GROUPED_JIT_CACHE_MAX = 32
# Identity-driven miss counts per stage_fn code object.  Fresh closures
# per call share a code object but never hit the identity-keyed cache; a
# miss only counts when a cached entry matches the key in every component
# *except* stage_fn identity, so legitimate misses (new shapes, new
# config, LRU eviction) never accumulate toward the warning.
_GROUPED_JIT_MISSES: dict = {}
_GROUPED_JIT_MISSES_MAX = 64
_GROUPED_JIT_MISS_WARN_AT = 4


def _note_cache_miss(stage_fn, key) -> None:
    code = getattr(stage_fn, "__code__", None)
    if code is None:
        return
    identity_driven = any(
        k[0] is not stage_fn and k[1:] == key[1:] for k in _GROUPED_JIT_CACHE
    )
    if not identity_driven:
        return
    if len(_GROUPED_JIT_MISSES) >= _GROUPED_JIT_MISSES_MAX:
        _GROUPED_JIT_MISSES.pop(next(iter(_GROUPED_JIT_MISSES)))
    misses = _GROUPED_JIT_MISSES.get(code, 0) + 1
    _GROUPED_JIT_MISSES[code] = misses
    if misses == _GROUPED_JIT_MISS_WARN_AT:
        import warnings

        warnings.warn(
            f"pipeline_apply(remat_ticks=...) has recompiled {misses} times "
            f"for distinct stage_fn objects sharing the code at "
            f"{code.co_filename}:{code.co_firstlineno}. The grouped-remat "
            "jit cache keys on stage_fn *identity*; pass one stable "
            "stage_fn object (hoist it out of the step loop) instead of a "
            "fresh closure/lambda per call.",
            stacklevel=4,
        )


def _abstract_key(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef,
            tuple((l.shape, jnp.result_type(l).name) for l in leaves))


def split_into_microbatches(batch, num_microbatches: int):
    """Reshape every leaf ``[m*b, ...] -> [m, b, ...]``.

    The analog of the reference's ``get_kth_microbatch``
    (``pipeline_parallel/utils.py:228-240``), done once up front so the
    microbatch loop is a traced ``scan`` dimension instead of host slicing.
    """
    def split(leaf):
        if leaf.shape[0] % num_microbatches != 0:
            raise ValueError(
                f"batch dim {leaf.shape[0]} not divisible by "
                f"num_microbatches={num_microbatches}"
            )
        return leaf.reshape((num_microbatches, leaf.shape[0] // num_microbatches)
                            + leaf.shape[1:])
    return jax.tree_util.tree_map(split, batch)


def stack_stage_params(per_stage_params: Sequence[Any]):
    """Stack a list of per-virtual-stage param pytrees along a new leading dim.

    The analog of ``build_model``'s per-rank module list
    (``schedules/common.py:30-150``): virtual stage ``v`` (= chunk
    ``v // pp`` on stage ``v % pp``) is row ``v`` — plain layer order.
    """
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *per_stage_params)


def _entry_ticks(m: int, pp: int, vpp: int) -> np.ndarray:
    period = pp * vpp
    j = np.arange(m)
    return (j // pp) * period + (j % pp)


def _exit_schedule(total_ticks: int, period: int, pp: int, m: int,
                   pad_to: Optional[int] = None):
    """Static per-tick exit metadata ``(j_out, valid)``.

    Tick ``t`` is microbatch ``j_out``'s last-virtual-stage exit iff
    ``u = t - (period-1)`` is an entry tick shifted by the pipe depth:
    ``j_out = (u // period) * pp + (u % period)`` with ``u % period < pp``.
    The one copy of this formula — both the flat scan and the grouped-remat
    scan consume these arrays as ``xs``.  Ticks past ``total_ticks`` (group
    padding) are invalid; invalid entries have ``j_out`` forced to 0.
    """
    n = total_ticks if pad_to is None else pad_to
    t = np.arange(n)
    u = t - (period - 1)
    ug, ur = u // period, u % period
    j_out = ug * pp + ur
    valid = (u >= 0) & (ur < pp) & (j_out < m) & (t < total_ticks)
    return np.where(valid, j_out, 0), valid


def pipeline_total_ticks(m: int, pp: int, vpp: int = 1) -> int:
    """Total schedule ticks per rank for one step: ``entry[-1] + pp*vpp``
    (the single source of the tick-count formula — the bubble fraction
    and the hardware tick-anchor harness both derive from it)."""
    entry = _entry_ticks(m, pp, vpp)
    return int(entry[-1]) + pp * vpp


def pipeline_bubble_fraction(m: int, pp: int, vpp: int = 1) -> float:
    """Fraction of schedule ticks that are bubbles, from the schedule math.

    Of :func:`pipeline_total_ticks` ticks, ``m*vpp`` do useful stage
    work.  For ``vpp=1`` this reduces exactly to 1F1B's textbook bubble
    ``(pp-1)/(m+pp-1)`` (reference
    ``fwd_bwd_pipelining_without_interleaving.py`` warmup+cooldown count);
    interleaving divides the bubble by ``vpp`` as expected.  The perf
    harness (``examples/bench_pipeline.py``) checks measured step time
    against this prediction.
    """
    return 1.0 - (m * vpp) / pipeline_total_ticks(m, pp, vpp)


def pipeline_apply(
    stage_fn: StageFn,
    stage_params,
    inputs,
    *,
    num_chunks: int = 1,
    axis: str = PIPELINE_AXIS,
    mesh: Optional[Mesh] = None,
    remat: bool = True,
    remat_ticks: Optional[int] = None,
    params_already_local: bool = False,
    shard_microbatches: bool = False,
):
    """Run microbatched ``inputs`` through the rotation pipeline.

    ``stage_params``: pytree with leading dim ``pp*num_chunks`` (virtual-stage
    major).  ``inputs``: activation pytree with leading microbatch dim ``m``;
    its per-microbatch structure/shape must equal ``stage_fn``'s output.
    Returns the last virtual stage's outputs, ``[m, ...]``, replicated over
    ``axis`` (the reference's last-stage-only outputs, broadcast so the loss
    can be computed SPMD).

    Differentiable: use inside a ``jax.grad`` of the full train loss to get
    the backward pipeline (see module docstring).

    ``params_already_local``: for calls from inside an enclosing
    ``shard_map`` that already bound ``axis`` — params are then the local
    ``[num_chunks, 1, ...]`` slices and no sharding wrapper is applied.

    ``remat_ticks``: scan ticks in ``jax.checkpoint``-ed groups of this
    size (``True`` picks ``pp*num_chunks``, one pipeline period).  The
    backward then stores one boundary activation per *group* instead of
    per tick — the 1F1B-class live-activation bound (module docstring) —
    at the cost of one extra rotation-forward of recompute.

    ``shard_microbatches``: hold only ``m/pp`` microbatch rows per pp rank
    instead of replicating the full ``[m, ...]`` input and output buffers
    on every rank (round-1 VERDICT weak #4).  Entry rows are fetched with
    a one-row owner-masked ``psum`` broadcast at each tick and exit rows
    delivered to their owner the same way — O(row) traffic per tick, the
    same order as the rotation ``ppermute`` itself — cutting the two live
    ``[m, ...]`` buffers to ``[m/pp, ...]``.  Requires ``m % pp == 0``;
    the return value is still the full ``[m, ...]`` outputs (gathered once
    at the end).  Combined with ``params_already_local``, ``inputs`` must
    be this rank's **local shard** ``[m/pp, ...]`` (contiguous rows
    ``[s*m/pp, (s+1)*m/pp)``).
    """
    if mesh is None and not params_already_local:
        mesh = get_mesh()
    pp = (cc.axis_size(axis) if params_already_local else mesh.shape[axis])
    vpp = num_chunks
    period = pp * vpp

    # Normalize remat_ticks once: None/False -> off, True -> one period,
    # else an exact positive integer group size.
    if remat_ticks is None or remat_ticks is False:
        group_size = None
    elif remat_ticks is True:
        group_size = period
    else:
        group_size = operator.index(remat_ticks)
        if group_size < 1:
            raise ValueError(
                f"remat_ticks must be True or a positive group size, got "
                f"{remat_ticks!r} (use None/False to disable)")

    leaves = jax.tree_util.tree_leaves(inputs)
    if not leaves:
        raise ValueError("inputs pytree is empty")
    m = leaves[0].shape[0]
    if shard_microbatches and params_already_local:
        m = m * pp  # inputs are this rank's local [m/pp, ...] shard
    if shard_microbatches and m % pp != 0:
        raise ValueError(
            f"shard_microbatches requires num_microbatches ({m}) divisible "
            f"by pp ({pp})")
    entry = _entry_ticks(m, pp, vpp)
    total_ticks = pipeline_total_ticks(m, pp, vpp)  # == entry[-1] + period

    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    mpp = m // pp if shard_microbatches else m

    def local_pipeline(params_local, x_mb):
        # params_local leaves: [vpp, 1, ...] (chunk-major local slice).
        # x_mb leaves: [m, ...] replicated, or [m/pp, ...] sharded.
        s = lax.axis_index(axis)

        def chunk_params(c):
            return jax.tree_util.tree_map(
                lambda l: lax.dynamic_index_in_dim(
                    l, c, axis=0, keepdims=False
                )[0],
                params_local,
            )

        def fetch_entry(j):
            if not shard_microbatches:
                return jax.tree_util.tree_map(
                    lambda l: lax.dynamic_index_in_dim(l, j, axis=0,
                                                       keepdims=False),
                    x_mb,
                )
            # owner-masked one-row psum broadcast (same pattern and cost
            # class as the exit delivery below: ~2 rows per link per tick,
            # vs pp-1 rows for a ring all_gather).
            local_j = jnp.clip(j - s * mpp, 0, mpp - 1)
            owner = j // mpp
            return jax.tree_util.tree_map(
                lambda l: lax.psum(
                    jnp.where(
                        s == owner,
                        lax.dynamic_index_in_dim(l, local_j, axis=0,
                                                 keepdims=False),
                        jnp.zeros(l.shape[1:], l.dtype)),
                    axis),
                x_mb,
            )

        def rotate(state, t):
            """One rotation tick: inject entries, apply the chunk, shift.
            Returns ``(shifted_state, y)`` with ``y`` the pre-shift stage
            output (the last stage's ``y`` is a microbatch exit)."""
            grp = t // period
            r = t % period
            j = jnp.clip(grp * pp + r, 0, m - 1)
            entry_mb = fetch_entry(j)
            is_entry = jnp.logical_and(s == 0, r < pp)
            x_in = jax.tree_util.tree_map(
                lambda e, c_: jnp.where(is_entry, e, c_), entry_mb, state
            )
            c = jnp.clip(((t - s) // pp) % vpp, 0, vpp - 1)
            # Profiler scopes on the tick body (scanned, so each name
            # appears once in the program but tags every tick's ops in a
            # capture): stage compute vs the rotation hop — the
            # pipeline-bubble evidence of the capture runbook.
            with named_span("pipeline/stage_compute"):
                y = fn(chunk_params(c), x_in)
            with named_span("pipeline/rotate_shift"):
                shifted = jax.tree_util.tree_map(
                    lambda l: lax.ppermute(
                        l, axis, [(i, (i + 1) % pp) for i in range(pp)]
                    ),
                    y,
                )
            return shifted, y

        def grouped_ticks():
            """Two-level remat: scan ticks in ``jax.checkpoint``-ed groups
            whose carry is the rotation state only.  Exit rows leave the
            checkpointed region as scan *outputs* and are scattered into
            the output buffer outside it, so the only residual stored per
            group is one boundary activation — O(T/G) live rows (module
            docstring) vs the flat scan's O(T)."""
            G = group_size
            ngroups = -(-total_ticks // G)
            j_out_np, valid_np = _exit_schedule(total_ticks, period, pp, m,
                                                pad_to=ngroups * G)
            t_np = np.arange(ngroups * G)

            def group_body(state, tg):
                def inner(st, t):
                    st, y = rotate(st, t)
                    if shard_microbatches:
                        # deliver the exit row to all ranks (its owner
                        # writes it below) — same per-tick traffic class
                        # as the rotation ppermute.
                        y = jax.tree_util.tree_map(
                            lambda yl: lax.psum(
                                jnp.where(s == pp - 1, yl,
                                          jnp.zeros_like(yl)),
                                axis),
                            y,
                        )
                    return st, y

                return lax.scan(inner, state, tg)

            group_fn = jax.checkpoint(group_body)
            nrows = mpp if shard_microbatches else m

            def outer(carry, xs):
                state, outbuf = carry
                tg, j_idx, valid = xs
                state, rows = group_fn(state, tg)  # rows: [G, ...] pytree
                # Scatter: valid exits go to their row, everything else to
                # the dump row ``nrows`` (never read) — no read-modify-
                # write, so the buffer is not a residual of anything.
                if shard_microbatches:
                    own = valid & (j_idx // mpp == s)
                    widx = jnp.where(own, j_idx - s * mpp, nrows)
                else:
                    widx = jnp.where(valid & (s == pp - 1), j_idx, nrows)
                outbuf = jax.tree_util.tree_map(
                    lambda buf, rl: buf.at[widx].set(rl), outbuf, rows
                )
                return (state, outbuf), None

            carry0 = jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape[1:], l.dtype), x_mb
            )
            out0 = jax.tree_util.tree_map(
                lambda l: jnp.zeros((nrows + 1,) + l.shape[1:], l.dtype),
                x_mb,
            )
            xs = (
                jnp.asarray(t_np).reshape(ngroups, G),
                jnp.asarray(j_out_np).reshape(ngroups, G),
                jnp.asarray(valid_np).reshape(ngroups, G),
            )
            (_, outs), _ = lax.scan(outer, (carry0, out0), xs)
            outs = jax.tree_util.tree_map(lambda l: l[:nrows], outs)
            if shard_microbatches:
                return jax.tree_util.tree_map(
                    lambda l: lax.all_gather(l, axis, axis=0, tiled=True),
                    outs)
            return jax.tree_util.tree_map(lambda l: lax.psum(l, axis), outs)

        if group_size is not None:
            return grouped_ticks()

        def tick(carry, xs):
            t, j_outc, exit_valid = xs  # from _exit_schedule
            state, outbuf = carry
            state, y = rotate(state, t)
            # Exit bookkeeping: accumulate the exiting row into the output
            # buffer (O(1) rows touched per tick) instead of stacking all
            # T tick outputs.
            if shard_microbatches:
                # deliver the last stage's row to its owner rank: one-row
                # psum broadcast (same O(row) per-tick traffic class as the
                # rotation ppermute), then an ownership-masked local write.
                y_bcast = jax.tree_util.tree_map(
                    lambda yl: lax.psum(
                        jnp.where(s == pp - 1, yl, jnp.zeros_like(yl)),
                        axis),
                    y,
                )
                own = exit_valid & (j_outc // mpp == s)
                widx = jnp.clip(j_outc - s * mpp, 0, mpp - 1)
                outbuf = jax.tree_util.tree_map(
                    lambda buf, yl: lax.dynamic_update_index_in_dim(
                        buf,
                        jnp.where(
                            own, yl,
                            lax.dynamic_index_in_dim(buf, widx, axis=0,
                                                     keepdims=False),
                        ),
                        widx, axis=0,
                    ),
                    outbuf, y_bcast,
                )
            else:
                do_write = exit_valid & (s == pp - 1)
                outbuf = jax.tree_util.tree_map(
                    lambda buf, yl: lax.dynamic_update_index_in_dim(
                        buf,
                        jnp.where(
                            do_write, yl,
                            lax.dynamic_index_in_dim(buf, j_outc, axis=0,
                                                     keepdims=False),
                        ),
                        j_outc, axis=0,
                    ),
                    outbuf, y,
                )
            return (state, outbuf), None

        carry0 = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape[1:], l.dtype), x_mb
        )
        out0 = jax.tree_util.tree_map(jnp.zeros_like, x_mb)
        j_out_np, valid_np = _exit_schedule(total_ticks, period, pp, m)
        (_, outs), _ = lax.scan(
            tick, (carry0, out0),
            (jnp.arange(total_ticks), jnp.asarray(j_out_np),
             jnp.asarray(valid_np)))
        if shard_microbatches:
            # each rank holds its own m/pp rows; materialize the full [m,..]
            # outputs once (tiled all_gather) to keep the return contract.
            return jax.tree_util.tree_map(
                lambda l: lax.all_gather(l, axis, axis=0, tiled=True), outs)
        # Only the last stage wrote real exits; broadcast them so the loss
        # computes identically on every pp rank (analog of losses living on
        # the last stage only, schedules/common.py:297-320).
        return jax.tree_util.tree_map(lambda l: lax.psum(l, axis), outs)

    if params_already_local:
        return local_pipeline(stage_params, inputs)

    def reshape_chunk_major(l):
        # [pp*vpp, ...] virtual-stage major -> [vpp, pp, ...]: the pp dim
        # shards so device s holds rows (c, s) = virtual stages c*pp + s.
        return l.reshape((vpp, pp) + l.shape[1:])

    params_cm = jax.tree_util.tree_map(reshape_chunk_major, stage_params)

    from apex_tpu.parallel.collectives import shard_over

    in_spec_x = P(axis) if shard_microbatches else P()

    def build():
        return shard_over(
            local_pipeline,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(None, axis), params_cm),
                jax.tree_util.tree_map(lambda _: in_spec_x, inputs),
            ),
            out_specs=P(),
        )

    if group_size is None:
        return build()(params_cm, inputs)
    # jax.checkpoint inside shard_map cannot evaluate eagerly ("closed_call
    # inside shard_map"), so the grouped path needs a jit wrapper.  Wrapping
    # a fresh closure per call would defeat jit's cache, so memoize the
    # jitted program on everything its trace depends on.
    key = (stage_fn, mesh, axis, vpp, remat, group_size, shard_microbatches,
           _abstract_key(params_cm), _abstract_key(inputs))
    jitted = _GROUPED_JIT_CACHE.pop(key, None)  # pop+reinsert = LRU order
    if jitted is None:
        _note_cache_miss(stage_fn, key)
        if len(_GROUPED_JIT_CACHE) >= _GROUPED_JIT_CACHE_MAX:
            _GROUPED_JIT_CACHE.pop(next(iter(_GROUPED_JIT_CACHE)))
        jitted = jax.jit(build())
    _GROUPED_JIT_CACHE[key] = jitted
    return jitted(params_cm, inputs)


def forward_backward_no_pipelining(
    stage_fn: StageFn,
    loss_fn: LossFn,
    stage_params,
    inputs,
    targets,
    *,
    loss_scale=None,
    remat: bool = False,
    **_unused,
):
    """Microbatched grad accumulation without pipelining.

    Reference: ``fwd_bwd_no_pipelining.py:23-85`` — forward/backward per
    microbatch with grad sync deferred to the last one (the ``no_sync``
    context).  Under SPMD the deferral is automatic: the scan accumulates
    local grads and XLA inserts the data-parallel reduction once, afterwards.

    ``stage_fn(params, input) -> output``, ``loss_fn(output, target) ->
    scalar``; ``inputs``/``targets`` have leading microbatch dim ``m``.
    Returns ``(per_microbatch_losses, summed_grads)``; fold any ``1/m``
    averaging into ``loss_fn`` exactly as the reference folds it into
    ``loss_func`` (``schedules/common.py:297-320``).
    """
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def mb_loss(params, mb):
        inp, tgt = mb
        loss = loss_fn(fn(params, inp), tgt)
        scaled = loss if loss_scale is None else loss * loss_scale
        return scaled, loss

    grad_fn = jax.grad(mb_loss, has_aux=True)

    def step(acc, mb):
        g, loss = grad_fn(stage_params, mb)
        return jax.tree_util.tree_map(jnp.add, acc, g), loss

    zeros = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    grads, losses = lax.scan(step, zeros, (inputs, targets))
    return losses, grads


def _pipelined_fwd_bwd(stage_fn, loss_fn, stage_params, inputs, targets, *,
                       num_chunks, axis, mesh, loss_scale, remat,
                       remat_ticks=None):
    def total_loss(params):
        outs = pipeline_apply(
            stage_fn, params, inputs,
            num_chunks=num_chunks, axis=axis, mesh=mesh, remat=remat,
            remat_ticks=remat_ticks,
        )
        losses = jax.vmap(loss_fn)(outs, targets)
        total = jnp.sum(losses)
        if loss_scale is not None:
            total = total * loss_scale
        return total, losses

    grads, losses = jax.grad(total_loss, has_aux=True)(stage_params)
    return losses, grads


def forward_backward_pipelining_without_interleaving(
    stage_fn: StageFn,
    loss_fn: LossFn,
    stage_params,
    inputs,
    targets,
    *,
    axis: str = PIPELINE_AXIS,
    mesh: Optional[Mesh] = None,
    loss_scale=None,
    remat: bool = True,
    remat_ticks=None,
    **_unused,
):
    """1F1B-equivalent schedule
    (``fwd_bwd_pipelining_without_interleaving.py:241``); see module
    docstring.  Returns ``(losses[m], grads)`` with grads summed over
    microbatches (the reference's ``main_grad`` accumulation).
    ``remat_ticks`` opts into the 1F1B-class activation bound
    (grouped-tick remat, :func:`pipeline_apply`)."""
    return _pipelined_fwd_bwd(
        stage_fn, loss_fn, stage_params, inputs, targets,
        num_chunks=1, axis=axis, mesh=mesh, loss_scale=loss_scale, remat=remat,
        remat_ticks=remat_ticks,
    )


def forward_backward_pipelining_with_interleaving(
    stage_fn: StageFn,
    loss_fn: LossFn,
    stage_params,
    inputs,
    targets,
    *,
    num_chunks: int,
    axis: str = PIPELINE_AXIS,
    mesh: Optional[Mesh] = None,
    loss_scale=None,
    remat: bool = True,
    remat_ticks=None,
    **_unused,
):
    """Interleaved virtual-pipeline schedule
    (``fwd_bwd_pipelining_with_interleaving.py:27-744``).

    ``stage_params`` leading dim is ``pp * num_chunks`` in layer order
    (virtual-stage major): chunk ``c`` of stage ``s`` is row ``c*pp + s``,
    matching the reference's microbatch→chunk mapping (``:221-259``).
    """
    if num_chunks < 2:
        raise ValueError(
            "interleaved schedule requires num_chunks >= 2 (use "
            "forward_backward_pipelining_without_interleaving)"
        )
    return _pipelined_fwd_bwd(
        stage_fn, loss_fn, stage_params, inputs, targets,
        num_chunks=num_chunks, axis=axis, mesh=mesh, loss_scale=loss_scale,
        remat=remat, remat_ticks=remat_ticks,
    )


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: int = 1,
):
    """Dispatcher, ``schedules/__init__.py:22-35``."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return functools.partial(
                forward_backward_pipelining_with_interleaving,
                num_chunks=virtual_pipeline_model_parallel_size,
            )
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
