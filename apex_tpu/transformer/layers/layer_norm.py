"""Sequence-parallel-aware LayerNorm wrappers.

Behavioral spec: ``apex/transformer/layers/layer_norm.py`` — the reference
subclasses ``FusedLayerNorm``/``MixedFusedLayerNorm`` only to stamp a
``sequence_parallel`` attribute on weight/bias
(``_set_sequence_parallel_enabled:26``, classes ``:33,54``) so the DDP/grad
hooks later all-reduce those grads across the tensor-parallel group (SP
shards activations over ``tp``, but the LN params are replicated, so each
rank sees only its sequence shard's grad contribution).

Under SPMD the *primary* fix is structural, not a hook: pass replicated
params into ``shard_map`` with honest ``P()`` specs
(:mod:`apex_tpu.transformer.tensor_parallel.partition`) and the shard_map
transpose inserts the gradient psum itself.
:func:`allreduce_sequence_parallel_gradients` remains for reference-style
code that carries params as per-rank local trees inside one long-lived
``shard_map`` (where no spec describes them) — the direct analog of the
reference's backward hook.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
)
from apex_tpu.parallel.mesh import TENSOR_AXIS

__all__ = [
    "FastLayerNorm",
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
    "allreduce_sequence_parallel_gradients",
    "mark_sequence_parallel_params",
]

# ``FastLayerNorm`` (apex/contrib/layer_norm/layer_norm.py) is the tuned
# persistent-kernel variant of the same math; on TPU the fused path covers
# all hidden sizes, so it is the same module.
FastLayerNorm = FusedLayerNorm

_SP_PARAM_PATH_MARKERS = ("layernorm", "layer_norm", "norm")


def mark_sequence_parallel_params(path: str) -> bool:
    """True if a param path belongs to a replicated-norm param (the set the
    reference stamps with ``sequence_parallel=True``, ``layer_norm.py:26-52``)."""
    lowered = path.lower()
    return any(m in lowered for m in _SP_PARAM_PATH_MARKERS)


def allreduce_sequence_parallel_gradients(
    grads,
    axis: str = TENSOR_AXIS,
    is_sequence_parallel_param=None,
):
    """Sum replicated-param grads over the tensor axis under SP.

    The analog of the reference's backward grad hook for
    ``sequence_parallel``-flagged params: with activations sharded along the
    sequence dim over ``tp``, each rank's LN/bias grad covers only its
    sequence shard and must be summed (``layers.py:406-412`` discussion and
    ``layer_norm.py:26``).  Call inside the ``shard_map`` that bound
    ``axis``, after ``jax.grad``:

        grads = allreduce_sequence_parallel_gradients(grads)

    ``is_sequence_parallel_param(path_str) -> bool`` defaults to
    :func:`mark_sequence_parallel_params` (path contains a norm marker).
    """
    pred = is_sequence_parallel_param or mark_sequence_parallel_params

    def fix(path, g):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if pred(name):
            return lax.psum(g, axis)
        return g

    return jax.tree_util.tree_map_with_path(fix, grads)
