"""``apex_tpu.transformer.layers`` — reference ``apex/transformer/layers``."""

from apex_tpu.transformer.layers.layer_norm import (
    FastLayerNorm,
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    allreduce_sequence_parallel_gradients,
)

__all__ = [
    "FastLayerNorm",
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
    "allreduce_sequence_parallel_gradients",
]
