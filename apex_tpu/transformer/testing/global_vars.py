"""Global test-harness state: args + autoresume hook.

Behavioral spec: ``apex/transformer/testing/global_vars.py`` — global args
registry (``:89-140``) and the ADLR autoresume poller (``:75-87,158-166``)
that ``check_adlr_autoresume_termination``
(``pipeline_parallel/utils.py:142-143``) consults so preempted cluster
jobs checkpoint and requeue themselves.

TPU-first: ADLR's poller is NVIDIA-cluster-internal, so :class:`AutoResume`
generalizes the *protocol* — a termination signal (sentinel file or env
var, which is how Borg/GKE/SLURM preemption notices are commonly surfaced)
polled on an interval, plus the checkpoint-and-requeue hook.  The
:func:`check_autoresume_termination` helper mirrors the reference's call
shape: call it every iteration with your save callback.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Optional

__all__ = [
    "AutoResume",
    "get_autoresume",
    "set_autoresume",
    "check_autoresume_termination",
    "get_args",
    "set_args",
]

_GLOBAL_ARGS: Optional[Any] = None
_GLOBAL_AUTORESUME: Optional["AutoResume"] = None


def set_args(args) -> None:
    """Register harness args (reference ``set_global_variables``/_GLOBAL_ARGS)."""
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = args


def get_args():
    """Reference ``get_args`` (``global_vars.py:36``)."""
    if _GLOBAL_ARGS is None:
        raise RuntimeError("args not initialized; call set_args first")
    return _GLOBAL_ARGS


class AutoResume:
    """Preemption-notice poller (the ``AutoResume`` ADLR hook analog).

    Termination is requested when ``signal_file`` exists or
    ``signal_env`` is set to a truthy value; ``min_poll_interval``
    rate-limits filesystem checks exactly like the reference's
    ``termination_requested`` poller.
    """

    def __init__(self,
                 signal_file: Optional[str] = None,
                 signal_env: str = "APEX_TPU_AUTORESUME_TERMINATE",
                 min_poll_interval: float = 10.0):
        self.signal_file = signal_file or os.environ.get(
            "APEX_TPU_AUTORESUME_FILE")
        self.signal_env = signal_env
        self.min_poll_interval = min_poll_interval
        self._last_poll = 0.0
        self._cached = False

    def init(self) -> None:  # reference API shape (autoresume.init())
        self._last_poll = 0.0
        self._cached = False

    def termination_requested(self) -> bool:
        now = time.monotonic()
        if now - self._last_poll < self.min_poll_interval:
            return self._cached
        self._last_poll = now
        env_val = os.environ.get(self.signal_env, "").strip().lower()
        env_requested = env_val not in ("", "0", "false", "no", "off")
        self._cached = bool(
            env_requested
            or (self.signal_file and os.path.exists(self.signal_file)))
        return self._cached

    def request_resume(self) -> None:
        """Signal the scheduler to requeue (reference
        ``autoresume.request_resume()``).  Generic analog: remove the
        sentinel so the requeued job starts clean."""
        if self.signal_file and os.path.exists(self.signal_file):
            try:
                os.remove(self.signal_file)
            except OSError:
                pass


def set_autoresume(autoresume: Optional[AutoResume]) -> None:
    global _GLOBAL_AUTORESUME
    _GLOBAL_AUTORESUME = autoresume


def get_autoresume() -> Optional[AutoResume]:
    """Reference ``get_adlr_autoresume`` (``global_vars.py:75``)."""
    return _GLOBAL_AUTORESUME


def check_autoresume_termination(iteration: int,
                                 save_fn: Callable[[int], None]) -> bool:
    """Reference ``check_adlr_autoresume_termination``
    (``pipeline_parallel/utils.py:142-143`` / megatron training.py): when
    termination is requested, checkpoint via ``save_fn(iteration)``,
    request requeue, and return True so the training loop exits.

    Multi-process jobs agree on the decision first (the reference
    all-reduces the flag over ranks for the same reason): a preemption
    notice delivered to one host must stop *every* rank, or the others
    hang in their next collective.
    """
    ar = get_autoresume()
    local = bool(ar is not None and ar.termination_requested())
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        import numpy as np

        flags = multihost_utils.process_allgather(
            np.asarray([local], np.int32))
        decided = bool(np.asarray(flags).any())
    else:
        decided = local
    if not decided:
        return False
    save_fn(iteration)
    if ar is not None:
        ar.request_resume()
    return True
