"""Standalone Megatron-style transformer language model.

Behavioral spec: ``apex/transformer/testing/standalone_transformer_lm.py`` —
``ParallelMLP:165``, ``CoreAttention:213``, ``ParallelAttention:358``,
``ParallelTransformerLayer:598``, ``ParallelTransformer:780``,
``Embedding:1239``, ``TransformerLanguageModel:1358``,
``parallel_lm_logits:1130`` — the reference's production-shaped GPT/BERT used
by every distributed test and the GPT scaling harness.

TPU-first notes
---------------
- Configuration is one dataclass (:class:`TransformerConfig`) instead of the
  977-line Megatron argparser (``testing/arguments.py``) — SURVEY.md §5
  config-system note.  Field names follow the reference's args.
- Activations use the Megatron ``[s, b, h]`` layout so Megatron-style
  sequence parallelism (first-dim sharding,
  ``tensor_parallel/mappings.py:63-139``) applies unchanged.
- Tensor parallelism: modules take the mesh axis name; run the model inside
  ``shard_map`` with that axis bound (or ``tensor_model_parallel_size=1``
  for plain jit).  XLA inserts/overlaps the collectives the reference
  hand-schedules.
- Pipeline parallelism: :class:`ParallelTransformerLayer` is the homogeneous
  stage unit; stack per-layer params with
  :func:`~apex_tpu.transformer.pipeline_parallel.stack_stage_params` and
  drive them with :func:`~apex_tpu.transformer.pipeline_parallel.pipeline_apply`
  (embedding/head live outside the pipelined region — see
  ``standalone_gpt.py``).
- Modern-architecture options beyond the reference's testing GPT
  (parity-plus, from its Megatron lineage): RoPE / NoPE
  (``position_embedding_type``, ``transformer/rope.py``), grouped-query
  attention (``num_query_groups`` — group-major fused QKV so tp chops
  land on whole groups), and SwiGLU (``swiglu`` — separate gate/up
  column linears, TP-exact).  All compose with tp/sp/cp and the flash
  path; defaults reproduce the reference exactly.
- Dropout uses the flax ``"dropout"`` rng; pass seeds derived with
  :func:`apex_tpu.transformer.tensor_parallel.random.model_parallel_rng_key`
  so tp ranks decorrelate exactly like the reference's
  ``model_parallel_cuda_manual_seed`` (``random.py:204``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import AttnMaskType, FusedScaleMaskSoftmax
from apex_tpu.parallel.collectives import bound_axis_size
from apex_tpu.parallel.mesh import TENSOR_AXIS
from apex_tpu.transformer.enums import AttnType, LayerType
from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel import mappings
from apex_tpu.transformer.tensor_parallel.utils import divide

__all__ = [
    "TransformerConfig",
    "ParallelMLP",
    "CoreAttention",
    "ParallelAttention",
    "ParallelTransformerLayer",
    "ParallelTransformer",
    "Embedding",
    "TransformerLanguageModel",
    "parallel_lm_logits",
    "Pooler",
]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """The argparser surface the standalone LM consumes
    (``testing/arguments.py`` defaults), as a dataclass."""

    hidden_size: int = 128
    num_layers: int = 2
    num_attention_heads: int = 8
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    kv_channels: Optional[int] = None      # default hidden/heads
    padded_vocab_size: int = 1024
    max_position_embeddings: int = 512

    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    init_method_std: float = 0.02
    layernorm_epsilon: float = 1e-5

    apply_query_key_layer_scaling: bool = True
    attention_softmax_in_fp32: bool = False
    apply_residual_connection_post_layernorm: bool = False
    bias_gelu_fusion: bool = True
    masked_softmax_fusion: bool = True
    # Pallas flash attention for the causal core (no score matrix in HBM);
    # falls back to the fused-softmax path for padding masks / dropout.
    use_flash_attention: bool = False

    sequence_parallel: bool = False
    tensor_axis: Optional[str] = TENSOR_AXIS  # None = no tensor parallelism
    # Ring-decomposed collective matmul on every Column/Row parallel linear
    # (tensor_parallel/overlap.py): the SP all-gather/reduce-scatter is
    # pipelined under partial GEMMs, one collective-permute hop at a time,
    # forward and backward.  Only changes the schedule (and only where
    # sequence_parallel puts a collective on the layer); values and grads
    # match the monolithic path to fp32 tolerance.
    overlap_comm: bool = False
    # Context parallelism (ring attention over a cp mesh axis): activations
    # carry the LOCAL sequence shard [s/cp, b, h]; the causal core runs
    # :func:`apex_tpu.transformer.context_parallel.ring_attention`.  Run the
    # model inside shard_map with this axis bound (gpt_cp_train.py is the
    # worked harness).  Mutually exclusive with sequence_parallel
    # (validated in __post_init__); causal attention only (enforced in
    # CoreAttention).
    context_axis: Optional[str] = None
    # "ring" (K/V chunks rotate via ppermute; any head count) or "ulysses"
    # (all_to_all head<->sequence swap; needs heads % cp == 0, one a2a pair
    # instead of cp neighbor hops).
    context_impl: str = "ring"

    def __post_init__(self):
        if self.context_axis is not None and self.sequence_parallel:
            raise ValueError(
                "context_axis and sequence_parallel are mutually exclusive:"
                " both reinterpret the sequence dimension as sharded (over"
                " cp and tp respectively) and composing them would compute"
                " attention over a misread shard layout")
        if self.context_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"context_impl must be 'ring' or 'ulysses', got "
                f"{self.context_impl!r}")
        if self.position_embedding_type not in ("learned", "rope", "none"):
            raise ValueError(
                f"position_embedding_type must be 'learned', 'rope' or "
                f"'none', got {self.position_embedding_type!r}")
        if not 0.0 < self.rotary_percent <= 1.0:
            raise ValueError(
                f"rotary_percent must be in (0, 1], got "
                f"{self.rotary_percent} (use position_embedding_type="
                f"'none' for no position signal)")
        if (self.num_query_groups is not None
                and (self.num_query_groups <= 0
                     or self.num_attention_heads % self.num_query_groups)):
            raise ValueError(
                f"num_query_groups ({self.num_query_groups}) must be "
                f"positive and divide num_attention_heads "
                f"({self.num_attention_heads})")

    # Mixture-of-experts (parity-plus: the reference stubs SwitchMLP out,
    # standalone_transformer_lm.py:675; see apex_tpu/transformer/moe.py).
    num_experts: Optional[int] = None
    expert_capacity_factor: float = 1.25
    expert_axis: Optional[str] = None

    # --- modern-architecture options (parity-plus: the reference's testing
    # GPT is learned-positions/MHA/GeLU only; its Megatron lineage grew
    # RoPE/GQA/SwiGLU and this stack supports them across tp/sp/cp) ---
    # "learned" (reference behavior), "rope" (rotary on q/k, no position
    # table — see transformer/rope.py), or "none" (NoPE).
    position_embedding_type: str = "learned"
    rotary_base: float = 10000.0
    # fraction of head_dim rotated (Megatron --rotary-percent)
    rotary_percent: float = 1.0
    # Grouped-query attention: number of K/V head groups (None = MHA,
    # 1 = MQA).  Must divide num_attention_heads; under tensor
    # parallelism the tp world size must divide it (groups are
    # column-sharded alongside their query heads).
    num_query_groups: Optional[int] = None
    # LLaMA-style gated MLP: silu(gate(x)) * up(x) with separate gate/up
    # column linears (TP-exact under any tp size; ffn_hidden_size is NOT
    # auto-scaled by 2/3 — set it explicitly for iso-params).
    swiglu: bool = False

    dtype: Any = jnp.float32        # compute dtype (bf16 under the O2 policy)
    param_dtype: Any = jnp.float32

    # FP8 transformer-layer GEMMs (qkv / attention out / fc1 / fc2) via
    # :func:`apex_tpu.amp.fp8.fp8_matmul_t`: e4m3 operands with delayed
    # scaling, e5m2 just-in-time cotangents, amax pmax-shared over
    # ``tensor_axis`` (the reference's TE amax groups,
    # ``apex/transformer/parallel_state.py:280-291``).  The delayed scales
    # live in the mutable ``"fp8_meta"`` collection — train steps apply with
    # ``mutable=["fp8_meta"]`` and carry the collection forward (see
    # ``tests/test_fp8.py::test_fp8_gpt_trains``).  Embedding/LM head stay
    # in the compute dtype (the TE recipe).
    fp8: bool = False

    @property
    def ffn_size(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.kv_channels or divide(self.hidden_size,
                                          self.num_attention_heads)

    @property
    def query_groups(self) -> int:
        """K/V head groups (== num_attention_heads for MHA)."""
        return self.num_query_groups or self.num_attention_heads

    @property
    def rotary_dim(self) -> int:
        """Rotated leading channels of each head (even, >= 2)."""
        return max(2, int(self.head_dim * self.rotary_percent) // 2 * 2)

    def init_method(self):
        """``init_method_normal`` (reference ``:96-103``)."""
        return nn.initializers.normal(stddev=self.init_method_std)

    def scaled_init_method(self):
        """``scaled_init_method_normal`` — std/sqrt(2*num_layers) for
        output-facing weights (reference ``:105-114``)."""
        return nn.initializers.normal(
            stddev=self.init_method_std / math.sqrt(2.0 * self.num_layers)
        )


class ParallelMLP(nn.Module):
    """h → 4h (column, gelu) → h (row).  Reference ``ParallelMLP:165-212``:
    the first GEMM keeps its output sharded, bias+gelu fuse
    (``bias_gelu_fusion``), the second GEMM all-reduces (or
    reduce-scatters under SP)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h, bias = ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn_size,
            sequence_parallel=cfg.sequence_parallel,
            skip_bias_add=True,
            axis=cfg.tensor_axis,
            kernel_init=cfg.init_method(),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, fp8=cfg.fp8,
            overlap_comm=cfg.overlap_comm,
            name="dense_h_to_4h",
        )(x)
        if cfg.swiglu:
            # LLaMA-style gated MLP: a SEPARATE gate column linear (w1/w3
            # split) rather than one fused 2*ffn projection — the fused
            # form's gate/up split lands differently on each tp chop,
            # while two linears are TP-exact under any tp size.  XLA
            # fuses silu+multiply into one elementwise region between
            # the GEMMs.
            gate, gate_bias = ColumnParallelLinear(
                cfg.hidden_size, cfg.ffn_size,
                sequence_parallel=cfg.sequence_parallel,
                skip_bias_add=True,
                axis=cfg.tensor_axis,
                kernel_init=cfg.init_method(),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype, fp8=cfg.fp8,
                overlap_comm=cfg.overlap_comm,
                name="dense_h_to_4h_gate",
            )(x)
            h = jax.nn.silu(gate + gate_bias) * (h + bias)
        else:
            # bias_gelu fusion (reference fused_bias_gelu.py): one fused
            # elementwise region under XLA either way.
            h = jax.nn.gelu(h + bias, approximate=cfg.bias_gelu_fusion)
        out, out_bias = RowParallelLinear(
            cfg.ffn_size, cfg.hidden_size,
            input_is_parallel=True,
            sequence_parallel=cfg.sequence_parallel,
            skip_bias_add=True,
            axis=cfg.tensor_axis,
            kernel_init=cfg.scaled_init_method(),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, fp8=cfg.fp8,
            overlap_comm=cfg.overlap_comm,
            name="dense_4h_to_h",
        )(h)
        return out, out_bias


class CoreAttention(nn.Module):
    """Scaled-dot-product attention core, reference ``CoreAttention:213-357``:
    BMM1 → FusedScaleMaskSoftmax → attention dropout → BMM2, with
    query-key layer scaling (scores divided by an extra ``layer_number``
    factor, compensated inside the softmax scale — the fp16 overflow guard)."""

    config: TransformerConfig
    layer_number: int = 1
    attn_mask_type: AttnMaskType = AttnMaskType.padding

    @nn.compact
    def __call__(self, q, k, v, mask, deterministic: bool = True,
                 segment_ids=None):
        cfg = self.config
        # q/k/v: [s, b, n_local, d]
        sq, b, n, d = q.shape
        sk = k.shape[0]

        if (cfg.context_axis is not None
                and self.attn_mask_type != AttnMaskType.causal):
            # Falling through to the fused-softmax path would silently
            # attend within the local [s/cp] shard only.
            raise NotImplementedError(
                "context_axis supports causal self-attention only; "
                "non-causal attention over a cp-sharded sequence needs "
                "ulysses_attention (context_parallel.py) wired explicitly")
        if (cfg.context_axis is not None
                and self.attn_mask_type == AttnMaskType.causal):
            # Context parallelism: q/k/v hold this rank's sequence shard.
            from apex_tpu.transformer import context_parallel as cp_lib

            kw = {}
            if cfg.attention_dropout > 0.0 and not deterministic:
                if cfg.context_impl == "ring":
                    # in-kernel dropout is not plumbed through the ring
                    # VJP (kernels re-driven per visiting chunk); reject
                    # rather than silently skip it
                    raise NotImplementedError(
                        "attention_dropout under ring context parallelism "
                        "is not supported; use context_impl='ulysses' or "
                        "set attention_dropout=0.0")
                kw = dict(
                    dropout_rate=cfg.attention_dropout,
                    dropout_seed=jax.random.randint(
                        self.make_rng("dropout"), (), 0,
                        jnp.iinfo(jnp.int32).max),
                )
            attn = (cp_lib.ring_attention if cfg.context_impl == "ring"
                    else cp_lib.ulysses_attention)
            ctx = attn(
                q.transpose(1, 2, 0, 3), k.transpose(1, 2, 0, 3),
                v.transpose(1, 2, 0, 3), axis=cfg.context_axis, causal=True,
                **kw,
            )  # [b, n, sq_local, d]
            return ctx.transpose(2, 0, 1, 3).reshape(sq, b, n * d)

        # Flash handles the causal mask natively and *padding* masks via
        # segment ids ([b, s] ints: real tokens share an id, padding gets a
        # different one — the both-sides-real semantics of
        # ``bert_extended_attention_mask``); an arbitrary [b,1,sq,sk] mask
        # has no flash form and falls through to the fused-softmax path.
        use_flash = cfg.use_flash_attention and (
            self.attn_mask_type == AttnMaskType.causal
            or (self.attn_mask_type == AttnMaskType.padding
                and segment_ids is not None))
        if use_flash:
            from apex_tpu.ops.flash_attention import flash_attention
            if cfg.attention_dropout > 0.0 and not deterministic:
                # In-kernel counter-based dropout: derive a per-call scalar
                # seed from the flax "dropout" rng stream (the analog of
                # the reference's CUDA philox offsets).
                seed = jax.random.randint(
                    self.make_rng("dropout"), (), 0, jnp.iinfo(jnp.int32).max
                )
                drop = dict(dropout_rate=cfg.attention_dropout,
                            dropout_seed=seed)
            else:
                drop = {}
            if segment_ids is not None:
                drop.update(segment_ids_q=segment_ids,
                            segment_ids_kv=segment_ids)
            ctx = flash_attention(
                q.transpose(1, 2, 0, 3), k.transpose(1, 2, 0, 3),
                v.transpose(1, 2, 0, 3),
                causal=self.attn_mask_type == AttnMaskType.causal, **drop,
            )  # [b, n, sq, d]
            return ctx.transpose(2, 0, 1, 3).reshape(sq, b, n * d)

        norm_factor = math.sqrt(d)
        coeff = None
        if cfg.apply_query_key_layer_scaling:
            coeff = max(1, self.layer_number)
            norm_factor *= coeff

        # BMM1: [b*n, sq, sk] on the MXU, accumulating fp32.
        qt = q.transpose(1, 2, 0, 3).reshape(b * n, sq, d)
        kt = k.transpose(1, 2, 0, 3).reshape(b * n, sk, d)
        scores = jnp.matmul(
            qt, kt.transpose(0, 2, 1),
            preferred_element_type=jnp.float32,
        ) / norm_factor
        scores = scores.reshape(b, n, sq, sk).astype(
            jnp.float32 if cfg.attention_softmax_in_fp32 else cfg.dtype
        )

        softmax = FusedScaleMaskSoftmax(
            input_in_fp16=cfg.dtype == jnp.float16,
            input_in_bf16=cfg.dtype == jnp.bfloat16,
            attn_mask_type=self.attn_mask_type,
            scaled_masked_softmax_fusion=cfg.masked_softmax_fusion,
            mask_func=None,
            softmax_in_fp32=True,
            scale=coeff,
        )
        probs = softmax(scores, mask)
        probs = nn.Dropout(rate=cfg.attention_dropout)(
            probs, deterministic=deterministic
        )
        probs = probs.astype(cfg.dtype)

        # BMM2 → context [s, b, n_local*d]
        ctx = jax.lax.batch_matmul(
            probs.reshape(b * n, sq, sk),
            v.transpose(1, 2, 0, 3).reshape(b * n, sk, d),
        )
        ctx = ctx.reshape(b, n, sq, d).transpose(2, 0, 1, 3)
        return ctx.reshape(sq, b, n * d)


class ParallelAttention(nn.Module):
    """Self/cross attention with TP-sharded heads.

    Reference ``ParallelAttention:358-597``: fused QKV column linear
    (3*h out-sharded), core attention over the local heads, row-linear output
    projection with the residual-facing scaled init."""

    config: TransformerConfig
    layer_number: int = 1
    attention_type: AttnType = AttnType.self_attn
    attn_mask_type: AttnMaskType = AttnMaskType.padding

    def _maybe_rotary(self, q, k):
        """Rotate q/k (RoPE) when configured; no-op otherwise.  Runs
        BEFORE the GQA broadcast (rotating ``g_local`` K heads, not
        ``n_local`` copies) and before any cp exchange — under context
        parallelism the positions are this rank's *global* token indices
        (shard offset + local arange), so rotated keys travel the
        ring/all-to-all already position-stamped."""
        cfg = self.config
        if cfg.position_embedding_type != "rope":
            return q, k
        from apex_tpu.transformer.rope import apply_rotary, rotary_cos_sin

        s_local = q.shape[0]
        positions = jnp.arange(s_local)
        if cfg.context_axis is not None:
            positions = positions + (
                jax.lax.axis_index(cfg.context_axis) * s_local)
        cos, sin = rotary_cos_sin(positions, cfg.rotary_dim,
                                  cfg.rotary_base, q.dtype)
        return apply_rotary(q, cos, sin), apply_rotary(k, cos, sin)

    @nn.compact
    def __call__(self, x, mask, encoder_output=None, deterministic=True,
                 segment_ids=None):
        cfg = self.config
        world = bound_axis_size(cfg.tensor_axis)
        n_local = divide(cfg.num_attention_heads, world)
        d = cfg.head_dim
        proj = cfg.num_attention_heads * d

        if self.attention_type == AttnType.self_attn:
            # Fused QKV in GROUP-MAJOR layout: for each of the
            # ``query_groups`` K/V groups, its ``heads_per_group`` query
            # heads then its one K and one V head — so the column-parallel
            # chop hands every tp rank whole groups and the layout is
            # identical for any tp size dividing ``query_groups``.  MHA
            # (groups == heads) degenerates to the per-head [q|k|v]
            # triples this module always used.
            g = cfg.query_groups
            hpg = divide(cfg.num_attention_heads, g)
            g_local = divide(g, world)
            qkv = ColumnParallelLinear(
                cfg.hidden_size, (cfg.num_attention_heads + 2 * g) * d,
                sequence_parallel=cfg.sequence_parallel,
                axis=cfg.tensor_axis,
                kernel_init=cfg.init_method(),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype, fp8=cfg.fp8,
                overlap_comm=cfg.overlap_comm,
                name="query_key_value",
            )(x)
            s, b = qkv.shape[0], qkv.shape[1]
            qkv = qkv.reshape(s, b, g_local, (hpg + 2) * d)
            q = qkv[..., :hpg * d].reshape(s, b, n_local, d)
            k = qkv[..., hpg * d:(hpg + 1) * d]  # [s, b, g_local, d]
            v = qkv[..., (hpg + 1) * d:]
            q, k = self._maybe_rotary(q, k)
            if hpg > 1 and cfg.context_axis is None:
                # broadcast each K/V group across its query heads for the
                # single-rank flash/softmax cores (XLA fuses the repeat
                # into the operand read).  Under context parallelism the
                # grouped K/V passes through: ring/ulysses transfer the
                # compact g-head K/V over the interconnect and broadcast
                # locally per chunk (context_parallel._expand_kv) — the
                # GQA bandwidth saving is exactly the long-context win.
                k = jnp.repeat(k, hpg, axis=2)
                v = jnp.repeat(v, hpg, axis=2)
        else:
            q = ColumnParallelLinear(
                cfg.hidden_size, proj,
                sequence_parallel=cfg.sequence_parallel,
                axis=cfg.tensor_axis, kernel_init=cfg.init_method(),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype, fp8=cfg.fp8,
                overlap_comm=cfg.overlap_comm,
                name="query",
            )(x)
            kv = ColumnParallelLinear(
                cfg.hidden_size, 2 * proj,
                sequence_parallel=False, axis=cfg.tensor_axis,
                kernel_init=cfg.init_method(),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype, fp8=cfg.fp8,
                overlap_comm=cfg.overlap_comm,
                name="key_value",
            )(encoder_output)
            s, b = q.shape[0], q.shape[1]
            q = q.reshape(s, b, n_local, d)
            kv = kv.reshape(kv.shape[0], b, n_local, 2 * d)
            k, v = jnp.split(kv, 2, axis=-1)

        ctx = CoreAttention(
            cfg, layer_number=self.layer_number,
            attn_mask_type=self.attn_mask_type, name="core_attention",
        )(q, k, v, mask, deterministic=deterministic,
          segment_ids=segment_ids)

        out, bias = RowParallelLinear(
            proj, cfg.hidden_size,
            input_is_parallel=True,
            sequence_parallel=cfg.sequence_parallel,
            skip_bias_add=True,
            axis=cfg.tensor_axis,
            kernel_init=cfg.scaled_init_method(),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, fp8=cfg.fp8,
            overlap_comm=cfg.overlap_comm,
            name="dense",
        )(ctx)
        return out, bias


class ParallelTransformerLayer(nn.Module):
    """Pre-LN transformer block, reference ``ParallelTransformerLayer:598-779``:
    LN → attention → bias-dropout-residual → LN → MLP →
    bias-dropout-residual, with optional post-LN residual source
    (``apply_residual_connection_post_layernorm``)."""

    config: TransformerConfig
    layer_number: int = 1
    layer_type: LayerType = LayerType.encoder
    self_attn_mask_type: AttnMaskType = AttnMaskType.padding

    @nn.compact
    def __call__(self, x, mask, encoder_output=None, enc_dec_mask=None,
                 deterministic: bool = True, segment_ids=None):
        cfg = self.config
        ln1 = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon,
                             name="input_layernorm")(x)
        attn_out, attn_bias = ParallelAttention(
            cfg, layer_number=self.layer_number,
            attn_mask_type=self.self_attn_mask_type, name="self_attention",
        )(ln1, mask, deterministic=deterministic, segment_ids=segment_ids)
        residual = ln1 if cfg.apply_residual_connection_post_layernorm else x
        h = residual + nn.Dropout(rate=cfg.hidden_dropout)(
            attn_out + attn_bias, deterministic=deterministic
        )

        if self.layer_type == LayerType.decoder:
            ln_cross = FusedLayerNorm(
                cfg.hidden_size, eps=cfg.layernorm_epsilon,
                name="post_inter_attention_layernorm",
            )(h)
            cross_out, cross_bias = ParallelAttention(
                cfg, layer_number=self.layer_number,
                attention_type=AttnType.cross_attn,
                attn_mask_type=AttnMaskType.padding,
                name="inter_attention",
            )(ln_cross, enc_dec_mask, encoder_output=encoder_output,
              deterministic=deterministic)
            residual = (ln_cross
                        if cfg.apply_residual_connection_post_layernorm else h)
            h = residual + nn.Dropout(rate=cfg.hidden_dropout)(
                cross_out + cross_bias, deterministic=deterministic
            )

        ln2 = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon,
                             name="post_attention_layernorm")(h)
        if cfg.num_experts is not None:
            from apex_tpu.transformer.moe import SwitchMLP

            mlp_out, _aux = SwitchMLP(
                hidden_size=cfg.hidden_size, ffn_size=cfg.ffn_size,
                num_experts=cfg.num_experts,
                capacity_factor=cfg.expert_capacity_factor,
                expert_axis=cfg.expert_axis,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="mlp",
            )(ln2)
            # (1,)-shaped, NOT rank-0: this zero rides the gradient path
            # (mlp_out + mlp_bias), and under jax 0.4.x's old shard_map a
            # rank-0 value crossing the shard_map boundary in the
            # transposed (backward) program has no dimension to carry its
            # device-varying names — `_check_names` raises `_SpecError`
            # when the 3D trainer stages under `value_and_grad`.  The
            # singleton axis broadcasts identically and checks cleanly on
            # every jax version we shim.
            mlp_bias = jnp.zeros((1,), cfg.dtype)
        else:
            mlp_out, mlp_bias = ParallelMLP(cfg, name="mlp")(ln2)
        residual = ln2 if cfg.apply_residual_connection_post_layernorm else h
        return residual + nn.Dropout(rate=cfg.hidden_dropout)(
            mlp_out + mlp_bias, deterministic=deterministic
        )


class ParallelTransformer(nn.Module):
    """Layer stack + final LN, reference ``ParallelTransformer:780-1129``.

    ``post_process`` controls the final LayerNorm exactly like the
    reference's pipeline-stage flags; the per-layer loop is a Python loop
    (layers are distinct flax submodules with their own params — the
    pipelined path instead stacks layer params and uses ``pipeline_apply``).
    """

    config: TransformerConfig
    self_attn_mask_type: AttnMaskType = AttnMaskType.causal
    pre_process: bool = True
    post_process: bool = True

    @nn.compact
    def __call__(self, x, mask, deterministic: bool = True,
                 segment_ids=None):
        cfg = self.config
        for i in range(cfg.num_layers):
            x = ParallelTransformerLayer(
                cfg, layer_number=i + 1,
                self_attn_mask_type=self.self_attn_mask_type,
                name=f"layers_{i}",
            )(x, mask, deterministic=deterministic,
              segment_ids=segment_ids)
        if self.post_process:
            x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon,
                               name="final_layernorm")(x)
        return x


class Embedding(nn.Module):
    """Word (vocab-parallel) + learned position embeddings + dropout,
    reference ``Embedding:1239-1357``.  Output is ``[s, b, h]``; under SP the
    caller scatters the sequence dim
    (``scatter_to_sequence_parallel_region``)."""

    config: TransformerConfig
    add_position_embedding: bool = True

    # setup-style so ``word_embeddings`` is shareable for the tied LM head.
    def setup(self):
        cfg = self.config
        # rope/none position types carry no learned position table — the
        # position signal lives in the attention rotation (or nowhere)
        self._learned_positions = (self.add_position_embedding
                                   and cfg.position_embedding_type
                                   == "learned")
        self.word_embeddings = VocabParallelEmbedding(
            cfg.padded_vocab_size, cfg.hidden_size,
            axis=cfg.tensor_axis,
            embedding_init=cfg.init_method(),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
        )
        if self._learned_positions:
            self.position_embeddings = nn.Embed(
                cfg.max_position_embeddings, cfg.hidden_size,
                embedding_init=cfg.init_method(),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            )
        self.dropout = nn.Dropout(rate=cfg.hidden_dropout)

    def __call__(self, token_ids, position_ids=None, deterministic=True):
        cfg = self.config
        if position_ids is not None and not self._learned_positions:
            # RoPE derives positions inside the attention (arange +
            # cp-shard offset) and has no hook for caller ids yet;
            # dropping them silently would mis-rotate packed sequences.
            raise NotImplementedError(
                "custom position_ids are only honored with "
                "position_embedding_type='learned'; the rope path "
                "derives positions internally (packed-sequence resets "
                "are not yet supported under rope)")
        words = self.word_embeddings(token_ids)  # [b, s, h]
        if self._learned_positions:
            if position_ids is None:
                position_ids = jnp.arange(token_ids.shape[1])[None, :]
            words = words + self.position_embeddings(position_ids)
        x = words.transpose(1, 0, 2)  # [s, b, h] Megatron layout
        if cfg.sequence_parallel and bound_axis_size(cfg.tensor_axis) > 1:
            x = mappings.scatter_to_sequence_parallel_region(
                x, cfg.tensor_axis
            )
        return self.dropout(x, deterministic=deterministic)


class Pooler(nn.Module):
    """Tanh pooler over a sequence index, reference ``Pooler:1190-1238``."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, hidden, sequence_index: int = 0):
        cfg = self.config
        pooled = hidden[sequence_index]  # [b, h]
        return jnp.tanh(
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     kernel_init=cfg.init_method(), name="dense")(pooled)
        )


def parallel_lm_logits(hidden, word_embeddings, config: TransformerConfig,
                       bias=None):
    """LM head sharing the (vocab-sharded) embedding matrix.

    Reference ``parallel_lm_logits:1130-1189``: under SP first all-gather the
    sequence shards, then the column-parallel GEMM against the embedding
    table; output stays vocab-sharded for
    :func:`~apex_tpu.transformer.tensor_parallel.vocab_parallel_cross_entropy`.
    Input ``[s, b, h]`` → logits ``[s, b, vocab_local]``.
    """
    world = bound_axis_size(config.tensor_axis)
    if config.sequence_parallel and world > 1:
        hidden = mappings.gather_from_sequence_parallel_region(
            hidden, config.tensor_axis, True
        )
    elif world > 1:
        hidden = mappings.copy_to_tensor_model_parallel_region(
            hidden, config.tensor_axis
        )
    if hasattr(word_embeddings, "attend"):
        # Bound VocabParallelEmbedding module: tied-weight GEMM.
        logits = word_embeddings.attend(hidden)
    else:
        logits = jnp.einsum("sbh,vh->sbv", hidden,
                            jnp.asarray(word_embeddings, hidden.dtype))
    if bias is not None:
        logits = logits + bias
    return logits


class TransformerLanguageModel(nn.Module):
    """Embedding + transformer (+ pooler), reference
    ``TransformerLanguageModel:1358-1529``."""

    config: TransformerConfig
    self_attn_mask_type: AttnMaskType = AttnMaskType.causal
    add_pooler: bool = False

    def setup(self):
        cfg = self.config
        self.embedding = Embedding(cfg)
        self.encoder = ParallelTransformer(
            cfg, self_attn_mask_type=self.self_attn_mask_type
        )
        if self.add_pooler:
            self.pooler = Pooler(cfg)

    def __call__(self, token_ids, position_ids=None, attention_mask=None,
                 deterministic: bool = True, pooling_sequence_index: int = 0,
                 segment_ids=None):
        x = self.embedding(token_ids, position_ids,
                           deterministic=deterministic)
        hidden = self.encoder(x, attention_mask, deterministic=deterministic,
                              segment_ids=segment_ids)
        if self.add_pooler:
            pooled = self.pooler(hidden, pooling_sequence_index)
            return hidden, pooled
        return hidden
