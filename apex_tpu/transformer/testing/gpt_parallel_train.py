"""Full 3D-parallel GPT training step: dp × pp(×vpp) × tp(+sp).

The integration point of the whole runtime — the analog of the reference's
GPT pipeline test/production shape (``tests/L0/run_transformer/
test_pipeline_parallel_fwd_bwd.py``, ``gpt_scaling_test.py``): vocab/tensor-
parallel embedding and layers (``tp`` axis, Megatron sequence parallelism),
the rotation pipeline over ``pp`` with virtual chunks, data parallelism over
``dp``, vocab-parallel cross entropy, and a fused optimizer — all inside
ONE ``shard_map`` over the mesh, with *honest* per-leaf PartitionSpecs so
every gradient reduction (dp grad psum, SP replicated-param psum) is
inserted by the shard_map transpose rather than hand-written (see
:mod:`apex_tpu.transformer.tensor_parallel.partition`).

Layer-stack layout: per-layer params are stacked virtual-stage-major
``[L, ...]`` and reshaped to ``[vpp, pp, ...]`` so the ``pp`` dim shards
(chunk ``c`` of stage ``s`` = virtual stage ``c*pp + s`` — the interleaved
schedule's chunk mapping, ``fwd_bwd_pipelining_with_interleaving.py:221``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.softmax import AttnMaskType
from apex_tpu.parallel import collectives as cc
from apex_tpu.parallel.mesh import (
    DATA_AXIS,
    PIPELINE_AXIS,
    TENSOR_AXIS,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    pipeline_apply,
    split_into_microbatches,
)
from apex_tpu.transformer.tensor_parallel import infer_param_specs
from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
from apex_tpu.transformer.testing.standalone_gpt import gpt_next_token_loss
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    Embedding,
    ParallelTransformerLayer,
    TransformerConfig,
    parallel_lm_logits,
)

__all__ = ["GPT3DParams", "build_gpt_3d", "gpt3d_logical_folds"]


class GPT3DParams(NamedTuple):
    embedding: dict
    layers: dict      # stacked [vpp, pp, ...]
    final_ln: dict


def gpt3d_logical_folds(tree):
    """Fold-count pytree for :func:`apex_tpu.resilience.reshard.
    build_spec`: same structure as ``tree``, ``2`` on every leaf of a
    :class:`GPT3DParams` ``layers`` stack, ``0`` elsewhere.

    The layer stack is ``[vpp, pp, ...]`` — a plain reshape of the
    virtual-stage-major ``[L, ...]`` logical stack (chunk ``c`` of stage
    ``s`` is virtual stage ``c*pp + s``, so row-major merge/split IS the
    interleaved schedule's chunk mapping).  Annotating the two leading
    dims as one folded logical axis lets a checkpoint written at
    ``(vpp, pp) = (1, 2)`` restore onto ``(2, 1)`` — the tp/pp
    elastic-resume transition — by merging to ``[L]`` and re-splitting.
    Works on any pytree *containing* GPT3DParams nodes (the packed
    train state: params, a mirroring ``OptState``, sentinel state).
    """
    def mark(node):
        if isinstance(node, GPT3DParams):
            def const(sub, v):
                return jax.tree_util.tree_map(lambda _: v, sub)

            return GPT3DParams(embedding=const(node.embedding, 0),
                               layers=const(node.layers, 2),
                               final_ln=const(node.final_ln, 0))
        return 0

    return jax.tree_util.tree_map(
        mark, tree, is_leaf=lambda x: isinstance(x, GPT3DParams))


def _prepend(spec_tree, *dims):
    return jax.tree_util.tree_map(
        lambda s: P(*dims, *tuple(s)), spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def build_gpt_3d(
    config: TransformerConfig,
    *,
    num_chunks: int = 1,
    num_microbatches: int = 2,
    mesh=None,
    dp_axis: str = DATA_AXIS,
    pp_axis: str = PIPELINE_AXIS,
    tp_axis: str = TENSOR_AXIS,
    moe_aux_coeff: float = 1e-2,
    remat_ticks=None,
    packed_inputs: bool = False,
    block_diagonal: bool = False,
):
    """Return ``(init_fn, train_step, param_specs_fn)``.

    - ``init_fn(rng, sample_tokens) -> (params, param_specs)`` — global
      arrays with their PartitionSpec tree (params built under a tp-only
      shard_map so vocab/width shards initialize per-rank).
    - ``train_step(params, opt_state, tokens, opt) -> (params, opt_state,
      loss)`` — call under ``jax.jit``; internally one shard_map over
      (dp, pp, tp).

    ``config.num_layers`` must equal ``pp * num_chunks`` (one transformer
    layer per virtual stage); ``tokens: [global_batch, seq]`` sharded on dp.

    ``remat_ticks``: forward to :func:`pipeline_apply` for the 1F1B-class
    live-activation bound (grouped-tick remat); the train step must run
    under ``jax.jit`` (it should anyway).

    ``packed_inputs``: the real-data ingestion mode for
    :class:`~apex_tpu.data.sequence.PackedSequenceLoader` streams — the
    ``tokens`` argument of the loss/step becomes the loader's
    ``(tokens [b, s], segment_ids [b, s])`` pair (both dp-sharded), and
    the next-token loss is masked with
    :func:`~apex_tpu.data.sequence.segment_loss_mask` so no position
    predicts across a document boundary or into padding.  The loss
    becomes masked-sum / masked-count (accumulated across microbatches),
    and by default the attention stays plain causal (the standard packed
    pre-training trade).  Everything else — pipeline, sentinel,
    telemetry, collective budget — is unchanged.

    ``block_diagonal`` (requires ``packed_inputs`` and
    ``config.use_flash_attention``): close the packed trade — the
    per-microbatch segment ids ride the pipelined activation pytree
    (rotating with the microbatch they describe; int leaves carry no
    tangent, so the backward schedule is untouched) and feed the flash
    kernel's segment masking, so attention is **block-diagonal causal**
    — no position attends back into the previous document.  The fused
    softmax core has no segment mechanism (it would silently ignore
    them), hence the flash requirement.  Full-coverage segments (one
    document spanning the row) reproduce the plain-causal forward
    bitwise: the combined causal∧same-segment mask degenerates to the
    causal mask and the kernel arithmetic is unchanged
    (``tests/test_sequence_data.py``).
    """
    cfg = config
    if block_diagonal:
        if not packed_inputs:
            raise ValueError(
                "block_diagonal requires packed_inputs=True — the segment "
                "ids that define the blocks arrive with the packed batch")
        if not cfg.use_flash_attention:
            raise ValueError(
                "block_diagonal requires config.use_flash_attention: the "
                "fused-softmax attention core has no segment-mask "
                "mechanism and would silently ignore the ids")
    if mesh is None:
        from apex_tpu.parallel.mesh import get_mesh
        mesh = get_mesh()
    pp = mesh.shape[pp_axis]
    vpp = num_chunks
    if cfg.num_layers != pp * vpp:
        raise ValueError(
            f"num_layers ({cfg.num_layers}) != pp*vpp ({pp}*{vpp})"
        )

    embed = Embedding(cfg)
    layer = ParallelTransformerLayer(
        cfg, self_attn_mask_type=AttnMaskType.causal
    )
    final_ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon)

    def init_fn(rng, sample_tokens):
        mb_tokens = sample_tokens[: max(1, sample_tokens.shape[0]
                                        // num_microbatches)]

        def local_init(tokens):
            e = embed.init(rng, tokens)["params"]
            h = embed.apply({"params": e}, tokens)
            per_layer = [
                layer.init(jax.random.fold_in(rng, i), h, None)["params"]
                for i in range(cfg.num_layers)
            ]
            stacked = jax.tree_util.tree_map(
                lambda *ls: jnp.stack(ls), *per_layer
            )
            ln = final_ln.init(jax.random.fold_in(rng, 10_000), h)["params"]
            return e, stacked, ln

        shapes = jax.eval_shape(local_init, mb_tokens)
        ep_axis = cfg.expert_axis
        e_specs = infer_param_specs(shapes[0], axis=tp_axis)
        l_specs = _prepend(infer_param_specs(
            jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape[1:], l.dtype),
                shapes[1],
            ), axis=tp_axis, ep_axis=ep_axis
        ), None)  # [L, ...] replicated stack dim at init time
        ln_specs = jax.tree_util.tree_map(lambda _: P(), shapes[2])

        e, stacked, ln = cc.shard_over(
            local_init, mesh=mesh, in_specs=(P(),),
            out_specs=(e_specs, l_specs, ln_specs),
        )(mb_tokens)

        # [L, ...] virtual-stage major -> [vpp, pp, ...]; pp dim shards.
        stacked = jax.tree_util.tree_map(
            lambda l: l.reshape((vpp, pp) + l.shape[1:]), stacked
        )
        layer_specs = _prepend(infer_param_specs(
            jax.tree_util.tree_map(lambda l: l[0, 0], stacked),
            axis=tp_axis, ep_axis=cfg.expert_axis
        ), None, pp_axis)

        params = GPT3DParams(embedding=e, layers=stacked, final_ln=ln)
        specs = GPT3DParams(embedding=e_specs, layers=layer_specs,
                            final_ln=ln_specs)
        return params, specs

    def _local_loss(p: GPT3DParams, batch, with_aux: bool = False):
        """Mean LM loss of the local dp shard; runs with dp/pp/tp bound.
        With ``packed_inputs`` the batch is ``(tokens, segment_ids)`` and
        the mean is the segment-masked one (see :func:`build_gpt_3d`).

        Returns a ``(1,)``-shaped array, NOT a scalar: jax 0.4.x's
        old-style shard_map cannot name-check rank-0 values crossing the
        shard_map boundary under ``value_and_grad`` (scalar residual
        out-names trip ``_check_names`` with a ``_SpecError``; the
        promotion pass misses forwarded scalars), so every scalar on the
        loss tail keeps a singleton axis until outside the shard_map.

        ``with_aux=True`` (telemetry): returns ``(1 + m,)`` — the loss
        followed by the per-microbatch MoE aux vector, ``stop_gradient``
        -cut so the backward program is byte-for-byte the bare one.  Any
        collective the aux vector needs is the *widened* form of one the
        bare path already performs (never an extra op — the
        instrumented/bare HLO compare in tests/test_observability.py)."""
        if packed_inputs:
            tokens, segments = batch
            seg_mbs = split_into_microbatches(segments, num_microbatches)
        else:
            tokens = batch
        mbs = split_into_microbatches(tokens, num_microbatches)

        def embed_one(t):
            return embed.apply({"params": p.embedding}, t)

        h = jax.vmap(embed_one)(mbs)  # [m, s(/tp), mb, hid]
        # MoE aux loss rides the pipeline as a per-microbatch (1,)-shaped
        # slot in the activation pytree (stage output structure stays
        # homogeneous); dense configs carry a zero.  (1,) and not rank-0
        # per tick for the same _check_names reason as the loss below.
        aux0 = jnp.zeros((num_microbatches, 1), jnp.float32)

        if block_diagonal:
            # Segment ids ride the activation pytree so each microbatch's
            # ids rotate with its activations through the schedule (int32,
            # tangent-free — the transposed pipeline is unchanged); every
            # stage feeds them to the flash kernel's segment masking.
            def stage_fn(lp, xa):
                x, aux, seg = xa
                y, mut = layer.apply({"params": lp}, x, None,
                                     segment_ids=seg,
                                     mutable=["losses"])
                from apex_tpu.transformer.moe import collect_moe_aux

                return y, aux + collect_moe_aux(mut), seg

            out, aux_out, _ = pipeline_apply(
                stage_fn, p.layers, (h, aux0, seg_mbs), axis=pp_axis,
                num_chunks=vpp, params_already_local=True,
                remat_ticks=remat_ticks,
            )
        else:
            def stage_fn(lp, xa):
                x, aux = xa
                y, mut = layer.apply({"params": lp}, x, None,
                                     mutable=["losses"])
                from apex_tpu.transformer.moe import collect_moe_aux

                return y, aux + collect_moe_aux(mut)

            out, aux_out = pipeline_apply(
                stage_fn, p.layers, (h, aux0), axis=pp_axis,
                num_chunks=vpp, params_already_local=True,
                remat_ticks=remat_ticks,
            )

        def logits_of(hid):
            hid = final_ln.apply({"params": p.final_ln}, hid)
            return parallel_lm_logits(
                hid, p.embedding["word_embeddings"]["embedding"], cfg
            )

        if packed_inputs:
            from apex_tpu.data.sequence import segment_loss_mask

            def head_one(hid, t, seg):
                per_tok = gpt_next_token_loss(logits_of(hid), t, cfg)
                m = segment_loss_mask(seg)
                # (1,)-shaped like every scalar on the loss tail (the
                # old-shard_map _check_names constraint below)
                return (jnp.sum(per_tok * m).reshape(1),
                        jnp.sum(m).reshape(1))

            sums, counts = jax.vmap(head_one)(out, mbs, seg_mbs)
            # Leave the shard as [masked_sum, masked_count] — the
            # DIVISION happens outside the dp reduction (make_loss_fn):
            # a dp mean of per-shard ratios would equal-weight shards
            # whatever their real-token count, but mean-of-sums over
            # mean-of-counts is exactly global-sum/global-count (the dp
            # divisor cancels), so unevenly padded shards weigh by
            # their real tokens.
            ce = jnp.concatenate([jnp.sum(sums).reshape(1),
                                  jnp.maximum(jnp.sum(counts),
                                              1.0).reshape(1)])
        else:
            def head_one(hid, t):
                return jnp.mean(gpt_next_token_loss(logits_of(hid), t, cfg))

            losses = jax.vmap(head_one)(out, mbs)
            ce = jnp.mean(losses).reshape(1)
        # Telemetry rider: the per-microbatch aux vector is observational
        # only — stop_gradient keeps the differentiated subgraph (and so
        # the grads, bit for bit) identical to the bare path.  Dense
        # configs have no MoE aux: report zeros WITHOUT reading the
        # pipeline's aux carry — a dense bare step never consumes it, so
        # XLA DCEs its rotation ppermute, and reading it here would
        # resurrect a collective the bare step doesn't perform (the
        # instrumented/bare HLO compare in tests/test_observability.py).
        if not with_aux:
            aux_mb = None
        elif cfg.num_experts is not None:
            aux_mb = jax.lax.stop_gradient(
                aux_out.reshape(num_microbatches))
        else:
            aux_mb = jnp.zeros((num_microbatches,), jnp.float32)
        if cfg.num_experts is not None:
            aux_term = jnp.mean(aux_out).reshape(1)
            if cfg.tensor_axis is not None:
                # Under SP each tp rank routed a different sequence shard,
                # so its aux scalar differs; ce is tp-replicated (vocab-
                # parallel CE psums over tp) and the loss leaves this
                # shard_map with a replicated out-spec — average aux over
                # tp so the replication contract stays honest
                # (tensor_parallel/partition.py docstring).
                if with_aux:
                    # ONE tp reduction either way: the aux telemetry rides
                    # the existing (1,) pmean as extra payload (element 0
                    # is the same value bitwise — pmean is elementwise).
                    red = cc.all_reduce(
                        jnp.concatenate([aux_term, aux_mb]),
                        tp_axis, "mean")
                    aux_term, aux_mb = red[:1], red[1:]
                else:
                    aux_term = cc.all_reduce(aux_term, tp_axis, "mean")
            if packed_inputs:
                # packed ce is [sum, count] — the aux term cannot be
                # added to a sum; it rides out as a third element and is
                # composed after the division (make_loss_fn)
                ce = jnp.concatenate([ce, aux_term])
            else:
                ce = ce + moe_aux_coeff * aux_term
        if with_aux:
            return jnp.concatenate([ce, aux_mb])
        return ce

    def _batch_spec():
        """dp-sharded spec for the batch argument — a single tokens array,
        or the (tokens, segments) pair under ``packed_inputs``."""
        if packed_inputs:
            return (P(dp_axis), P(dp_axis))
        return P(dp_axis)

    def make_loss_fn(param_specs):
        """Global (dp-mean) loss over global arrays.

        ``jax.grad`` of THIS function is the supported way to train: the
        shard_map transpose then inserts every cross-rank gradient
        reduction — dp psum for all params, tp psum for SP-replicated
        norms/biases — because the specs tell the truth about replication
        (tensor_parallel/partition.py).  Taking grads *inside* the
        shard_map instead would silently drop the dp reduction.

        The loss leaves the shard_map body as a ``(1,)``-shaped array with
        an explicit replicated spec and is squeezed back to a scalar
        *outside*: jax 0.4.x's ``jax.experimental.shard_map`` partial-eval
        (staging under ``value_and_grad``) runs ``_check_names`` over the
        body's outputs and trips a ``_SpecError`` on a rank-0 residual
        out-name — a scalar output has no dimension to carry the vma
        names, while the ``(1,)`` form checks cleanly on every jax version
        we shim (new shard_map accepts both).
        """
        inner = cc.shard_over(
            lambda p, t: cc.all_reduce(
                _local_loss(p, t), dp_axis, "mean"),
            mesh=mesh,
            in_specs=(param_specs, _batch_spec()),
            out_specs=P(None),
        )

        def loss_fn(params, tokens):
            vec = inner(params, tokens)
            if not packed_inputs:
                return jnp.squeeze(vec, axis=0)
            # [sum, count(, aux_term)] dp-mean-reduced: mean-of-sums /
            # mean-of-counts IS global-sum/global-count (dp cancels) —
            # the exact masked mean, however unevenly padding lands
            loss = vec[0] / vec[1]
            if cfg.num_experts is not None:
                loss = loss + moe_aux_coeff * vec[2]
            return loss

        return loss_fn

    def make_aux_loss_fn(param_specs):
        """Telemetry variant of :func:`make_loss_fn`: returns
        ``loss_fn(params, tokens) -> (loss, aux_mb)`` with ``aux_mb``
        the dp-mean per-microbatch MoE aux vector ``[m]`` (zeros for
        dense configs), for ``jax.value_and_grad(..., has_aux=True)``.

        Same collective budget as the bare loss: the aux vector rides
        the existing dp pmean of the ``(1,)`` loss as a widened
        ``(1+m,)`` payload, and is ``stop_gradient``-cut inside — so the
        differentiated program (and the grads, bit for bit) is the bare
        one."""
        inner = cc.shard_over(
            lambda p, t: cc.all_reduce(
                _local_loss(p, t, with_aux=True), dp_axis, "mean"),
            mesh=mesh,
            in_specs=(param_specs, _batch_spec()),
            out_specs=P(None),
        )

        def loss_fn(params, tokens):
            vec = inner(params, tokens)
            if not packed_inputs:
                return vec[0], vec[1:]
            loss = vec[0] / vec[1]  # exact global masked mean (above)
            base = 2
            if cfg.num_experts is not None:
                loss = loss + moe_aux_coeff * vec[base]
                base += 1
            return loss, vec[base:]

        return loss_fn

    def make_train_step(opt, param_specs, scaler=None, grad_tap=None,
                        collect_stats=False):
        """``scaler=None``: the plain step.  With an ``amp`` scaler
        algorithm the unified non-finite sentinel
        (:mod:`apex_tpu.resilience.sentinel`) is threaded through: the
        loss is scaled, gradients overflow-checked (on the *global*
        grads, outside the shard_map — every rank sees the same flag),
        and the optimizer apply runs under one ``lax.cond`` so an
        overflow step leaves params and optimizer state bit-unchanged;
        ``sentinel.skipped_steps`` surfaces the skip count.  Signature
        becomes ``step(params, state, tokens, sentinel) -> (params,
        state, sentinel, loss)`` (loss reported unscaled).

        ``grad_tap`` (sentinel path only): a ``grads -> grads`` hook
        applied between the backward and the sentinel check — the seam
        the fault harness (:mod:`apex_tpu.testing.faults`) uses to
        inject non-finite gradients inside the compiled step.

        ``collect_stats`` appends a jit-carried
        :class:`apex_tpu.observability.PartialTrainStats` as the LAST
        output (loss, grad/param global-norm partials, non-finite leaf
        flags, loss scale, sentinel skip count, per-microbatch MoE aux).
        The params/grads here are SHARDED global arrays, so the norms
        leave the step as per-device partial sums
        (``ts.device_partial_norms`` — a shard_map whose output keeps
        the device axis, hence ZERO extra collectives; the host
        finalizes the tiny partials matrix at fetch time) and the aux
        vector rides the existing loss reductions
        (``make_aux_loss_fn``).  Zero host syncs; params and optimizer
        state stay bit-identical to the uninstrumented step (pinned by
        tests/test_observability.py)."""
        from apex_tpu.observability import trainstats as ts

        loss_fn = (make_aux_loss_fn(param_specs) if collect_stats
                   else make_loss_fn(param_specs))
        if collect_stats:
            partial_norms = ts.device_partial_norms(mesh, param_specs)

        if scaler is None:
            if not collect_stats:
                def step(params, state, tokens):
                    loss, grads = jax.value_and_grad(loss_fn)(
                        params, tokens)
                    new_p, new_state = opt.step(grads, state, params)
                    return new_p, new_state, loss

                return step

            def stats_step(params, state, tokens):
                (loss, aux_mb), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, tokens)
                new_p, new_state = opt.step(grads, state, params)
                stats = ts.partial_train_stats(
                    loss, partial_norms(grads, params), moe_aux=aux_mb)
                return new_p, new_state, loss, stats

            return stats_step

        from apex_tpu.resilience.sentinel import sentinel_guarded_apply

        def guarded_step(params, state, tokens, sent):
            scale_used = sent.scaler.scale

            if collect_stats:
                def scaled_loss(p, t):
                    loss, aux_mb = loss_fn(p, t)
                    return loss * scale_used, aux_mb

                (loss_s, aux_mb), grads = jax.value_and_grad(
                    scaled_loss, has_aux=True)(params, tokens)
            else:
                def scaled_loss(p, t):
                    return loss_fn(p, t) * scale_used

                loss_s, grads = jax.value_and_grad(scaled_loss)(
                    params, tokens)
            if grad_tap is not None:
                grads = grad_tap(grads)
            # grads here are GLOBAL arrays (the shard_map lives inside
            # loss_fn), so no cross-rank flag agreement is needed:
            # axes=None.
            new_p, new_state, new_sent = sentinel_guarded_apply(
                scaler, opt, grads, state, params, sent,
                grad_scale=scale_used)
            loss = loss_s / scale_used
            if not collect_stats:
                return new_p, new_state, new_sent, loss
            stats = ts.partial_train_stats(
                loss, partial_norms(grads, params), grad_scale=scale_used,
                loss_scale=scale_used,
                skipped_steps=new_sent.skipped_steps, moe_aux=aux_mb)
            return new_p, new_state, new_sent, loss, stats

        return guarded_step

    return init_fn, make_loss_fn, make_train_step
