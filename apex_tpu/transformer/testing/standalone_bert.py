"""Standalone BERT — reference ``apex/transformer/testing/standalone_bert.py``.

``BertModel``: bidirectional ``TransformerLanguageModel`` with pooler, tied
LM head (layernorm + embedding-tied logits) and binary (NSP) head; loss =
masked-LM CE + sentence-order CE (reference ``post_language_model_processing``
and ``bert_extended_attention_mask``).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.softmax import AttnMaskType
from apex_tpu.parallel.collectives import bound_axis_size
from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    TransformerConfig,
    TransformerLanguageModel,
    parallel_lm_logits,
)

__all__ = ["BertModel", "bert_extended_attention_mask"]


def bert_extended_attention_mask(attention_mask):
    """``[b, s]`` 1/0 padding mask → ``[b, 1, s, s]`` bool "masked-out" mask.

    Reference ``standalone_bert.py`` / megatron ``bert_model.py``: attend
    only where both query and key positions are real tokens; True = masked.
    """
    m = attention_mask.astype(bool)
    both = m[:, None, :, None] & m[:, None, None, :]
    return ~both


class BertLMHead(nn.Module):
    """Dense + gelu + LN, then embedding-tied logits (reference
    ``BertLMHead``)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, hidden, word_embeddings):
        cfg = self.config
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     kernel_init=cfg.init_method(), name="dense")(hidden)
        h = nn.gelu(h)
        h = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon,
                           name="layernorm")(h)
        # Bias is vocab-sharded like the embedding (reference sizes it to the
        # local shard, megatron bert_model.py mpu_vocab_size).
        world = bound_axis_size(cfg.tensor_axis)
        bias = self.param(
            "bias", nn.initializers.zeros,
            (cfg.padded_vocab_size // world,), cfg.param_dtype,
        )
        return parallel_lm_logits(h, word_embeddings, cfg, bias=bias)


class BertModel(nn.Module):
    """Bidirectional LM + pooler + LM/NSP heads (reference
    ``standalone_bert.py`` ``BertModel``)."""

    config: TransformerConfig
    add_binary_head: bool = True

    def setup(self):
        cfg = self.config
        self.language_model = TransformerLanguageModel(
            cfg, self_attn_mask_type=AttnMaskType.padding,
            add_pooler=self.add_binary_head,
        )
        self.lm_head = BertLMHead(cfg)
        if self.add_binary_head:
            self.binary_head = nn.Dense(
                2, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                kernel_init=cfg.init_method(),
            )

    def __call__(self, input_ids, attention_mask, position_ids=None,
                 deterministic: bool = True):
        ext_mask = bert_extended_attention_mask(attention_mask)
        # Under flash attention the [b,s] padding mask is expressed as
        # segment ids (real tokens id 0, padding id 1): attention is kept
        # only where both sides share an id — exactly ``ext_mask``'s
        # both-real semantics (padding rows attend only padding; their
        # outputs are ignored by the masked LM loss, as in the reference).
        seg = ((1 - attention_mask).astype(jnp.int32)
               if self.config.use_flash_attention else None)
        out = self.language_model(input_ids, position_ids, ext_mask,
                                  deterministic=deterministic,
                                  segment_ids=seg)
        hidden, pooled = out if self.add_binary_head else (out, None)
        lm_logits = self.lm_head(
            hidden, self.language_model.embedding.word_embeddings
        )
        binary_logits = None
        if self.add_binary_head:
            binary_logits = self.binary_head(pooled)
        return lm_logits, binary_logits
