"""Test/bench harness models — reference ``apex/transformer/testing``."""

from apex_tpu.transformer.testing.standalone_transformer_lm import (
    CoreAttention,
    Embedding,
    ParallelAttention,
    ParallelMLP,
    ParallelTransformer,
    ParallelTransformerLayer,
    Pooler,
    TransformerConfig,
    TransformerLanguageModel,
    parallel_lm_logits,
)
from apex_tpu.transformer.testing.standalone_gpt import (
    GPTModel,
    gpt_loss,
    gpt_next_token_loss,
    init_gpt_layer_stack,
)
from apex_tpu.transformer.testing.standalone_bert import (
    BertModel,
    bert_extended_attention_mask,
)

__all__ = [
    "TransformerConfig",
    "ParallelMLP",
    "CoreAttention",
    "ParallelAttention",
    "ParallelTransformerLayer",
    "ParallelTransformer",
    "Embedding",
    "Pooler",
    "TransformerLanguageModel",
    "parallel_lm_logits",
    "GPTModel",
    "gpt_loss",
    "gpt_next_token_loss",
    "init_gpt_layer_stack",
    "BertModel",
    "bert_extended_attention_mask",
]
