"""Standalone GPT — reference ``apex/transformer/testing/standalone_gpt.py``.

``GPTModel`` (reference ``:45``, wrapping ``TransformerLanguageModel`` with a
causal mask and ``post_language_model_processing``: logits against the shared
embedding + vocab-parallel cross entropy) plus the pipelined-stage helpers
the SPMD schedules need (see
:mod:`apex_tpu.transformer.pipeline_parallel.schedules` stage-homogeneity
note).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import AttnMaskType
from apex_tpu.parallel.collectives import bound_axis_size
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.transformer.tensor_parallel import vocab_parallel_cross_entropy
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    ParallelTransformerLayer,
    TransformerConfig,
    TransformerLanguageModel,
    parallel_lm_logits,
)

__all__ = ["GPTModel", "gpt_loss", "gpt_next_token_loss",
           "init_gpt_layer_stack"]


class GPTModel(nn.Module):
    """GPT LM: causal ``TransformerLanguageModel`` + embedding-tied logits.

    Forward returns per-token next-token loss ``[b, s-1]`` when
    ``labels`` is given, else logits ``[s, b, vocab(/tp)]``.

    Deliberate API divergence from the reference
    ``post_language_model_processing``: there the *data pipeline* pre-shifts
    labels; this framework has no mandatory data pipeline, so ``labels``
    are the **raw tokens** and the shift happens centrally in
    :func:`gpt_next_token_loss` — every caller (tests, bench, 3D trainer)
    gets the same non-degenerate objective.
    """

    config: TransformerConfig

    def setup(self):
        self.language_model = TransformerLanguageModel(
            self.config, self_attn_mask_type=AttnMaskType.causal
        )

    def __call__(self, input_ids, position_ids=None, attention_mask=None,
                 labels=None, deterministic: bool = True):
        cfg = self.config
        hidden = self.language_model(input_ids, position_ids, attention_mask,
                                     deterministic=deterministic)
        logits = parallel_lm_logits(
            hidden, self.language_model.embedding.word_embeddings, cfg
        )
        if labels is None:
            return logits
        return gpt_next_token_loss(logits, labels, cfg)


def gpt_next_token_loss(logits, tokens, config: TransformerConfig):
    """Shifted LM objective: position ``t`` predicts token ``t+1``.

    ``logits [s, b, v(/tp)]`` (full sequence — ``parallel_lm_logits`` has
    already gathered SP shards), ``tokens [b, s]`` raw; returns ``[b, s-1]``
    per-token losses.  Without the shift the objective is trivially
    learnable through the tied embedding (round-1 ADVICE).
    """
    return gpt_loss(logits[:-1], tokens[:, 1:], config)


def gpt_loss(logits, labels, config: TransformerConfig):
    """Per-token LM loss ``[b, s]`` from ``[s, b, v(/tp)]`` logits.

    Vocab-parallel CE under tensor parallelism
    (``tensor_parallel/cross_entropy.py:23-131``), fused max+logsumexp CE
    (``apex/contrib/xentropy``) otherwise.

    HBM-bandwidth note (the loss head is ~27 % of GPT-124M step FLOPs and
    its logits tensor is ~0.8 GB at the bench shapes): the big ``[s, b,
    v]`` tensor is flattened **in its native s-major order** — only the
    int32 labels and the fp32 per-token losses (both [b, s], KBs) get
    transposed — and half logits enter the CE kernel in their storage
    dtype (``half_to_float=True``; the kernel upcasts row-wise in fp32
    and keeps original-dtype residuals, ``ops/xentropy.py``).  Both are
    value-identical to transposing/upcasting first: the upcast point
    commutes with the row reductions, and row order commutes with a
    per-row loss."""
    v = logits.shape[-1]
    flat = logits.reshape(-1, v)            # [s*b, v] — no big transpose
    labels_sb = labels.T.reshape(-1)        # [b,s] -> [s*b] row order
    world = bound_axis_size(config.tensor_axis)
    if world > 1:
        loss = vocab_parallel_cross_entropy(flat, labels_sb,
                                            axis=config.tensor_axis)
    else:
        loss = softmax_cross_entropy_loss(
            flat,
            labels_sb,
            padding_idx=-1,  # no padding label in LM loss
            half_to_float=True,  # fp32 losses, half logits stay half
        )
    return loss.reshape(logits.shape[0], labels.shape[0]).T  # -> [b, s]


def init_gpt_layer_stack(key, config: TransformerConfig, sample_hidden,
                         sample_mask=None):
    """Init per-layer params for the pipelined GPT.

    Returns ``(make_stage_fn, per_layer_params_list)``.
    ``make_stage_fn(mask=None, deterministic=True, rngs=None)`` builds the
    homogeneous ``stage_fn(layer_params, x)`` the rotation schedule consumes
    — mask/dropout mode are bound per *call*, not frozen at init.

    The pipelined decomposition: embedding and the loss head run outside the
    rotation (replicated over ``pp``); the ``num_layers`` homogeneous
    :class:`ParallelTransformerLayer` blocks are the virtual stages.
    """
    cfg = config
    layer = ParallelTransformerLayer(
        cfg, self_attn_mask_type=AttnMaskType.causal
    )
    keys = jax.random.split(key, cfg.num_layers)
    per_layer = [
        layer.init(k, sample_hidden, sample_mask)["params"] for k in keys
    ]

    def make_stage_fn(mask=None, deterministic: bool = True, rngs=None):
        def stage_fn(layer_params, x):
            return layer.apply({"params": layer_params}, x, mask,
                               deterministic=deterministic, rngs=rngs)
        return stage_fn

    return make_stage_fn, per_layer
