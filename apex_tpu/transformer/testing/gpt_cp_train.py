"""Context-parallel GPT training: ring attention over a ``cp`` mesh axis.

The reference has no long-context training path at all (SURVEY §5: fused
softmax caps at 16384 keys, fmha at 512) — this harness is the
capability-parity-plus integration: the full standalone GPT stack
(:mod:`standalone_transformer_lm`) trains with its **sequence dimension
sharded over the cp axis**, the causal core running
:func:`~apex_tpu.transformer.context_parallel.ring_attention` (K/V chunks
rotating via ``ppermute``, ring-level custom VJP), composed with data
parallelism on the batch dimension.  Per-device activation memory is
O(seq/cp); total trainable context length scales linearly with the ring.

Cross-shard mechanics handled here (the parts a user would get wrong):

- **global position ids**: rank ``r`` embeds positions
  ``r*s_local + [0, s_local)``;
- **next-token labels across the shard boundary**: each rank's final
  position predicts the *next rank's first token*, fetched with one
  ``ppermute`` column rotation; the global last position has no target and
  is masked out of the loss;
- **loss normalization**: masked sum / count ``psum``-reduced over
  ``(dp, cp)`` so the scalar leaving the shard_map is truly replicated.

Numerics are parity-tested against the unsharded flash GPT in
``tests/test_gpt_cp.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.softmax import AttnMaskType
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.parallel import collectives as cc
from apex_tpu.parallel.mesh import CONTEXT_AXIS, DATA_AXIS
from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    Embedding,
    ParallelTransformerLayer,
    TransformerConfig,
    parallel_lm_logits,
)

__all__ = ["build_gpt_cp"]


def build_gpt_cp(
    config: TransformerConfig,
    *,
    mesh=None,
    dp_axis: str = DATA_AXIS,
    cp_axis: str = CONTEXT_AXIS,
):
    """Return ``(init_fn, make_loss_fn, make_train_step)``.

    ``config.context_axis`` must equal ``cp_axis`` (the causal core then
    runs ring attention on local shards) and ``tensor_axis`` must be None
    (cp x tp composition is Ulysses territory, not this harness).
    ``tokens: [global_batch, seq]`` — batch shards over dp, sequence over
    cp; ``seq`` must divide by the cp size and fit
    ``max_position_embeddings``.
    """
    cfg = config
    if cfg.context_axis != cp_axis:
        raise ValueError(
            f"config.context_axis ({cfg.context_axis!r}) must equal "
            f"cp_axis ({cp_axis!r})")
    if cfg.tensor_axis is not None:
        raise ValueError("context-parallel harness requires tensor_axis="
                         "None (use Ulysses for head-sharded attention)")
    if mesh is None:
        from apex_tpu.parallel.mesh import get_mesh
        mesh = get_mesh()

    embed = Embedding(cfg)
    layer = ParallelTransformerLayer(
        cfg, self_attn_mask_type=AttnMaskType.causal)
    final_ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon)

    def _local_forward(params, tokens_local):
        """Logits for this rank's [b_local, s_local] token shard."""
        s_local = tokens_local.shape[1]
        r = lax.axis_index(cp_axis)
        if cfg.position_embedding_type == "learned":
            # global position ids for this rank's sequence shard; under
            # rope the attention derives the same offsets itself
            # (ParallelAttention._maybe_rotary) and the embedding takes
            # no position argument
            pos = r * s_local + jnp.arange(s_local)[None, :]
        else:
            pos = None
        h = embed.apply({"params": params["embedding"]}, tokens_local,
                        position_ids=pos)  # [s_local, b, h]
        for i in range(cfg.num_layers):
            h = layer.apply(
                {"params": params[f"layer_{i}"]}, h, None)
        h = final_ln.apply({"params": params["final_ln"]}, h)
        return parallel_lm_logits(
            h, params["embedding"]["word_embeddings"]["embedding"], cfg)

    def _local_loss(params, tokens_local):
        cp = cc.axis_size(cp_axis)
        r = lax.axis_index(cp_axis)
        logits = _local_forward(params, tokens_local)  # [s_local, b, v]

        # Labels: shift within the shard; the final position's target is
        # the NEXT rank's first token (one ppermute column rotation).
        # Rank cp-1 receives rank 0's first token — a garbage target for
        # the global last position, masked below.
        first_col = tokens_local[:, :1]
        perm = [(i, (i - 1) % cp) for i in range(cp)]
        nxt = lax.ppermute(first_col, cp_axis, perm)
        labels = jnp.concatenate([tokens_local[:, 1:], nxt], axis=1)

        per_tok = softmax_cross_entropy_loss(
            jnp.transpose(logits, (1, 0, 2)).reshape(-1, logits.shape[-1])
            .astype(jnp.float32),
            labels.reshape(-1), padding_idx=-1,
        ).reshape(labels.shape)
        mask = jnp.ones_like(per_tok)
        mask = mask.at[:, -1].set(jnp.where(r == cp - 1, 0.0, 1.0))
        local_sum = jnp.sum(per_tok * mask)
        local_cnt = jnp.sum(mask)
        gsum = lax.psum(local_sum, (dp_axis, cp_axis))
        gcnt = lax.psum(local_cnt, (dp_axis, cp_axis))
        return gsum / gcnt

    def init_fn(rng, sample_tokens):
        """Params are replicated (no tp): init on one shard's shapes.

        Init traces outside shard_map (no cp axis bound), so it uses a
        serial twin of the layer (``context_axis=None``) — the attention
        core is parameterless, so the param structure is identical.
        """
        import dataclasses

        cfg_init = dataclasses.replace(cfg, context_axis=None)
        layer_init = ParallelTransformerLayer(
            cfg_init, self_attn_mask_type=AttnMaskType.causal)
        cp = mesh.shape[cp_axis]
        s_local = sample_tokens.shape[1] // cp
        t0 = sample_tokens[:1, :s_local]
        e = embed.init(rng, t0)["params"]
        h = embed.apply({"params": e}, t0)
        params = {"embedding": e}
        for i in range(cfg.num_layers):
            params[f"layer_{i}"] = layer_init.init(
                jax.random.fold_in(rng, i), h, None)["params"]
        params["final_ln"] = final_ln.init(
            jax.random.fold_in(rng, 10_000), h)["params"]
        specs = jax.tree_util.tree_map(lambda _: P(), params)
        return params, specs

    def make_loss_fn(param_specs):
        return cc.shard_over(
            _local_loss,
            mesh=mesh,
            in_specs=(param_specs, P(dp_axis, cp_axis)),
            out_specs=P(),
        )

    def make_train_step(opt, param_specs):
        loss_fn = make_loss_fn(param_specs)

        def step(params, state, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
            new_p, new_state = opt.step(grads, state, params)
            return new_p, new_state, loss

        return step

    return init_fn, make_loss_fn, make_train_step
