"""Mixture-of-Experts (Switch) MLP with expert parallelism.

Parity-plus: the reference *stubs* MoE out — ``standalone_transformer_lm.py:675``
asserts ``args.num_experts is None`` with the ``SwitchMLP`` call commented —
and SURVEY §2.5 lists expert parallelism as "absent in reference; optional
extension".  Long-context/distributed being first-class here, EP gets the
same treatment as the other strategies: experts shard over a mesh axis and
tokens move with one ``all_to_all`` each way (the standard TPU MoE
dispatch; the ``cp`` axis or the ``dp`` axis both work — whichever the
caller binds).

Routing is Switch-Transformer top-1 with capacity:

- router in fp32, top-1 expert + gate probability per token;
- capacity ``C = ceil(T/E * capacity_factor)`` per expert; overflow
  tokens are *dropped* (their MoE output is zero — the transformer's
  residual connection carries them, exactly Switch semantics);
- load-balancing aux loss ``E * Σ_e f_e·P_e`` (fraction routed × mean
  router prob), returned to the caller (the module form ``sow``s it into
  the ``"losses"`` collection as ``moe_aux``).

Expert-parallel dataflow (``expert_axis`` bound, ``E % ep == 0``): local
dispatch builds ``[E, C, h]``, one ``all_to_all`` regroups to
``[E/ep, ep*C, h]`` so each rank runs only its experts over everyone's
tokens, and the reverse ``all_to_all`` brings outputs home — numerically
identical to the dense path (tested).

Memory honesty: under EP the expert stacks are declared at their **local**
shape ``[E/ep, ...]`` (the same rank-folded-init convention as the
tensor-parallel linears), with init rng folded by ``axis_index`` so expert
groups decorrelate; ``infer_param_specs`` ships matching ``P(ep_axis)``
dim-0 specs, so parameters, gradients, and optimizer state all live 1/ep
per rank and expert grads are *not* psummed over the ep axis (each rank
owns its experts).  The router stays replicated.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel import collectives as cc

__all__ = ["SwitchMLP", "collect_moe_aux", "switch_route"]


def collect_moe_aux(mutated_collections) -> jnp.ndarray:
    """Sum every ``moe_aux`` value sown into the ``"losses"`` collection
    (one per MoE layer) — add ``coeff * collect_moe_aux(mut)`` to the
    training loss.  Returns 0.0 when no MoE layer ran."""
    from collections.abc import Mapping

    if not isinstance(mutated_collections, Mapping):
        raise TypeError(
            f"expected the mutated-collections mapping from "
            f"module.apply(..., mutable=['losses']), got "
            f"{type(mutated_collections).__name__}")
    losses = mutated_collections.get("losses", {})
    total = jnp.float32(0.0)
    for path, leaf in jax.tree_util.tree_leaves_with_path(losses):
        if any("moe_aux" in str(getattr(k, "key", k)) for k in path):
            total = total + jnp.sum(jnp.asarray(leaf))
    return total


def switch_route(logits32, capacity: int):
    """Top-1 Switch routing tensors from fp32 router logits ``[T, E]``.

    Returns ``(dispatch [T, E, C] bool, gate [T] f32, aux f32)``.
    """
    T, E = logits32.shape
    probs = jax.nn.softmax(logits32, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, E]
    # position of each token within its expert's queue (1-based)
    pos = jnp.cumsum(onehot, axis=0) * onehot
    keep = (pos > 0) & (pos <= capacity)
    cpos = jnp.clip(pos.astype(jnp.int32) - 1, 0, capacity - 1)
    dispatch = keep[:, :, None] & (
        cpos[:, :, None]
        == jnp.arange(capacity, dtype=jnp.int32)[None, None, :])

    # Switch load-balance loss: E * sum_e fraction_e * mean_prob_e
    fraction = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = E * jnp.sum(fraction * mean_prob)
    return dispatch, gate, aux


class SwitchMLP(nn.Module):
    """Switch-style MoE FFN block, drop-in for the dense MLP position.

    ``expert_axis``: mesh axis to shard experts over (``None`` = all
    experts local).  Experts are dense h→ffn→h MLPs with gelu (tensor
    parallelism *within* an expert is a composition left to the caller —
    Megatron's commented-out SwitchMLP wraps ParallelMLP the same way).
    Input/output ``[s, b, h]``; the aux loss is returned and also sown
    into the ``"losses"`` collection (key ``moe_aux``) — **add it to the
    training objective** (``~1e-2`` coefficient; Switch Transformer
    §2.2), e.g. via :func:`collect_moe_aux` on the mutated collections.

    Under EP the expert params are declared at local shape
    ``[E/ep, ...]`` — init must run inside the ``shard_map`` that binds
    ``expert_axis`` (the tensor-parallel rank-folded-init convention).
    """

    hidden_size: int
    ffn_size: int
    num_experts: int
    capacity_factor: float = 1.25
    expert_axis: Optional[str] = None
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        s, b, h = x.shape
        E = self.num_experts
        T = s * b
        capacity = max(1, int(-(-T * self.capacity_factor // E)))

        ep = cc.bound_axis_size(self.expert_axis)
        if E % ep:
            raise ValueError(
                f"num_experts ({E}) not divisible by expert-parallel "
                f"world ({ep})")
        e_local = E // ep

        def expert_init(base):
            # rank-folded init: each ep rank draws its own experts' weights
            def init(rng, shape, dtype):
                if ep > 1:
                    rng = jax.random.fold_in(
                        rng, cc.axis_index(self.expert_axis))
                return base(rng, shape, dtype)
            return init

        router = self.param("router", nn.initializers.normal(0.02),
                            (h, E), jnp.float32)
        w1 = self.param("w1", expert_init(nn.initializers.normal(0.02)),
                        (e_local, h, self.ffn_size), self.param_dtype)
        b1 = self.param("b1", nn.initializers.zeros,
                        (e_local, self.ffn_size), self.param_dtype)
        w2 = self.param("w2", expert_init(nn.initializers.normal(
            0.02 / (2 * E) ** 0.5)), (e_local, self.ffn_size, h),
            self.param_dtype)
        b2 = self.param("b2", nn.initializers.zeros, (e_local, h),
                        self.param_dtype)

        flat = x.reshape(T, h)
        logits = flat.astype(jnp.float32) @ router
        dispatch, gate, aux = switch_route(logits, capacity)
        dd = dispatch.astype(self.dtype)

        expert_in = jnp.einsum("tec,th->ech", dd,
                               flat.astype(self.dtype))  # [E, C, h]

        def one_expert(xe, w1e, b1e, w2e, b2e):
            hmid = jax.nn.gelu(xe @ w1e.astype(self.dtype)
                               + b1e.astype(self.dtype))
            return hmid @ w2e.astype(self.dtype) + b2e.astype(self.dtype)

        if ep > 1:
            # tokens -> expert owners: [E, C, h] -> [E/ep, ep*C, h]
            regroup = cc.all_to_all(expert_in, self.expert_axis,
                                    split_axis=0, concat_axis=1)
            out_local = jax.vmap(one_expert)(regroup, w1, b1, w2, b2)
            # outputs home: [E/ep, ep*C, h] -> [E, C, h]
            expert_out = cc.all_to_all(out_local, self.expert_axis,
                                       split_axis=1, concat_axis=0)
        else:
            expert_out = jax.vmap(one_expert)(expert_in, w1, b1, w2, b2)

        y = jnp.einsum("tec,ech->th", dd, expert_out)
        y = y * gate.astype(self.dtype)[:, None]
        self.sow("losses", "moe_aux", aux)
        return y.reshape(s, b, h), aux
