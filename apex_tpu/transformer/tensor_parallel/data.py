"""Tensor-parallel input-data broadcast.

Behavioral spec: ``apex/transformer/tensor_parallel/data.py`` —
``broadcast_data:80`` sends a dict of int64 tensors from tp-rank-0 to the
whole tensor-parallel group (with key/shape bookkeeping ``:34-78`` so
non-src ranks can allocate receive buffers).

Under SPMD there are no receive buffers to size — every rank already holds
an array of the right shape — so the shape plumbing disappears and the
broadcast is a masked psum from rank 0 over the tensor axis.  The semantic
content (guarantee all TP ranks see bit-identical batches even if their host
input pipelines drifted) is preserved.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

from apex_tpu.parallel import collectives
from apex_tpu.parallel.mesh import TENSOR_AXIS

__all__ = ["broadcast_data"]


def broadcast_data(
    keys,
    data: Dict[str, jnp.ndarray],
    datatype=jnp.int32,
    axis: Optional[str] = TENSOR_AXIS,
) -> Dict[str, jnp.ndarray]:
    """Broadcast ``data[k] for k in keys`` from tp-rank 0 to all tp ranks.

    The reference flattens all values into one int64 tensor for a single
    NCCL broadcast (``data.py:97-111``); XLA fuses the per-key broadcasts
    itself so we keep them separate.  ``datatype`` keeps the reference's
    signature; values are cast to it (the reference asserts instead,
    ``:89-94``).
    """
    out = {}
    for k in keys:
        v = jnp.asarray(data[k], datatype)
        if axis is not None:
            v = collectives.broadcast(v, axis, root=0)
        out[k] = v
    return out
