"""Tensor/sequence parallelism — rebuild of ``apex/transformer/tensor_parallel``.

Export surface mirrors ``apex/transformer/tensor_parallel/__init__.py:1-75``.
"""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    linear_with_grad_accumulation,
    parallel_init,
)
from apex_tpu.transformer.tensor_parallel.overlap import (
    gather_matmul,
    matmul_scatter,
)
from apex_tpu.transformer.tensor_parallel.partition import (
    DEFAULT_RULES,
    infer_param_specs,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.random import (
    RngStatesTracker,
    checkpoint,
    data_parallel_rng_key,
    get_rng_states_tracker,
    model_parallel_rng_key,
    model_parallel_seed,
)
from apex_tpu.transformer.tensor_parallel.utils import (
    VocabUtility,
    divide,
    ensure_divisibility,
    split_tensor_along_last_dim,
)

__all__ = [
    "vocab_parallel_cross_entropy",
    "DEFAULT_RULES",
    "infer_param_specs",
    "broadcast_data",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "linear_with_grad_accumulation",
    "parallel_init",
    "gather_matmul",
    "matmul_scatter",
    "copy_to_tensor_model_parallel_region",
    "gather_from_sequence_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "RngStatesTracker",
    "checkpoint",
    "data_parallel_rng_key",
    "get_rng_states_tracker",
    "model_parallel_rng_key",
    "model_parallel_seed",
    "VocabUtility",
    "divide",
    "ensure_divisibility",
    "split_tensor_along_last_dim",
]
