"""Model-parallel RNG policy and activation checkpointing.

Behavioral spec: ``apex/transformer/tensor_parallel/random.py`` —
``CudaRNGStatesTracker:124`` (named RNG states, ``fork():175``),
``model_parallel_cuda_manual_seed:204`` (tensor-parallel ranks get
``seed + 2718 + tp_rank`` for sharded params, the same ``seed`` for
replicated ones), and gradient checkpointing ``CheckpointFunction:237`` /
``checkpoint:308`` (recompute with the RNG states restored so dropout
patterns match).

JAX's counter-based PRNG dissolves most of this: there is no mutable device
RNG state to stash/restore — recompute under ``jax.checkpoint`` replays the
same fold-in chain, so dropout-in-recompute correctness (the entire reason
``CheckpointFunction`` saves RNG states, ``random.py:237-306``) holds by
construction.  What remains is the *seed-offset policy*: sharded params and
per-rank dropout must draw different streams per tensor-parallel rank,
replicated params the same stream.  ``model_parallel_rng_key`` implements
exactly that fold.

``init_checkpointed_activations_memory_buffer`` (``random.py:48``) —
pre-allocated activation stores with TP-partitioned checkpoints — has no
analog: ``jax.checkpoint`` policies decide what is saved and XLA allocates.
``checkpoint`` here forwards to ``jax.checkpoint`` with the reference's
``distribute_saved_activations`` expressed as a saveable-policy choice.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from apex_tpu.parallel.mesh import TENSOR_AXIS

__all__ = [
    "MODEL_PARALLEL_RNG_OFFSET",
    "model_parallel_rng_key",
    "data_parallel_rng_key",
    "RngStatesTracker",
    "get_rng_states_tracker",
    "model_parallel_seed",
    "checkpoint",
]

# The reference's fixed offset separating the model-parallel stream from the
# default stream (``random.py:222``: ``tensor_model_parallel_seed = offset +
# tensor_model_parallel_rank`` with ``offset = seed + 2718``).
MODEL_PARALLEL_RNG_OFFSET = 2718


def model_parallel_rng_key(key, axis: Optional[str] = TENSOR_AXIS):
    """Per-tensor-parallel-rank stream: fold tp-rank into ``key``.

    Use for sharded-param init and any dropout applied to tensor-parallel
    (sharded) activations — the ``model-parallel-rng`` fork
    (``random.py:230-235``).
    """
    if axis is None:
        return key
    key = jax.random.fold_in(key, MODEL_PARALLEL_RNG_OFFSET)
    return jax.random.fold_in(key, lax.axis_index(axis))


def data_parallel_rng_key(key, axis: str):
    """Per-data-parallel-rank stream (distinct dropout per replica batch)."""
    return jax.random.fold_in(key, lax.axis_index(axis))


class RngStatesTracker:
    """Named RNG streams — API parity with ``CudaRNGStatesTracker``
    (``random.py:124-202``).

    States are plain keys; ``fork`` returns the named key folded with a
    per-use counter instead of a context manager swapping device state.
    """

    def __init__(self):
        self._states = {}
        self._uses = {}

    def reset(self):
        self._states.clear()
        self._uses.clear()

    def get_states(self):
        return dict(self._states)

    def set_states(self, states):
        self._states = dict(states)
        self._uses = {k: 0 for k in self._states}

    def add(self, name: str, key):
        if name in self._states:
            raise RuntimeError(f"rng state {name} already exists")
        self._states[name] = key
        self._uses[name] = 0

    def fork(self, name: str = "model-parallel-rng"):
        if name not in self._states:
            raise RuntimeError(f"rng state {name} is not added")
        use = self._uses[name]
        self._uses[name] = use + 1
        return jax.random.fold_in(self._states[name], use)


_TRACKER = RngStatesTracker()


def get_rng_states_tracker() -> RngStatesTracker:
    """Analog of ``get_cuda_rng_tracker`` (``random.py:196``)."""
    return _TRACKER


def model_parallel_seed(seed: int, axis: Optional[str] = TENSOR_AXIS):
    """Analog of ``model_parallel_cuda_manual_seed`` (``random.py:204``).

    Returns the default (replicated) key and registers the model-parallel
    stream on the tracker.  Call inside ``shard_map``.
    """
    key = jax.random.PRNGKey(seed)
    _TRACKER.reset()
    _TRACKER.add("model-parallel-rng", model_parallel_rng_key(key, axis))
    return key


def checkpoint(fn, *args, use_reentrant: bool = True, policy=None, **kwargs):
    """Activation-checkpointed call — ``tensor_parallel.checkpoint``
    (``random.py:308-330``).

    ``policy`` is a ``jax.checkpoint_policies`` entry; the default (save
    nothing) matches the reference's full recompute.  The reference's
    ``distribute_saved_activations`` (partition the saved input across TP
    ranks, ``random.py:253-262``) corresponds to checkpointing with inputs
    saved sharded — under SPMD saved residuals inherit the sharding of the
    values themselves, so it needs no special handling.
    """
    del use_reentrant  # torch-ism; recompute is always functional here
    return jax.checkpoint(fn, policy=policy)(*args, **kwargs)
