"""Differentiable collective primitives for tensor/sequence parallelism.

Behavioral spec: ``apex/transformer/tensor_parallel/mappings.py`` — the
autograd Functions ``_CopyToModelParallelRegion`` / ``_ReduceFromModelParallelRegion``
/ ``_ScatterToModelParallelRegion`` / ``_GatherFromModelParallelRegion``
(last-dim, ``:143-211``) and the sequence-parallel first-dim family
``_ScatterToSequenceParallelRegion`` / ``_GatherFromSequenceParallelRegion``
/ ``_ReduceScatterToSequenceParallelRegion`` (``:213-273``), built on
``_reduce:31``, ``_split_along_last_dim:45``, ``_split_along_first_dim:63``,
``_gather_along_last_dim:83``, ``_gather_along_first_dim:103``,
``_reduce_scatter_along_first_dim:122``.

The reference hand-writes every forward/backward collective pair because
torch autograd knows nothing about process groups.  JAX's ``shard_map`` AD
*does* know: with the varying-manual-axes (vma) machinery, the transpose of
``psum`` is replication-aware, the transpose of ``all_gather`` is
``psum_scatter``, the transpose of a local dynamic-slice is assembled across
ranks — i.e. exactly the reference's pairs:

====================================  =========================  ==========================
reference autograd Function           forward here               JAX-derived backward
====================================  =========================  ==========================
``_CopyToModelParallelRegion``        identity                   all-reduce (at the
                                                                 replication boundary)
``_ReduceFromModelParallelRegion``    ``psum``                   identity/broadcast
``_ScatterToModelParallelRegion``     local slice (last dim)     all-gather
``_GatherFromModelParallelRegion``    ``all_gather`` (last dim)  local slice
``_ScatterToSequenceParallelRegion``  local slice (first dim)    all-gather
``_GatherFromSequenceParallelRegion`` ``all_gather`` (first)     reduce-scatter
``_ReduceScatterToSequenceParallel…`` ``psum_scatter`` (first)   all-gather
====================================  =========================  ==========================

so these are *plain functions*, verified gradient-exact against unsharded
references in ``tests/test_tensor_parallel.py``.  Hand-rolled ``custom_vjp``
collectives would double-count sums that ``shard_map`` already inserts when
transposing replicated inputs.

All functions must run where ``axis`` is a bound mesh axis name (inside
``shard_map``/``shard_over``).  NCCL is replaced by XLA collectives over
ICI/DCN; there is no stream management — XLA schedules and overlaps.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel import collectives as cc

from apex_tpu.parallel.mesh import TENSOR_AXIS

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
]


def _split_local(x, axis_name: str, dim: int):
    """Keep this rank's chunk of ``x`` along ``dim`` —
    ``_split_along_{last,first}_dim`` (``mappings.py:45,63``)."""
    n = cc.axis_size(axis_name)
    if n == 1:
        return x
    chunk = x.shape[dim] // n
    if chunk * n != x.shape[dim]:
        raise ValueError(
            f"dimension {dim} of size {x.shape[dim]} not divisible by "
            f"parallel size {n}"
        )
    idx = lax.axis_index(axis_name)
    starts = [0] * x.ndim
    sizes = list(x.shape)
    starts[dim] = idx * chunk
    sizes[dim] = chunk
    return lax.dynamic_slice(x, starts, sizes)


def copy_to_tensor_model_parallel_region(x, axis: str = TENSOR_AXIS):
    """Enter the tensor-parallel region: identity fwd, summed grads bwd.

    Reference ``copy_to_tensor_model_parallel_region`` (``mappings.py:276``).
    A no-op marker under shard_map — the gradient sum happens where the
    replicated value was produced; kept for API parity and readability.
    """
    del axis
    return x


def reduce_from_tensor_model_parallel_region(x, axis: str = TENSOR_AXIS):
    """Exit the tensor-parallel region: psum fwd, identity bwd.

    Reference ``reduce_from_tensor_model_parallel_region`` (``mappings.py:280``)
    — row-linear partial outputs summed to the full activation.
    """
    if cc.axis_size(axis) == 1:
        return x
    return lax.psum(x, axis)


def scatter_to_tensor_model_parallel_region(x, axis: str = TENSOR_AXIS):
    """Split last dim, keep local chunk; bwd = all-gather.

    Reference ``scatter_to_tensor_model_parallel_region`` (``mappings.py:284``).
    """
    return _split_local(x, axis, -1)


def gather_from_tensor_model_parallel_region(x, axis: str = TENSOR_AXIS):
    """All-gather along last dim; bwd = keep local chunk.

    Reference ``gather_from_tensor_model_parallel_region`` (``mappings.py:288``)
    — the ``gather_output=True`` path of column-parallel linear.
    """
    if cc.axis_size(axis) == 1:
        return x
    return lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)


def scatter_to_sequence_parallel_region(x, axis: str = TENSOR_AXIS):
    """Split sequence (first) dim, keep local chunk; bwd = all-gather.

    Reference ``scatter_to_sequence_parallel_region`` (``mappings.py:292``) —
    entering the SP region after the embedding.
    """
    return _split_local(x, axis, 0)


def gather_from_sequence_parallel_region(
    x, axis: str = TENSOR_AXIS, tensor_parallel_output_grad: bool = True
):
    """All-gather the sequence dim; bwd = reduce-scatter of partial grads.

    Reference ``gather_from_sequence_parallel_region`` (``mappings.py:296``).
    The reference needs the ``tensor_parallel_output_grad`` hint to decide
    reduce-scatter (partial-sum upstream grads) vs plain split (replicated
    upstream grads, ``mappings.py:238-252``) — JAX's vma-aware transpose
    makes that decision from the cotangent's replication type, so the flag is
    accepted for parity and ignored.
    """
    del tensor_parallel_output_grad
    if cc.axis_size(axis) == 1:
        return x
    return lax.all_gather(x, axis, axis=0, tiled=True)


def reduce_scatter_to_sequence_parallel_region(x, axis: str = TENSOR_AXIS):
    """Reduce-scatter along the sequence dim; bwd = all-gather.

    Reference ``reduce_scatter_to_sequence_parallel_region``
    (``mappings.py:300``) — the SP exit of row-parallel linear, replacing the
    all-reduce.
    """
    if cc.axis_size(axis) == 1:
        return x
    return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
