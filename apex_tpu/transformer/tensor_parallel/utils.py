"""Shape/partition utilities for tensor parallelism.

Behavioral spec: ``apex/transformer/tensor_parallel/utils.py`` (divisibility
asserts, ``split_tensor_along_last_dim``) and the vocab-range helper class
``VocabUtility`` (``apex/transformer/tensor_parallel/utils.py:55-80``).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

__all__ = [
    "ensure_divisibility",
    "divide",
    "split_tensor_along_last_dim",
    "VocabUtility",
]


def ensure_divisibility(numerator: int, denominator: int) -> None:
    """``apex/transformer/tensor_parallel/utils.py`` ``ensure_divisibility``."""
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Exact integer division (``utils.py`` ``divide``)."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(x, num_partitions: int) -> Tuple:
    """Split the last dimension into ``num_partitions`` equal chunks.

    Reference: ``split_tensor_along_last_dim`` (``utils.py``).  The
    ``contiguous_split_chunks`` flag is meaningless under XLA (no views).
    """
    last = x.shape[-1]
    divide(last, num_partitions)
    return tuple(jnp.split(x, num_partitions, axis=-1))


class VocabUtility:
    """Partition a vocabulary into contiguous per-rank ranges ``[fist, last)``.

    Reference: ``apex/transformer/tensor_parallel/utils.py:55-80``.
    """

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank
    ) -> Tuple:
        index_f = rank * per_partition_vocab_size
        return index_f, index_f + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(
        global_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        per_partition = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition, rank
        )
