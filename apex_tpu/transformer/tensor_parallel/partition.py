"""Parameter PartitionSpec inference — honest specs instead of grad hooks.

The reference keeps replicated parameters (LayerNorm weights, row-linear
biases) numerically consistent across tensor-parallel ranks by *stamping*
them ``sequence_parallel`` and all-reducing their grads in a backward hook
(``apex/transformer/layers/layer_norm.py:26-52``, ``tensor_parallel/
layers.py:757``).  Under SPMD that machinery dissolves: pass each param into
``shard_map`` with a spec that tells the truth — ``P()`` for replicated
leaves, ``P(axis)`` on the sharded dim for partitioned leaves — and the
shard_map transpose inserts the psum for replicated-leaf gradients itself.
Wrong specs (e.g. stacking replicated params as if sharded) silently skip
that psum and the ranks drift — exactly the bug class the reference's hooks
guard against.

:func:`infer_param_specs` builds the spec tree from path-pattern rules.
``DEFAULT_RULES`` covers the canonical module names of the standalone LM and
the tensor-parallel layers; models with custom names extend the rules (the
t5x/praxis "logical axis rules" pattern, TPU-idiomatic).
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import TENSOR_AXIS

__all__ = ["DEFAULT_RULES", "infer_param_specs"]

# (path regex, spec template) — template entries: "tp" marks the sharded dim.
# First match wins; no match = replicated.  Paths are "/".join of tree keys.
DEFAULT_RULES: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = (
    # vocab-parallel embedding table: [vocab/tp, h]
    (r"word_embeddings/embedding$", ("tp", None)),
    # column-parallel linears (QKV, h->4h, swiglu gate): kernel
    # [out/tp, in], bias [out/tp]
    (r"(query_key_value|query|key_value|dense_h_to_4h(_gate)?)/kernel$",
     ("tp", None)),
    (r"(query_key_value|query|key_value|dense_h_to_4h(_gate)?)/bias$",
     ("tp",)),
    # row-parallel linears (attention out, 4h->h): kernel [out, in/tp],
    # bias replicated (added after the reduction, layers.py:806-812).
    # NB: "dense" alone would also match the plain (replicated) pooler /
    # BertLMHead denses, so the attention projection is matched by its
    # parent module name.
    (r"(self_attention/dense|inter_attention/dense|dense_4h_to_h)/kernel$",
     (None, "tp")),
    # BERT LM head bias is vocab-sharded like the embedding
    (r"lm_head/bias$", ("tp",)),
    # Switch-MoE expert stacks (transformer/moe.py): dim 0 = local experts,
    # sharded over the expert-parallel axis ("ep" marker).  The router is
    # replicated (no rule).
    (r"mlp/w1$", ("ep", None, None)),
    (r"mlp/b1$", ("ep", None)),
    (r"mlp/w2$", ("ep", None, None)),
    (r"mlp/b2$", ("ep", None)),
)


def infer_param_specs(
    params,
    rules: Sequence[Tuple[str, Tuple[Optional[str], ...]]] = DEFAULT_RULES,
    axis: str = TENSOR_AXIS,
    ep_axis: Optional[str] = None,
):
    """PartitionSpec pytree for ``params`` from path-pattern ``rules``.

    Rule templates use the literal strings ``"tp"`` (tensor-parallel dim,
    substituted with ``axis``) and ``"ep"`` (expert-parallel dim,
    substituted with ``ep_axis``; dropped to replicated when ``ep_axis``
    is None).  Unmatched leaves are replicated (``P()``) — which is what
    makes their gradients correct under shard_map (see module docstring).
    """
    compiled = [(re.compile(pat), tpl) for pat, tpl in rules]

    def sub(t):
        if t == "tp":
            return axis
        if t == "ep":
            return ep_axis
        return t

    def spec_for(path, leaf):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        for pat, tpl in compiled:
            if pat.search(name):
                resolved = tuple(sub(t) for t in tpl)
                if len(resolved) > leaf.ndim:
                    raise ValueError(
                        f"rule {pat.pattern} spec {resolved} has more dims "
                        f"than param {name} with shape {leaf.shape}"
                    )
                return P(*resolved)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)
