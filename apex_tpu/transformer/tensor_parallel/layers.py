"""Tensor-parallel layers: vocab-parallel embedding, column/row linear.

Behavioral spec: ``apex/transformer/tensor_parallel/layers.py`` —
``VocabParallelEmbedding:174``, ``ColumnParallelLinear:460``,
``RowParallelLinear:645``, and the fused autograd function
``LinearWithGradAccumulationAndAsyncCommunication:279-437`` (sequence-parallel
all-gather of activations in forward; async all-reduce / reduce-scatter of the
input gradient overlapped with the weight-gradient GEMM; optional fused
wgrad accumulation into ``weight.main_grad``).

TPU-first notes:

- The reference's hand-rolled async overlap (dgrad collective started before
  the wgrad GEMM, ``layers.py:333-437``) is XLA's latency-hiding scheduler's
  job: both GEMMs and the collective appear in one fused backward computation
  and XLA overlaps them on the ICI DMA engines.  Nothing to hand-schedule.
- ``gradient_accumulation_fusion`` (wgrad accumulated straight into a
  persistent ``main_grad`` buffer) is donation: the optimizer's grad
  accumulator is a jit-carried buffer XLA updates in place — *measured*,
  not asserted: ``tests/test_wgrad_accum.py`` checks the compiled HLO's
  ``input_output_alias`` (in-place write into the donated accumulator),
  the alias-bytes accounting, and that scan-accumulation temp memory
  stays flat in the microbatch count.
- Weights follow the torch layout of the reference (``weight: [out, in]``,
  ``y = x @ w.T``) so checkpoints migrate 1:1; the *local* shard shapes match
  Megatron's partitioning (column: ``[out/tp, in]``, row: ``[out, in/tp]``).
- Modules run inside ``shard_map`` with the tensor axis bound (see
  :func:`apex_tpu.parallel.collectives.shard_over`).  Pass ``axis=None`` to
  get the degenerate single-rank layer.

Sharded-parameter init follows ``_initialize_affine_weight_gpu``
(``layers.py:137-172``): each rank draws from an independent stream — here
the flax RNG key folded with the rank (:func:`parallel_init`), the JAX analog
of the model-parallel RNG-tracker fork (``random.py:204-235``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.collectives import bound_axis_size
from apex_tpu.parallel.mesh import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel import mappings
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility, divide

__all__ = [
    "parallel_init",
    "linear_with_grad_accumulation",
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
]

Initializer = Callable[..., jax.Array]


def parallel_init(init_fn: Initializer, axis: Optional[str]) -> Initializer:
    """Wrap ``init_fn`` so each rank on ``axis`` draws independent values.

    The JAX analog of initializing the local shard under the
    ``model-parallel-rng`` tracker fork (``tensor_parallel/random.py:175``,
    used by ``_initialize_affine_weight_gpu`` ``layers.py:161``).
    """
    if axis is None:
        return init_fn

    def init(key, *args, **kwargs):
        key = jax.random.fold_in(key, lax.axis_index(axis))
        return init_fn(key, *args, **kwargs)

    return init


def _axis_size(axis: Optional[str]) -> int:
    # Degrades to the single-rank layer when the axis is not bound by an
    # enclosing shard_map — the same module code then runs single-device
    # (and jax.eval_shape can trace param structures outside the mesh).
    return bound_axis_size(axis)


def linear_with_grad_accumulation(
    x,
    weight,
    bias=None,
    *,
    sequence_parallel: bool = False,
    axis: Optional[str] = TENSOR_AXIS,
    fp8_metas=None,
    overlap_comm: bool = False,
):
    """``y = x @ w.T + b`` with optional SP all-gather of ``x``.

    Functional core of ``LinearWithGradAccumulationAndAsyncCommunication``
    (``layers.py:279-437``): under ``sequence_parallel`` the activation is
    all-gathered along the sequence (first) dim in forward and its gradient
    reduce-scattered in backward — exactly
    :func:`~apex_tpu.transformer.tensor_parallel.mappings.gather_from_sequence_parallel_region`
    with ``tensor_parallel_output_grad=True``.

    ``fp8_metas``: ``{"x": Fp8Meta, "w": Fp8Meta}`` — route the GEMM
    through :func:`apex_tpu.amp.fp8.fp8_matmul_t` (e4m3 operands, delayed
    scaling; e5m2 just-in-time cotangent).  The caller rolls the metas.

    ``overlap_comm``: replace the monolithic SP all-gather + GEMM with the
    ring-decomposed collective matmul
    (:func:`~apex_tpu.transformer.tensor_parallel.overlap.gather_matmul` —
    each ICI hop travels under a partial GEMM, forward and backward).
    """
    if sequence_parallel:
        if axis is None:
            raise ValueError("sequence_parallel requires a tensor axis")
        if overlap_comm:
            from apex_tpu.transformer.tensor_parallel.overlap import (
                gather_matmul,
            )

            y = gather_matmul(x, weight, axis, fp8_metas=fp8_metas)
            if bias is not None:
                y = y + bias
            return y
        x = mappings.gather_from_sequence_parallel_region(
            x, axis, True
        )
    if fp8_metas is not None:
        from apex_tpu.amp.fp8 import fp8_matmul_t

        y = fp8_matmul_t(x, weight, fp8_metas["x"], fp8_metas["w"])
    else:
        y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return y


class _Fp8MetaMixin:
    """Shared fp8 bookkeeping for the parallel linears: a mutable
    ``"fp8_meta"`` collection holding ``{"x", "w"}`` :class:`Fp8Meta`s, and
    the per-step delayed-scaling update with the amax ``pmax``-shared over
    the tensor axis (the reference's TE amax-sharing groups,
    ``apex/transformer/parallel_state.py:280-291``)."""

    def _fp8_metas(self):
        from apex_tpu.amp.fp8 import Fp8Meta

        return self.variable(
            "fp8_meta", "metas",
            lambda: {"x": Fp8Meta.init(), "w": Fp8Meta.init()})

    def _fp8_roll(self, metas, x_local, weight, axis_bound: bool):
        """Roll the delayed scales with this step's amaxes.  ``x_local`` may
        be the pre-all-gather sequence shard: its local amax ``pmax``-ed
        over the axis equals the gathered tensor's amax.

        Only rolls when the caller made the collection mutable (training
        steps pass ``mutable=["fp8_meta"]``); plain inference ``apply``
        runs with the stored scales frozen — the correct delayed-scaling
        eval semantics, and it keeps ``apply`` usable without threading
        state."""
        from apex_tpu.amp.fp8 import E4M3, update_meta

        if (self.is_initializing()
                or not self.is_mutable_collection("fp8_meta")):
            return
        axis = self.axis if axis_bound else None
        m = metas.value
        x_amax = jnp.max(jnp.abs(x_local)).astype(jnp.float32)
        w_amax = jnp.max(jnp.abs(weight)).astype(jnp.float32)
        metas.value = {
            "x": update_meta(m["x"], x_amax, E4M3, axis),
            "w": update_meta(m["w"], w_amax, E4M3, axis),
        }


class VocabParallelEmbedding(nn.Module):
    """Embedding sharded along the vocabulary dimension.

    Reference: ``apex/transformer/tensor_parallel/layers.py:174-277`` — each
    rank owns vocab range ``[rank*V/tp, (rank+1)*V/tp)``, out-of-range token
    ids are masked to 0, looked up locally, the masked rows zeroed, and the
    partial embeddings all-reduced.
    """

    num_embeddings: int
    embedding_dim: int
    axis: Optional[str] = TENSOR_AXIS
    embedding_init: Initializer = nn.initializers.normal(stddev=0.02)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    # setup-style (not @nn.compact) so the table is an attribute parents can
    # share for tied LM heads (``parallel_lm_logits`` weight tying,
    # standalone_transformer_lm.py:1130) via the flax setup-sharing pattern.
    def setup(self):
        world = _axis_size(self.axis)
        vocab_local = divide(self.num_embeddings, world)
        self.embedding = self.param(
            "embedding",
            parallel_init(self.embedding_init,
                          self.axis if world > 1 else None),
            (vocab_local, self.embedding_dim),
            self.param_dtype,
        )

    def __call__(self, token_ids):
        world = _axis_size(self.axis)
        vocab_local = divide(self.num_embeddings, world)
        weight = jnp.asarray(self.embedding, self.dtype)
        if world == 1:
            return jnp.take(weight, token_ids, axis=0)

        rank = lax.axis_index(self.axis)
        start, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
            vocab_local, rank
        )
        # Masked local lookup (layers.py:250-262): clamp out-of-range ids to
        # 0, zero their rows, then psum partials across the vocab shards.
        local_ids = token_ids - start
        in_range = (local_ids >= 0) & (local_ids < vocab_local)
        local_ids = jnp.where(in_range, local_ids, 0)
        out = jnp.take(weight, local_ids, axis=0)
        out = jnp.where(in_range[..., None], out, jnp.zeros_like(out))
        return mappings.reduce_from_tensor_model_parallel_region(out, self.axis)

    def attend(self, query):
        """Tied-head GEMM against the (vocab-sharded) table: ``[..., h] ->
        [..., vocab_local]`` — the core of ``parallel_lm_logits``."""
        weight = jnp.asarray(self.embedding, self.dtype)
        return jnp.matmul(query, weight.T)


class ColumnParallelLinear(nn.Module, _Fp8MetaMixin):
    """Linear with the output dimension sharded: ``W = [W_1 .. W_p]`` rows.

    Reference: ``ColumnParallelLinear`` (``layers.py:460-644``).  Forward
    semantics (``:609-641``):

    - ``sequence_parallel=True``: input is the local sequence shard; it is
      all-gathered along the sequence dim (and its grad reduce-scattered);
    - otherwise the input is replicated and passes through
      ``copy_to_tensor_model_parallel_region`` so its gradient is summed;
    - output is the local ``out/tp`` shard unless ``gather_output``.

    ``skip_bias_add`` returns the bias separately for downstream fusion
    (bias+gelu, bias+dropout+add) exactly like the reference (``:630-641``).
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    gather_output: bool = False
    sequence_parallel: bool = False
    skip_bias_add: bool = False
    axis: Optional[str] = TENSOR_AXIS
    kernel_init: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    fp8: bool = False  # e4m3/e5m2 GEMM with delayed scaling (fp8_matmul_t)
    # Ring-decomposed collective matmul: pipeline the SP all-gather under
    # partial GEMMs (overlap.gather_matmul).  Only affects the
    # sequence_parallel path — without SP there is no forward collective
    # on this layer to decompose.
    overlap_comm: bool = False

    @nn.compact
    def __call__(self, x):
        world = _axis_size(self.axis)
        out_local = divide(self.output_size, world)
        shard_axis = self.axis if world > 1 else None
        weight = self.param(
            "kernel",
            parallel_init(self.kernel_init, shard_axis),
            (out_local, self.input_size),
            self.param_dtype,
        )
        bias = (
            self.param(
                "bias",
                parallel_init(self.bias_init, shard_axis),
                (out_local,),
                self.param_dtype,
            )
            if self.use_bias
            else None
        )
        weight = jnp.asarray(weight, self.dtype)
        bias = None if bias is None else jnp.asarray(bias, self.dtype)

        if world > 1 and not self.sequence_parallel:
            x = mappings.copy_to_tensor_model_parallel_region(x, self.axis)
        fp8_metas = self._fp8_metas() if self.fp8 else None
        y = linear_with_grad_accumulation(
            x,
            weight,
            bias if not self.skip_bias_add else None,
            sequence_parallel=self.sequence_parallel and world > 1,
            axis=shard_axis,
            fp8_metas=None if fp8_metas is None else fp8_metas.value,
            overlap_comm=self.overlap_comm,
        )
        if fp8_metas is not None:
            self._fp8_roll(fp8_metas, x, weight, world > 1)
        if self.gather_output:
            if self.sequence_parallel:
                raise ValueError(
                    "gather_output is incompatible with sequence_parallel "
                    "(layers.py:578-582)"
                )
            if world > 1:
                y = mappings.gather_from_tensor_model_parallel_region(
                    y, self.axis
                )
        if self.skip_bias_add:
            return y, bias
        return y


class RowParallelLinear(nn.Module, _Fp8MetaMixin):
    """Linear with the input dimension sharded: ``W = [W_1; ..; W_p]`` cols.

    Reference: ``RowParallelLinear`` (``layers.py:645-813``).  Forward
    (``:777-812``): local GEMM on the input shard, then all-reduce of the
    partial outputs — or reduce-scatter along the sequence dim under
    ``sequence_parallel`` — and the (replicated) bias added after the
    reduction.
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel: bool = False
    skip_bias_add: bool = False
    axis: Optional[str] = TENSOR_AXIS
    kernel_init: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    fp8: bool = False  # e4m3/e5m2 GEMM with delayed scaling (fp8_matmul_t)
    # Ring-decomposed collective matmul: compute the SP reduce-scatter as
    # traveling partial-GEMM sums (overlap.matmul_scatter).  Only affects
    # the sequence_parallel path — the non-SP all-reduce exit is left to
    # XLA's own scheduling.
    overlap_comm: bool = False

    @nn.compact
    def __call__(self, x):
        world = _axis_size(self.axis)
        in_local = divide(self.input_size, world)
        shard_axis = self.axis if world > 1 else None
        weight = self.param(
            "kernel",
            parallel_init(self.kernel_init, shard_axis),
            (self.output_size, in_local),
            self.param_dtype,
        )
        # Bias is replicated and added after the reduction (layers.py:806-812)
        # — plain init, identical on every rank.
        bias = (
            self.param("bias", self.bias_init, (self.output_size,),
                       self.param_dtype)
            if self.use_bias
            else None
        )
        weight = jnp.asarray(weight, self.dtype)
        bias = None if bias is None else jnp.asarray(bias, self.dtype)

        if world > 1 and not self.input_is_parallel:
            if self.sequence_parallel:
                raise ValueError(
                    "sequence_parallel requires input_is_parallel "
                    "(layers.py:761-764)"
                )
            x = mappings.scatter_to_tensor_model_parallel_region(x, self.axis)
        fp8_metas = self._fp8_metas() if self.fp8 else None
        metas_val = None if fp8_metas is None else fp8_metas.value
        if self.sequence_parallel and self.overlap_comm and world > 1:
            # GEMM + reduce-scatter as one ring: partial sums travel the
            # ICI hops under the next partial GEMM (overlap.matmul_scatter)
            from apex_tpu.transformer.tensor_parallel.overlap import (
                matmul_scatter,
            )

            y = matmul_scatter(x, weight, self.axis, fp8_metas=metas_val)
        else:
            y = linear_with_grad_accumulation(
                x, weight, None, sequence_parallel=False, axis=shard_axis,
                fp8_metas=metas_val,
            )
            if world > 1:
                if self.sequence_parallel:
                    y = mappings.reduce_scatter_to_sequence_parallel_region(
                        y, self.axis
                    )
                else:
                    y = mappings.reduce_from_tensor_model_parallel_region(
                        y, self.axis
                    )
        if fp8_metas is not None:
            self._fp8_roll(fp8_metas, x, weight, world > 1)
        if self.skip_bias_add:
            return y, bias
        if bias is not None:
            y = y + bias
        return y
