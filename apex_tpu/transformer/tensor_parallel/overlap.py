"""Ring-decomposed collective matmul: overlap TP/SP collectives with GEMMs.

The monolithic sequence-parallel layers serialize communication against
computation: :class:`ColumnParallelLinear` all-gathers the sequence shards
*then* runs its GEMM, and :class:`RowParallelLinear` runs its GEMM *then*
reduce-scatters — ICI sits idle during the MXU work and vice versa.  This
module decomposes both into ``tp``-step rings so every step's
``collective-permute`` (one ICI neighbor hop) travels *under* a partial GEMM
the step does not depend on — the collective-matmul schedule of veScale
(arxiv 2509.07003) and TorchTitan's async TP (arxiv 2410.06511), and the
same overlap-first philosophy the ZeRO bucket pipeline applies on the data
axis.

- :func:`gather_matmul` computes ``all_gather(x, dim=0) @ w.T`` without ever
  materializing a monolithic all-gather: each step matmuls the
  currently-held sequence chunk against the full local weight shard while
  ``lax.ppermute`` rotates the next chunk one hop closer.
- :func:`matmul_scatter` computes ``reduce_scatter(x @ w.T, dim=0)`` as the
  transposed ring: each step adds one partial GEMM into an accumulator that
  travels the ring toward its home rank.

Both carry a custom VJP whose backward is the *matching transposed ring*
(``gather_matmul``'s input grad is a ``matmul_scatter``-shaped ring and vice
versa) rather than a monolithic collective, so the overlap survives
differentiation.  Per-chunk operand/cotangent products are pulled through
``jax.vjp`` of the underlying GEMM core, so the fp8 delayed-scaling path
(:func:`apex_tpu.amp.fp8.fp8_matmul_t` — e4m3 operands, e5m2 just-in-time
cotangents) composes without re-deriving its quantization math here; the
unused half of each pulled-back pair is dead-code-eliminated under jit.

Chunk bookkeeping: rank ``r`` starts holding chunk ``r``; rotation receives
from rank ``r+1``, so after ``t`` hops rank ``r`` holds chunk ``(r+t) % n``
(:func:`apex_tpu.parallel.collectives.ring_chunks` is the matching split).
The rings are Python-unrolled — ``tp`` is small and static — so the
compiled HLO carries ``n-1`` distinct ``collective-permute`` ops for XLA's
latency-hiding scheduler to sink under the neighboring dots.  Analyzer
rule APX201 (:mod:`apex_tpu.analysis`) asserts the decomposition survives
jit — ``tests/test_overlap_matmul.py``/``test_tensor_parallel.py`` and
``scripts/graph_lint.sh``'s ``overlap`` entry run the same check — and
APX202/APX104 validate the ring's ``ppermute`` permutations (a mismatch
is an ICI deadlock).

All functions run inside ``shard_map`` with ``axis`` bound, like the rest of
:mod:`~apex_tpu.transformer.tensor_parallel.mappings`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.observability.spans import named_span
from apex_tpu.parallel import collectives as cc
from apex_tpu.parallel.mesh import TENSOR_AXIS

__all__ = ["gather_matmul", "matmul_scatter"]


def _mm(x, w, metas):
    """The local GEMM core: ``x @ w.T`` (torch weight layout), routed
    through the fp8 delayed-scaling GEMM when metas are supplied."""
    if metas is None:
        return jnp.matmul(x, w.T)
    from apex_tpu.amp.fp8 import fp8_matmul_t

    return fp8_matmul_t(x, w, metas["x"], metas["w"])


def _mm_dx(g, x, w, metas):
    """Input cotangent of one chunk's ``_mm`` (``g @ w`` for the plain
    core; the e5m2 pullback for fp8).  The sibling weight-grad GEMM inside
    the pulled-back VJP is unused and DCE'd."""
    _, pull = jax.vjp(lambda xx: _mm(xx, w, metas), x)
    return pull(g)[0]


def _mm_dw(g, x, w, metas):
    """Weight cotangent of one chunk's ``_mm`` (``g.T @ x`` shaped
    ``[out, in]`` for the plain core)."""
    _, pull = jax.vjp(lambda ww: _mm(x, ww, metas), w)
    return pull(g)[0]


# Ring hops reuse the pipeline p2p helpers: cc.send_recv_prev receives
# from rank+1 (the held chunk index increases by one — the gather rings),
# cc.send_recv_next sends to rank+1 (the traveling-accumulator hop of the
# reduce-scatter rings).


def _gather_matmul_ring(x, w, metas, axis):
    """``all_gather(x, dim=0) @ w.T`` as an unrolled ring.

    Step ``t``: rank ``r`` holds chunk ``c = (r+t) % n``; the next chunk's
    ppermute is issued alongside the current chunk's GEMM (no data
    dependence between them — XLA overlaps the hop under the dot)."""
    n = cc.axis_size(axis)
    r = lax.axis_index(axis)
    cur, parts = x, []
    for t in range(n):
        # Chunk-step scope: in an xprof capture each ring step's hop +
        # partial GEMM group under one name, so the overlap (permute
        # sunk under the neighboring dot) is readable off the timeline.
        with named_span(f"ring/gather_matmul/step{t}"):
            nxt = cc.send_recv_prev(cur, axis) if t < n - 1 else None
            parts.append(((r + t) % n, _mm(cur, w, metas)))
            cur = nxt
    out = jnp.zeros((n,) + parts[0][1].shape, parts[0][1].dtype)
    for c, p in parts:
        out = lax.dynamic_update_index_in_dim(out, p, c, 0)
    return out.reshape((n * x.shape[0],) + out.shape[2:])


def _matmul_scatter_ring(x, w, metas, axis):
    """``reduce_scatter(x @ w.T, dim=0)`` as an unrolled ring.

    The accumulator travels toward rank+1; at step ``t`` rank ``r`` holds
    the partial sum destined for chunk ``d = (r + n-1-t) % n`` and adds its
    local partial GEMM for that chunk — after the remaining ``n-1-t`` hops
    the sum lands home with every rank's contribution folded in.  The hop
    is issued before the GEMM it overlaps with (the GEMM reads only local
    data)."""
    n = cc.axis_size(axis)
    r = lax.axis_index(axis)
    xc = cc.ring_chunks(x, n, 0)
    acc = None
    for t in range(n):
        with named_span(f"ring/matmul_scatter/step{t}"):
            if t:
                acc = cc.send_recv_next(acc, axis)
            d = (r + n - 1 - t) % n
            part = _mm(lax.dynamic_index_in_dim(xc, d, 0, keepdims=False),
                       w, metas)
            acc = part if acc is None else acc + part
    return acc


# --- gather_matmul -------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _gather_matmul(axis, x, w, metas):
    return _gather_matmul_ring(x, w, metas, axis)


def _gather_matmul_fwd(axis, x, w, metas):
    return _gather_matmul_ring(x, w, metas, axis), (x, w, metas)


def _gather_matmul_bwd(axis, res, dy):
    """Transposed rings, no monolithic collective.

    ``dx`` is the matmul-scatter ring over the (local) cotangent chunks:
    rank ``r``'s weight shard contributes ``dmm_x(dy_d)`` to every sequence
    chunk ``d``, and the partial sums travel home.  ``dw`` re-rotates the
    saved activation chunks (the forward's ring, re-driven — each rank's
    cotangent is local, so its weight grad needs no cross-rank reduction).
    """
    x, w, metas = res
    n = cc.axis_size(axis)
    r = lax.axis_index(axis)
    dyc = cc.ring_chunks(dy, n, 0)

    acc = None
    for t in range(n):
        with named_span(f"ring/gather_matmul_bwd_dx/step{t}"):
            if t:
                acc = cc.send_recv_next(acc, axis)
            d = (r + n - 1 - t) % n
            g_d = lax.dynamic_index_in_dim(dyc, d, 0, keepdims=False)
            part = _mm_dx(g_d, x, w, metas)
            acc = part if acc is None else acc + part
    dx = acc

    cur, dw = x, None
    for t in range(n):
        with named_span(f"ring/gather_matmul_bwd_dw/step{t}"):
            c = (r + t) % n
            nxt = cc.send_recv_prev(cur, axis) if t < n - 1 else None
            g_c = lax.dynamic_index_in_dim(dyc, c, 0, keepdims=False)
            part = _mm_dw(g_c, cur, w, metas)
            dw = part if dw is None else dw + part
            cur = nxt
    return dx, dw, None


_gather_matmul.defvjp(_gather_matmul_fwd, _gather_matmul_bwd)


def gather_matmul(x, w, axis: Optional[str] = TENSOR_AXIS, *, fp8_metas=None):
    """``all_gather(x, dim=0) @ w.T`` with the gather pipelined under the
    partial GEMMs (and the transposed ring as backward).

    ``x``: the local sequence shard ``[s_local, ..., in]``; ``w``: the full
    local weight shard ``[out_local, in]`` (torch layout).  Returns
    ``[s_local * tp, ..., out_local]`` — exactly the sequence-parallel
    :class:`ColumnParallelLinear` forward.  ``fp8_metas``
    (``{"x", "w"}`` :class:`~apex_tpu.amp.fp8.Fp8Meta`) routes each partial
    GEMM through the fp8 core; per-tensor delayed scales commute with
    sequence chunking, so the quantized values match the monolithic path.
    Degenerates to one local GEMM when ``axis`` is ``None`` or unbound.
    """
    if axis is None or cc.bound_axis_size(axis) == 1:
        return _mm(x, w, fp8_metas)
    return _gather_matmul(axis, x, w, fp8_metas)


# --- matmul_scatter ------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _matmul_scatter(axis, x, w, metas):
    return _matmul_scatter_ring(x, w, metas, axis)


def _matmul_scatter_fwd(axis, x, w, metas):
    return _matmul_scatter_ring(x, w, metas, axis), (x, w, metas)


def _matmul_scatter_bwd(axis, res, dy):
    """One shared ring serves both grads: the cotangent shard rotates
    (the transposed all-gather), and at each step its visiting chunk feeds
    the input grad for that sequence chunk *and* this rank's weight-grad
    partial — ``n-1`` hops total for the whole backward."""
    x, w, metas = res
    n = cc.axis_size(axis)
    r = lax.axis_index(axis)
    xc = cc.ring_chunks(x, n, 0)

    cur, dx_parts, dw = dy, [], None
    for t in range(n):
        with named_span(f"ring/matmul_scatter_bwd/step{t}"):
            c = (r + t) % n
            nxt = cc.send_recv_prev(cur, axis) if t < n - 1 else None
            x_c = lax.dynamic_index_in_dim(xc, c, 0, keepdims=False)
            # One joint pullback per step: both cotangents of the same
            # (chunk, weight) GEMM come from a single linearization.
            _, pull = jax.vjp(lambda xx, ww: _mm(xx, ww, metas), x_c, w)
            dx_c, dw_c = pull(cur)
            dx_parts.append((c, dx_c))
            dw = dw_c if dw is None else dw + dw_c
            cur = nxt
    dx = jnp.zeros((n,) + dx_parts[0][1].shape, dx_parts[0][1].dtype)
    for c, p in dx_parts:
        dx = lax.dynamic_update_index_in_dim(dx, p, c, 0)
    return dx.reshape(x.shape), dw, None


_matmul_scatter.defvjp(_matmul_scatter_fwd, _matmul_scatter_bwd)


def matmul_scatter(x, w, axis: Optional[str] = TENSOR_AXIS, *,
                   fp8_metas=None):
    """``reduce_scatter(x @ w.T, dim=0)`` with the scatter pipelined as
    traveling partial sums (and the transposed ring as backward).

    ``x``: the full-sequence input-sharded activation
    ``[s_local * tp, ..., in_local]``; ``w``: ``[out, in_local]``.  Returns
    the local sequence shard ``[s_local, ..., out]`` of the summed output —
    exactly the sequence-parallel :class:`RowParallelLinear` forward
    (bias, replicated, is added by the caller *after* the reduction).
    Degenerates to one local GEMM when ``axis`` is ``None`` or unbound.
    """
    if axis is None or cc.bound_axis_size(axis) == 1:
        return _mm(x, w, fp8_metas)
    return _matmul_scatter(axis, x, w, fp8_metas)
