"""Vocab-parallel softmax cross-entropy.

Behavioral spec: ``apex/transformer/tensor_parallel/cross_entropy.py`` —
``_VocabParallelCrossEntropy:23-131`` / ``vocab_parallel_cross_entropy:132``.
The logits stay sharded along the vocabulary dim; the softmax statistics are
assembled with three collectives, never materializing the full-vocab tensor:

1. all-reduce(MAX) of the per-row max logit (``:37-41``),
2. all-reduce(SUM) of the target logit, looked up only on the rank owning the
   target id (``:43-63``),
3. all-reduce(SUM) of the local ``sum(exp)`` (``:65-70``).

The reference hand-writes the backward (``softmax - onehot`` from saved
``exp_logits``, ``:75-80,96-130``) because torch autograd cannot
differentiate through NCCL.  Here the collectives are ``lax`` primitives
with replication-aware transposes, so plain JAX AD *derives* that same
backward — each rank's logit-shard gradient is its local
``softmax - onehot`` piece (verified against the unsharded reference in
``tests/test_tensor_parallel.py``).  The max-shift is wrapped in
``stop_gradient`` (gradient-invariant, and it keeps the nondifferentiable
``pmax`` out of the cotangent path).

Label smoothing (``:82-93``): here the smooth term uses the **global** mean
log-prob (``psum`` of the local sums over the full vocabulary) where the
reference averages over the local partition only — a small upstream bug we do
not reproduce; with tp=1 the two agree.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel import collectives as cc

from apex_tpu.parallel.mesh import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility

__all__ = ["vocab_parallel_cross_entropy"]


def _psum(x, axis):
    return x if axis is None else lax.psum(x, axis)


def _pmax(x, axis):
    return x if axis is None else lax.pmax(x, axis)


def vocab_parallel_cross_entropy(
    logits,
    target,
    axis: Optional[str] = TENSOR_AXIS,
    label_smoothing: float = 0.0,
):
    """Per-token CE loss from vocab-sharded ``logits`` ``[..., V/tp]``.

    ``target`` holds global token ids; returns loss with ``logits.shape[:-1]``
    in fp32 (the reference computes the softmax statistics in the input dtype
    but its fused-kernel sibling ``apex/contrib/xentropy`` accumulates fp32 —
    we always accumulate fp32).  Pass ``axis=None`` for the unsharded case.
    """
    logits = jnp.asarray(logits, jnp.float32)
    vocab_local = logits.shape[-1]
    world = 1 if axis is None else cc.axis_size(axis)
    vocab_global = vocab_local * world

    # (1) numerically-stable shift by the global max (cross_entropy.py:37-41).
    logits_max = _pmax(jnp.max(lax.stop_gradient(logits), axis=-1), axis)
    logits = logits - logits_max[..., None]

    # (2) target logit from the owning rank (cross_entropy.py:43-63).
    if axis is None:
        start = 0
    else:
        rank = lax.axis_index(axis)
        start, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
            vocab_local, rank
        )
    local_target = target - start
    in_range = (local_target >= 0) & (local_target < vocab_local)
    safe_target = jnp.where(in_range, local_target, 0)
    picked = jnp.take_along_axis(logits, safe_target[..., None], axis=-1)
    picked = jnp.squeeze(picked, -1)
    predicted_logit = _psum(jnp.where(in_range, picked, 0.0), axis)

    # (3) partition function (cross_entropy.py:65-70).
    sum_exp = _psum(jnp.sum(jnp.exp(logits), axis=-1), axis)
    lse = jnp.log(sum_exp)
    loss = lse - predicted_logit

    if label_smoothing > 0:
        # smooth term over the *global* vocab: mean_j log p_j
        # = mean_j (z_j - max) - lse  (see module docstring).
        s_hat = label_smoothing * vocab_global / (vocab_global - 1)
        mean_logits = _psum(jnp.sum(logits, axis=-1), axis) / vocab_global
        mean_log_probs = mean_logits - lse
        loss = (1.0 - s_hat) * loss - s_hat * mean_log_probs
    return loss
