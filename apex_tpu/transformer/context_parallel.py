"""Context parallelism: ring attention and Ulysses (all-to-all) attention.

The reference has **no long-context support** — sequence handling caps at
the fused softmax's 16384 keys (``apex/transformer/functional/
fused_softmax.py:233``) and FMHA's 512 (``apex/contrib/csrc/fmha``); SURVEY.md
§2.5/§5 designates ring/Ulysses context parallelism as the first-class
capability-parity-plus item of the TPU build.

**Ring attention** (blockwise attention over the ``cp`` mesh axis): every
rank holds a sequence shard of q/k/v; K/V chunks rotate around the ring via
``lax.ppermute`` (ICI neighbor hops) while each rank folds the visiting chunk
into its flash accumulator (running lse merge).  Peak memory is one sequence
shard + one visiting chunk; total sequence length scales linearly with the
ring size.

The backward is a custom VJP at the *ring* level — the flash-backward
identity (a chunk's gradient depends on other chunks only through the global
``lse`` and ``delta = rowsum(do*o)``) lets each reverse ring step re-drive
the per-chunk Pallas kernels (:func:`apex_tpu.ops.flash_attention.dq_chunk` /
:func:`dkv_chunk`) with the already-known global statistics: ``dq``
accumulates locally, ``dk/dv`` travel with their chunk and arrive home after
a full rotation.

**Ulysses attention** (DeepSpeed-Ulysses style): ``all_to_all`` swaps the
sharded dim from sequence to heads, each rank runs full-sequence flash
attention on ``heads/cp`` heads, and a second ``all_to_all`` swaps back.
Plain collectives, differentiable as-is (the transpose of an all-to-all is
the reverse all-to-all).

Both run inside ``shard_map`` with the ``cp`` axis bound; tensors are local
shards ``[b, h, s_local, d]``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel import collectives as cc

from apex_tpu.ops.flash_attention import (
    dkv_chunk,
    dq_chunk,
    flash_attention_with_lse,
)
from apex_tpu.parallel.mesh import CONTEXT_AXIS

__all__ = ["ring_attention", "ulysses_attention"]


def _merge(o, lse, o_new, lse_new):
    """Fold a partial (o_new, lse_new) into the running (o, lse)."""
    lse_tot = jnp.logaddexp(lse, lse_new)
    # Guard -inf - -inf when a row has seen nothing anywhere yet.
    w_old = jnp.exp(jnp.where(lse == lse_tot, 0.0, lse - lse_tot))
    w_old = jnp.where(jnp.isfinite(lse), w_old, 0.0)
    w_new = jnp.exp(jnp.where(lse_new == lse_tot, 0.0, lse_new - lse_tot))
    w_new = jnp.where(jnp.isfinite(lse_new), w_new, 0.0)
    o_tot = o * w_old[..., None] + o_new.astype(o.dtype) * w_new[..., None]
    return o_tot, lse_tot


def _rotate(tree, axis):
    n = cc.axis_size(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.tree_util.tree_map(lambda l: lax.ppermute(l, axis, perm), tree)


def _gqa_rep(q, k):
    """Query-heads-per-KV-head broadcast factor (1 = MHA).  K/V may carry
    fewer heads than q (grouped-query attention): the ring rotates and the
    a2a transfers only the grouped K/V — ``1/rep`` of the MHA bytes, GQA's
    whole point in the long-context regime — and the broadcast to query
    heads happens locally right before each kernel call."""
    h, g = q.shape[1], k.shape[1]
    if h % g:
        raise ValueError(f"q heads ({h}) must be a multiple of kv heads "
                         f"({g})")
    return h // g


def _expand_kv(x, rep):
    return x if rep == 1 else jnp.repeat(x, rep, axis=1)


def _reduce_kv_grad(dx, rep):
    """Adjoint of :func:`_expand_kv`: sum each group's query-head grads."""
    if rep == 1:
        return dx
    b, h, s, d = dx.shape
    return dx.reshape(b, h // rep, rep, s, d).sum(axis=2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention(q, k, v, axis: str = CONTEXT_AXIS, causal: bool = True,
                   scale: Optional[float] = None):
    """Flash attention over a ring-sharded sequence.

    ``q``: local shard ``[b, h, s_local, d]`` of a sequence of global
    length ``s_local * cp``; rank ``r`` owns positions
    ``[r*s_local, (r+1)*s_local)``.  ``k, v``: ``[b, g, s_local, d]``
    where ``g`` divides ``h`` (``g < h`` = grouped-query attention; only
    the g-head K/V travels the ring).  Returns the local output shard.
    """
    out, _ = _ring_fwd_math(q, k, v, axis, causal, scale)
    return out


def _ring_fwd_math(q, k, v, axis, causal, scale):
    cp = cc.axis_size(axis)
    r = lax.axis_index(axis)
    b, h, s_local, d = q.shape
    rep = _gqa_rep(q, k)

    o = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    kv = (k, v)

    def step(t, carry):
        o, lse, kv = carry
        k_cur, v_cur = kv
        chunk = (r - t) % cp  # home rank of the visiting chunk
        o_t, lse_t = _chunk_attn(q, _expand_kv(k_cur, rep),
                                 _expand_kv(v_cur, rep), causal, scale, r,
                                 chunk)
        o, lse = _merge(o, lse, o_t, lse_t)
        kv = _rotate(kv, axis)
        return o, lse, kv

    o, lse, _ = lax.fori_loop(0, cp, step, (o, lse, kv))
    return o.astype(q.dtype), lse


def _causal_case(chunk, r):
    """0 = fully visible (chunk < r), 1 = diagonal (==), 2 = masked (>).

    Offsets are traced under the ring loop but the Pallas kernels need
    static ones, so causal masking is decided at shard granularity: a whole
    earlier chunk is fully visible, the home chunk masks causally with
    offset 0, a later chunk contributes nothing.
    """
    return jnp.where(chunk < r, 0, jnp.where(chunk == r, 1, 2))


def _chunk_attn(q, k_cur, v_cur, causal, scale, r, chunk):
    if not causal:
        return flash_attention_with_lse(q, k_cur, v_cur, False, scale)

    def full(_):
        return flash_attention_with_lse(q, k_cur, v_cur, False, scale)

    def diag(_):
        return flash_attention_with_lse(q, k_cur, v_cur, True, scale)

    def masked(_):
        b, h, s_local, _d = q.shape
        return (jnp.zeros(q.shape, q.dtype),
                jnp.full((b, h, s_local), -jnp.inf, jnp.float32))

    return lax.switch(_causal_case(chunk, r), [full, diag, masked], None)


def _ring_vjp_fwd(q, k, v, axis, causal, scale):
    out, lse = _ring_fwd_math(q, k, v, axis, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_vjp_bwd(axis, causal, scale, res, do):
    q, k, v, out, lse = res
    cp = cc.axis_size(axis)
    r = lax.axis_index(axis)
    rep = _gqa_rep(q, k)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    dq = jnp.zeros(q.shape, jnp.float32)
    # dk/dv accumulators travel with their chunk (in the compact g-head
    # form — the per-chunk h-head grads reduce over each group before
    # accumulating, the adjoint of the _expand_kv broadcast): start at
    # home, after cp rotations they are home again.
    state = (k, v, jnp.zeros(k.shape, jnp.float32),
             jnp.zeros(v.shape, jnp.float32))

    def step(t, carry):
        dq, state = carry
        k_cur, v_cur, dk_acc, dv_acc = state
        k_exp, v_exp = _expand_kv(k_cur, rep), _expand_kv(v_cur, rep)
        chunk = (r - t) % cp

        def grads(is_causal):
            dq_t = dq_chunk(q, k_exp, v_exp, do, lse, delta,
                            causal=is_causal, scale=scale)
            dk_t, dv_t = dkv_chunk(q, k_exp, v_exp, do, lse, delta,
                                   causal=is_causal, scale=scale)
            return dq_t, dk_t, dv_t

        if causal:
            def zeros(_):
                return (jnp.zeros_like(q), jnp.zeros_like(k_exp),
                        jnp.zeros_like(v_exp))

            dq_t, dk_t, dv_t = lax.switch(
                _causal_case(chunk, r),
                [lambda _: grads(False), lambda _: grads(True), zeros],
                None,
            )
        else:
            dq_t, dk_t, dv_t = grads(False)

        dq = dq + dq_t.astype(jnp.float32)
        dk_acc = dk_acc + _reduce_kv_grad(dk_t.astype(jnp.float32), rep)
        dv_acc = dv_acc + _reduce_kv_grad(dv_t.astype(jnp.float32), rep)
        state = _rotate((k_cur, v_cur, dk_acc, dv_acc), axis)
        return dq, state

    dq, state = lax.fori_loop(0, cp, step, (dq, state))
    _, _, dk, dv = state
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ulysses_attention(q, k, v, axis: str = CONTEXT_AXIS,
                      causal: bool = True, scale: Optional[float] = None,
                      dropout_rate: float = 0.0, dropout_seed=None):
    """All-to-all (DeepSpeed-Ulysses) sequence-parallel attention.

    Local shards ``[b, h, s_local, d]`` with ``h % cp == 0``: a2a to
    ``[b, h/cp, s_global, d]``, full-sequence flash attention, a2a back.
    One a2a pair per call versus ring's ``cp`` neighbor hops — better when
    ``h >= cp`` and the sequence fits a single rank's VMEM streaming.

    Attention dropout works here (unlike ring attention): after the a2a
    each rank runs ordinary full-sequence flash with in-kernel dropout;
    the rank index is folded into the seed so different head groups draw
    different masks.
    """
    cp = cc.axis_size(axis)
    if q.shape[1] % cp != 0:
        raise ValueError(
            f"heads ({q.shape[1]}) must be divisible by cp ({cp})"
        )
    rep = _gqa_rep(q, k)
    # GQA: when the K/V groups themselves split over cp, a2a the compact
    # g-head K/V (1/rep of the MHA bytes) and broadcast after; otherwise
    # (g % cp != 0) the broadcast must happen first — the a2a needs a
    # head dim divisible by cp.
    if rep > 1 and k.shape[1] % cp == 0:
        post_rep = rep
    else:
        k, v = _expand_kv(k, rep), _expand_kv(v, rep)
        post_rep = 1

    # [b, h, s_local, d] -> [b, h/cp, s_global, d]
    def scatter_heads(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    drop = {}
    if dropout_rate > 0.0:
        if dropout_seed is None:  # mirror _seed_array's error, pre-asarray
            raise ValueError(
                "dropout_rate > 0 requires an explicit integer dropout_seed"
            )
        drop = dict(
            dropout_rate=dropout_rate,
            dropout_seed=jnp.asarray(dropout_seed, jnp.int32)
            + lax.axis_index(axis),
        )
    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    kg, vg = _expand_kv(kg, post_rep), _expand_kv(vg, post_rep)
    out, _ = flash_attention_with_lse(qg, kg, vg, causal, scale, **drop)
    return gather_heads(out)
