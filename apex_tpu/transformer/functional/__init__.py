"""Fused functional ops at the reference's import path.

``apex/transformer/functional/__init__.py`` exports ``FusedScaleMaskSoftmax``
(implementation in ``fused_softmax.py``); the TPU implementations live in
:mod:`apex_tpu.ops.softmax` and are re-exported here so migrated imports
(``from apex.transformer.functional import FusedScaleMaskSoftmax``) work
unchanged.
"""

from apex_tpu.ops.softmax import (
    AttnMaskType,
    FusedScaleMaskSoftmax,
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)

__all__ = [
    "AttnMaskType",
    "FusedScaleMaskSoftmax",
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
]
