"""apex_tpu.transformer — the Megatron-style model-parallel runtime.

TPU-native rebuild of ``apex/transformer`` (reference layout:
``apex/transformer/__init__.py``): tensor/sequence parallelism
(:mod:`~apex_tpu.transformer.tensor_parallel`), pipeline schedules
(:mod:`~apex_tpu.transformer.pipeline_parallel`), the model-parallel-aware
grad scaler (:mod:`~apex_tpu.transformer.amp`), and fused functional ops
(:mod:`~apex_tpu.transformer.functional`).

Where the reference manages NCCL process groups through
``parallel_state`` (``apex/transformer/parallel_state.py:155``), this runtime
runs SPMD over a named :class:`jax.sharding.Mesh` — ``parallel_state`` here
re-exports the mesh builder from :mod:`apex_tpu.parallel.mesh` so migrated
code keeps its import path.
"""

from apex_tpu.parallel import mesh as parallel_state
from apex_tpu.transformer import (
    context_parallel,
    pipeline_parallel,
    rope,
    tensor_parallel,
)
from apex_tpu.transformer.pipeline_parallel import get_forward_backward_func

__all__ = [
    "parallel_state",
    "tensor_parallel",
    "pipeline_parallel",
    "context_parallel",
    "rope",
    "get_forward_backward_func",
]
