"""Checkpoint / resume for full training state.

Behavioral spec: the reference's checkpoint surfaces — ``amp.state_dict``
(``apex/amp/frontend.py:365-404``), the imagenet example's model+optimizer
resume (``examples/imagenet/main_amp.py:177-193``), ``FP16_Optimizer.
state_dict`` (``apex/fp16_utils/fp16_optimizer.py:212-273``), and
``DistributedFusedAdam.state_dict(gather_on_root=...)`` /
``load_state_dict`` (``apex/contrib/optimizers/distributed_fused_adam.py``)
which gather the ZeRO-sharded optimizer shards into one portable dict.

TPU-first design: a checkpoint is "any pytree, restored against a
template".  ``save_checkpoint`` flattens the tree and writes the leaves
(host numpy) plus a path manifest; ``restore_checkpoint`` unflattens
against a ``like`` tree and verifies the manifest — so params, optimizer
``OptState``s, loss-scaler state, and custom counters all ride the same
two functions (no per-class state_dict plumbing).  ZeRO portability is
handled by :func:`gather_zero_state`/:func:`scatter_zero_state`: because
the SPMD shard layout is just "rank-major padded ravel", gathering is a
host-side reshape of the global arrays — no collectives, unlike the
reference's rank-0 NCCL gather.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "save_checkpoint",
    "save_checkpoint_async",
    "save_checkpoint_sharded",
    "save_checkpoint_sharded_async",
    "restore_checkpoint",
    "restore_checkpoint_sharded",
    "verify_checkpoint",
    "verify_checkpoint_sharded",
    "CheckpointCorruptError",
    "gather_zero_state",
    "scatter_zero_state",
]


class CheckpointCorruptError(ValueError):
    """A checkpoint failed integrity verification (checksum mismatch,
    torn/truncated file, unreadable archive).  Subclasses ``ValueError``
    so pre-existing ``except ValueError`` restore guards keep working;
    :meth:`apex_tpu.resilience.CheckpointManager.restore_latest` catches
    it to fall back to the previous intact checkpoint."""


# Manifest schema version.  1 = the PR 3 layout (leaves + checksums);
# 2 adds the optional ``sharding_spec`` logical-state description
# (``resilience/reshard.py``) consumed by the resharded restore path.
# Readers accept anything <= MANIFEST_VERSION and treat a NEWER version
# as corruption-class (an old binary must fall back, not misread).
MANIFEST_VERSION = 2


def _check_manifest_version(manifest: dict, path: str) -> None:
    ver = manifest.get("version", 1)
    if not isinstance(ver, int) or ver > MANIFEST_VERSION:
        raise CheckpointCorruptError(
            f"{path}: manifest version {ver!r} is newer than this reader "
            f"supports ({MANIFEST_VERSION}) — upgrade before restoring")


def _attach_spec(manifest: dict, spec) -> dict:
    """Embed a :class:`~apex_tpu.resilience.reshard.ShardingSpec` (or an
    already-serialized dict) into a manifest."""
    if spec is None:
        return manifest
    manifest = dict(manifest)
    manifest["sharding_spec"] = (spec if isinstance(spec, dict)
                                 else spec.to_json())
    return manifest


def _checksum(arr: np.ndarray) -> int:
    """crc32 over a leaf's raw bytes (dtype/shape are checked separately
    via the manifest, so bytes alone pin the value).  Fed through the
    buffer protocol — ``tobytes()`` would transiently double host memory
    per leaf on every save AND every verify."""
    flat = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
    return zlib.crc32(flat) & 0xFFFFFFFF


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts)


def _leaf_to_host(x) -> np.ndarray:
    """Fetch one leaf to host, including leaves sharded across *processes*
    (multi-host training): a non-fully-addressable global array is
    all-gathered over the process boundary first — the collective analog
    of the reference's rank-0 NCCL state gather
    (``distributed_fused_adam.py state_dict(gather_on_root=True)``)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))


def _snapshot(tree, step, copy_host_leaves=False):
    """Fetch every leaf to host (D2H; collective for cross-process shards)
    and build the restore-time manifest.

    ``copy_host_leaves``: ``device_get`` returns zero-copy *views* for
    leaves whose backing store is host memory — numpy leaves and
    CPU-backend ``jax.Array``s alike.  The async save needs real copies so
    in-place mutation or jit buffer *donation* after the call cannot
    corrupt the snapshot before the background write lands.  Leaves on an
    accelerator already get a fresh host buffer from the transfer and are
    never re-copied.
    """
    flat = jax.tree_util.tree_leaves_with_path(tree)

    def on_accelerator(x):
        return (isinstance(x, jax.Array)
                and all(d.platform != "cpu" for d in x.devices()))

    def to_host(x):
        host = _leaf_to_host(x)
        if copy_host_leaves and not on_accelerator(x):
            return np.array(host)
        return host

    arrays = {f"leaf_{i}": to_host(x) for i, (_, x) in enumerate(flat)}
    manifest = {
        "version": MANIFEST_VERSION,
        "step": step,
        "leaves": [
            {"path": _path_str(p), "shape": list(arrays[f"leaf_{i}"].shape),
             "dtype": str(arrays[f"leaf_{i}"].dtype)}
            for i, (p, _) in enumerate(flat)
        ],
    }
    return arrays, manifest


def _atomic_write(path, writer) -> str:
    """Crash-safe file write: ``writer(fileobj)`` into a unique temp in
    the target dir, fsync the file BEFORE the atomic ``os.replace`` and
    the directory AFTER it — without both, a host preemption can leave
    the rename durable but the data pages not (a named file full of
    zeros), the exact torn-checkpoint mode the rename exists to prevent.
    The unique temp name means concurrent saves to the same path cannot
    race, and the temp is unlinked on ANY failure (no orphan temps).
    O_CREAT with mode 0o666 lets the kernel apply the process umask
    atomically, with no umask() probing that could race other threads."""
    import uuid

    tmp = f"{path}.tmp.{uuid.uuid4().hex}"
    fd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o666)
    try:
        with os.fdopen(fd, "wb") as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def _write_npz(path, manifest, arrays) -> str:
    # Every array's crc32 rides in the manifest so torn or bit-flipped
    # data is detectable at verify/restore time (ISSUE 3).
    manifest = dict(manifest)
    manifest["checksums"] = {k: _checksum(v) for k, v in arrays.items()}
    return _atomic_write(
        path,
        lambda f: np.savez(f, __manifest__=json.dumps(manifest), **arrays))


def _fsync_dir(dirpath: str) -> None:
    """Make a rename durable: fsync the containing directory (no-op on
    filesystems that cannot open directories, e.g. some FUSE mounts)."""
    try:
        dfd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


def save_checkpoint(path: str, tree: Any, step: Optional[int] = None,
                    spec=None) -> None:
    """Write ``tree`` (any pytree of arrays/scalars) to ``path`` (.npz).

    Leaves are fetched to host (works on sharded global arrays — JAX
    assembles the full array; cross-process shards are all-gathered) and
    stored with a manifest of tree paths, shapes, and dtypes for
    restore-time verification.  ``spec`` (a
    :class:`~apex_tpu.resilience.reshard.ShardingSpec`) embeds the
    logical-state description that lets the checkpoint restore onto a
    different mesh shape (docs/resilience.md "restore-anywhere").

    Multi-host: call from **every** process (the gather is a collective);
    only process 0 writes the file, and a cross-process barrier orders the
    write before any rank returns.  ``path`` must be on a filesystem all
    hosts can read (NFS / GCS-fuse / single-host tests) — rank-0-local
    storage leaves other ranks unable to ``restore_checkpoint``.
    """
    _reraise_pending_failure(path)  # surface dropped async failures too
    arrays, manifest = _snapshot(tree, step)
    manifest = _attach_spec(manifest, spec)
    multi = jax.process_count() > 1
    if not multi or jax.process_index() == 0:
        _write_npz(path, manifest, arrays)
    _clear_write_failure(path)  # a durable save supersedes old failures
    if multi:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"save_checkpoint:{path}")


def save_checkpoint_async(path: str, tree: Any,
                          step: Optional[int] = None, spec=None):
    """Overlapped checkpointing: fetch-to-host happens on the caller's
    thread (device buffers are released as soon as the copies land — the
    next train step can donate/overwrite them safely), while the
    serialization + disk write runs on a background thread.

    Returns a handle with ``result()`` (wait; re-raises write errors) and
    ``done()``.  Call ``result()`` before shutdown or the next save to the
    same path (concurrent writes cannot corrupt each other — each uses a
    unique temp file — but last-replace-wins makes the surviving file
    ambiguous).  A write failure is also logged from the worker thread,
    so it is not silent even when the caller drops the handle.
    Single-process only: the multi-host collective gather of
    :func:`save_checkpoint` must run synchronously on every rank.
    """
    if jax.process_count() > 1:
        raise ValueError(
            "save_checkpoint_async is single-process; multi-host saves "
            "need the collective gather of save_checkpoint (or the "
            "gather-free save_checkpoint_sharded_async)")
    _reraise_pending_failure(path)
    # sync D2H (host-numpy leaves copied), then async IO
    arrays, manifest = _snapshot(tree, step, copy_host_leaves=True)
    manifest = _attach_spec(manifest, spec)
    return _submit_write(path, manifest, arrays, "async checkpoint")


# Failed background writes, keyed by destination (file path or sharded
# dir).  A dropped handle must not let a failed save masquerade as
# durable: the NEXT save to the same destination re-raises the recorded
# failure (ISSUE 3 satellite), in addition to the future's own
# ``result()`` re-raise and the worker-thread log line.
_FAILED_WRITES: dict = {}
_FAILED_WRITES_LOCK = threading.Lock()


def _record_write_failure(key: str, exc: BaseException) -> None:
    with _FAILED_WRITES_LOCK:
        _FAILED_WRITES[key] = exc


def _clear_write_failure(key: str) -> None:
    """The recorded failure exists ONLY for the dropped-handle case: it
    is cleared the moment it is observed (the handle's ``result()``
    re-raise) or superseded (a later successful save to the same
    destination) — otherwise a legitimate retry of the same step would
    spuriously trip the 'never waited on' guard."""
    with _FAILED_WRITES_LOCK:
        _FAILED_WRITES.pop(key, None)


def _reraise_pending_failure(dest: str) -> None:
    """Surface a recorded unobserved failure before starting a new save
    to ``dest`` OR to a sibling destination (same parent directory):
    step-indexed layouts write each save to a fresh ``step_N`` path, so
    exact-key matching alone would never revisit a failed step's key and
    the dropped-handle guarantee would be vacuous exactly where it
    matters most."""
    parent = os.path.dirname(os.path.abspath(dest))
    with _FAILED_WRITES_LOCK:
        key = next(
            (k for k in _FAILED_WRITES
             if k == dest or os.path.dirname(os.path.abspath(k)) == parent),
            None)
        exc = _FAILED_WRITES.pop(key, None) if key is not None else None
    if exc is not None:
        raise RuntimeError(
            f"a previous async checkpoint write to {key!r} failed and was "
            "never waited on — the checkpoint there is NOT durable"
        ) from exc


class _TrackedFuture:
    """Future wrapper that clears the per-destination failure record when
    the failure is delivered through ``result()`` (a timeout is not a
    delivery — the write is still in flight)."""

    def __init__(self, future, key):
        self._future = future
        self._key = key

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout=None):
        import concurrent.futures

        try:
            return self._future.result(timeout)
        except (TimeoutError, concurrent.futures.TimeoutError):
            raise
        except BaseException:
            _clear_write_failure(self._key)
            raise


def _submit_write(path, manifest, arrays, label, failure_key=None):
    """Background write on a dedicated single-use worker; failures are
    logged from the worker (not silent if the caller drops the handle),
    re-raised through the returned future's ``result()``, AND recorded
    under ``failure_key`` so the next save to the same destination
    re-raises them (a dropped handle cannot hide a failed save)."""
    import concurrent.futures

    key = failure_key if failure_key is not None else path

    def _write_logged():
        try:
            return _write_npz(path, manifest, arrays)
        except BaseException as e:
            import logging

            logging.getLogger(__name__).exception(
                "%s write to %r failed", label, path)
            _record_write_failure(key, e)
            raise

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    future = pool.submit(_write_logged)
    pool.shutdown(wait=False)
    return _TrackedFuture(future, key)


def _validate_template(manifest, like):
    """Shared restore-time template check (leaf count, per-leaf path and
    shape — the reference's load_state_dict strictness).  Returns
    ``(like_flat, treedef, like_paths)``."""
    like_flat, treedef = jax.tree_util.tree_flatten(like)
    if len(like_flat) != len(manifest["leaves"]):
        raise ValueError(
            f"checkpoint has {len(manifest['leaves'])} leaves, template "
            f"has {len(like_flat)}")
    like_paths = [
        _path_str(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(like)
    ]
    for i, (rec, tpath, tleaf) in enumerate(
            zip(manifest["leaves"], like_paths, like_flat)):
        if rec["path"] != tpath:
            raise ValueError(
                f"leaf {i} path mismatch: checkpoint {rec['path']!r} vs "
                f"template {tpath!r}")
        if tuple(rec["shape"]) != tuple(np.shape(tleaf)):
            raise ValueError(
                f"{tpath}: checkpoint shape {rec['shape']} vs template "
                f"{list(np.shape(tleaf))}")
    return like_flat, treedef, like_paths


def _template_dtype(tleaf):
    """Target dtype for a restored leaf: the template's (so a checkpoint
    written at a different precision — e.g. the reference O2 flow's
    portable fp32 checkpoints restored into a recast model — lands in
    the dtype the training step expects, never a silent mismatch)."""
    return tleaf.dtype if hasattr(tleaf, "dtype") else \
        np.asarray(tleaf).dtype


def restore_checkpoint(path: str, like: Any):
    """Read a checkpoint into the structure of ``like``.

    Returns ``(tree, step)``.  Leaf count and per-leaf paths/shapes must
    match the template; leaves are cast to the template's dtypes.
    """
    with np.load(path, allow_pickle=False) as data:
        manifest = json.loads(str(data["__manifest__"]))
        _check_manifest_version(manifest, path)
        leaves = [data[f"leaf_{i}"] for i in range(len(manifest["leaves"]))]

    like_flat, treedef, _ = _validate_template(manifest, like)
    out = [jnp.asarray(arr, dtype=_template_dtype(tleaf))
           for arr, tleaf in zip(leaves, like_flat)]
    return jax.tree_util.tree_unflatten(treedef, out), manifest["step"]


def _verify_npz(path: str) -> dict:
    """Integrity-check ONE ``.npz`` checkpoint file: the archive must be
    readable and every stored array must match its manifest crc32.
    Returns the manifest; raises :class:`CheckpointCorruptError` on any
    damage (torn write, truncation, bit flip).  Checkpoints written
    before checksums existed verify structurally only (archive readable,
    every manifest leaf present)."""
    try:
        with np.load(path, allow_pickle=False) as data:
            manifest = json.loads(str(data["__manifest__"]))
            _check_manifest_version(manifest, path)
            sums = manifest.get("checksums")
            keys = [k for k in data.files if k != "__manifest__"]
            for key in keys:
                arr = data[key]  # zipfile's own CRC also trips here
                if sums is not None:
                    want = sums.get(key)
                    if want is None:
                        raise CheckpointCorruptError(
                            f"{path}: array {key!r} missing from the "
                            "checksum manifest")
                    got = _checksum(arr)
                    if got != want:
                        raise CheckpointCorruptError(
                            f"{path}: checksum mismatch on {key!r} "
                            f"(stored {want}, recomputed {got})")
            if sums is not None and set(sums) - set(keys):
                raise CheckpointCorruptError(
                    f"{path}: arrays missing from archive: "
                    f"{sorted(set(sums) - set(keys))}")
    except CheckpointCorruptError:
        raise
    except Exception as e:
        # zipfile.BadZipFile, zlib.error, OSError on truncated reads,
        # json decode of a torn manifest — all are corruption here.
        raise CheckpointCorruptError(f"{path}: unreadable ({e!r})") from e
    return manifest


def verify_checkpoint(path: str) -> dict:
    """Full integrity pass over a flat checkpoint (``save_checkpoint`` /
    ``save_checkpoint_async`` output): archive readable, every array's
    crc32 matches the manifest.  Returns the manifest.  Raises
    :class:`CheckpointCorruptError` — callers that can fall back (e.g.
    ``CheckpointManager.restore_latest``) catch it and try the previous
    checkpoint."""
    return _verify_npz(path)


# ---------------------------------------------------------------------------
# Sharded (per-process) checkpointing — the pod-scale path
# ---------------------------------------------------------------------------


def _shard_key(index, shape) -> str:
    """Stable string key for a shard's global slice tuple."""
    if not shape:
        return "scalar"
    parts = []
    for s, dim in zip(index, shape):
        start = 0 if s.start is None else int(s.start)
        stop = dim if s.stop is None else int(s.stop)
        parts.append(f"{start}:{stop}")
    return ",".join(parts)


def save_checkpoint_sharded(ckpt_dir: str, tree: Any,
                            step: Optional[int] = None,
                            spec=None) -> None:
    """Pod-scale checkpoint: every process writes ONLY its own shards.

    The gather-free complement of :func:`save_checkpoint` — nothing ever
    crosses the process boundary (the reference's
    ``DistributedFusedAdam.state_dict(gather_on_root=False)`` per-rank
    shard dicts, ``distributed_fused_adam.py``; and how real TPU pods
    checkpoint, since gathering a pod-sized model onto one host does not
    fit).  Writes ``shard_{process}.npz`` files plus a manifest under
    ``ckpt_dir``; each device shard is written once globally (by the
    process holding its first replica), so replicated leaves cost one
    copy total, not one per replica.

    Call from **every** process.  ``ckpt_dir`` must be shared storage if
    :func:`restore_checkpoint_sharded` will run with a different
    process-to-host mapping.
    """
    _reraise_pending_failure(ckpt_dir)  # surface dropped async failures
    _clean_stale_shards(ckpt_dir)
    arrays, manifest, proc = _sharded_snapshot(tree, step)
    manifest = _attach_spec(manifest, spec)
    _write_npz(os.path.join(ckpt_dir, f"shard_{proc}.npz"),
               manifest, arrays)
    _clear_write_failure(ckpt_dir)  # durable save supersedes old failures
    _finish_sharded_save(ckpt_dir, manifest)


def _finish_sharded_save(ckpt_dir: str, manifest: Optional[dict]) -> None:
    """The one copy of the commit protocol, shared by the sync save and
    ``ShardedSaveHandle.finalize``: barrier (every rank's shard write is
    done) -> rank-0 ``manifest.json`` commit -> second barrier (no rank
    returns — and possibly restores — before the commit is durable)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(
            f"save_checkpoint_sharded:{ckpt_dir}")
    if manifest is not None:
        _commit_shard_manifest(ckpt_dir, manifest)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(
            f"save_checkpoint_sharded:commit:{ckpt_dir}")


def _sharded_snapshot(tree, step, copy_host_leaves=False):
    """Collect this process's shard arrays + manifest (stale-shard
    cleanup is separate: :func:`_clean_stale_shards`).  D2H copies
    complete before return, so the caller may donate/overwrite device
    buffers immediately; ``copy_host_leaves`` additionally copies leaves
    whose backing store is host memory — host-numpy leaves AND
    CPU-backend device shards, where ``np.asarray`` is a zero-copy view
    (the same donation-aliasing hazard :func:`_snapshot` guards)."""
    flat = jax.tree_util.tree_leaves_with_path(tree)
    proc = jax.process_index()
    arrays, leaf_meta = {}, []
    for i, (p, x) in enumerate(flat):
        shape = tuple(np.shape(x))
        if isinstance(x, jax.Array) and hasattr(x, "addressable_shards"):
            seen = set()
            for sh in x.addressable_shards:
                key = _shard_key(sh.index, shape)
                # first-replica ownership: exactly one device in the whole
                # job writes each distinct slice
                if sh.replica_id == 0 and key not in seen:
                    seen.add(key)
                    data = np.asarray(sh.data)
                    if copy_host_leaves and sh.device.platform == "cpu":
                        data = np.array(data)
                    arrays[f"leaf_{i}|{key}"] = data
        elif proc == 0:  # host-numpy / scalar leaves: rank 0 owns
            host = np.asarray(x)
            arrays[f"leaf_{i}|full"] = (np.array(host)
                                        if copy_host_leaves else host)
        dtype = x.dtype if isinstance(x, jax.Array) else np.asarray(x).dtype
        leaf_meta.append({"path": _path_str(p), "shape": list(shape),
                          "dtype": str(dtype)})
    manifest = {"version": MANIFEST_VERSION, "step": step, "sharded": True,
                "process_count": jax.process_count(),
                "leaves": leaf_meta}
    return arrays, manifest, proc


_SHARD_MANIFEST = "manifest.json"


def _commit_shard_manifest(ckpt_dir: str, shard_manifest: dict) -> None:
    """Rank 0 commits the save by writing ``manifest.json`` (atomic:
    temp + fsync + rename) AFTER every shard write has completed and the
    cross-process barrier has passed.  The manifest names the shard files
    the save owns, so (a) restore reads exactly those files — stale
    leftovers are ignored rather than fatal — and (b) stale-shard cleanup
    has an authority for what is referenced (the concurrent-writer race
    fix: only unreferenced files strictly older than the committed
    manifest are removed)."""
    if jax.process_index() != 0:
        return
    count = shard_manifest.get("process_count", 1)
    doc = {
        "version": 1,
        "step": shard_manifest.get("step"),
        "process_count": count,
        "files": [f"shard_{p}.npz" for p in range(count)],
    }
    _atomic_write(os.path.join(ckpt_dir, _SHARD_MANIFEST),
                  lambda f: f.write(json.dumps(doc).encode()))


def _read_shard_manifest(ckpt_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(ckpt_dir, _SHARD_MANIFEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _clean_stale_shards(ckpt_dir) -> None:
    """Rank 0 drops stale shard files so a later restore cannot blend old
    weights in.  Concurrent-writer safe (ISSUE 3 satellite): a shard file
    is removed only when it is (a) NOT referenced by the committed
    ``manifest.json`` AND (b) strictly older than that manifest — a file
    a second in-flight sharded save just renamed into place is younger
    than the last committed manifest and survives.  Temp files
    (``*.tmp.*``) are never touched here: the in-flight save that owns
    them unlinks on failure, and a crash leaves them inert (restore never
    reads them).  Without a committed manifest (legacy dirs) the old
    index-vs-process_count rule applies, which is safe because legacy
    saves were synchronous."""
    os.makedirs(ckpt_dir, exist_ok=True)
    if jax.process_index() != 0:
        return
    import glob as _glob

    committed = _read_shard_manifest(ckpt_dir)
    try:
        manifest_mtime = os.path.getmtime(
            os.path.join(ckpt_dir, _SHARD_MANIFEST))
    except OSError:
        manifest_mtime = None

    for old in _glob.glob(os.path.join(ckpt_dir, "shard_*.npz")):
        name = os.path.basename(old)
        try:
            idx = int(name[len("shard_"):-len(".npz")])
        except ValueError:
            continue
        if committed is not None and manifest_mtime is not None:
            if name in committed.get("files", []):
                continue  # referenced by the committed save
            try:
                if os.path.getmtime(old) >= manifest_mtime:
                    continue  # younger than the commit: in-flight writer
                os.unlink(old)
            except OSError:
                continue
        elif idx >= jax.process_count():
            os.unlink(old)


class ShardedSaveHandle:
    """Handle for :func:`save_checkpoint_sharded_async`.

    ``result()`` waits for this process's background write (re-raising
    write errors).  ``finalize()`` waits and then runs the cross-process
    barrier — call it from the **main thread on every process** before
    relying on the checkpoint or starting the next save to the same dir
    (collectives must not run on worker threads).

    ``timeout`` bounds only the **local** write wait; the barrier itself
    is an unbounded collective, so if a peer rank's write fails (it
    raises before reaching the barrier) the surviving ranks block in
    ``finalize`` until the job's own failure detection (e.g.
    ``jax.distributed`` heartbeats / the cluster runtime) tears the
    collective down — the same failure mode as every collective save,
    including the reference's rank-0 NCCL gather.
    """

    def __init__(self, future, ckpt_dir, manifest=None):
        self._future = future
        self._ckpt_dir = ckpt_dir
        self._manifest = manifest

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout=None):
        return self._future.result(timeout)

    def finalize(self, timeout=None):
        path = self.result(timeout)
        # Commit AFTER every shard is durable (local write waited, peer
        # writes barriered).  In a FRESH directory (the manager's
        # step-indexed layout) a crash before this point leaves the new
        # shards uncommitted and inert.  When overwriting a previous
        # save's directory in place, a crash mid-sequence can leave the
        # old manifest over replaced shard bytes — that state is
        # DETECTED (manifest-vs-shard step mismatch / inconsistent-shard
        # checks) rather than prevented; use one directory per step for
        # lossless recovery.
        _finish_sharded_save(self._ckpt_dir, self._manifest)
        return path


def save_checkpoint_sharded_async(ckpt_dir: str, tree: Any,
                                  step: Optional[int] = None,
                                  spec=None) -> ShardedSaveHandle:
    """Overlapped pod-scale checkpoint: the local-shard D2H snapshot runs
    on the caller's thread (buffers may be donated immediately after the
    call), the per-process ``shard_{p}.npz`` write runs in the
    background.  Unlike :func:`save_checkpoint_async` this works
    multi-host — no collective is needed for the snapshot (each process
    touches only its own shards); the cross-process ordering barrier
    moves into :meth:`ShardedSaveHandle.finalize`, which every process
    must call from its main thread.
    """
    _reraise_pending_failure(ckpt_dir)
    _clean_stale_shards(ckpt_dir)
    arrays, manifest, proc = _sharded_snapshot(
        tree, step, copy_host_leaves=True)
    manifest = _attach_spec(manifest, spec)
    path = os.path.join(ckpt_dir, f"shard_{proc}.npz")
    return ShardedSaveHandle(
        _submit_write(path, manifest, arrays, "async sharded checkpoint",
                      failure_key=ckpt_dir),
        ckpt_dir, manifest)


def _shard_paths(ckpt_dir: str):
    """The shard files a restore/verify should read: exactly the ones the
    committed ``manifest.json`` references when one exists (stale
    leftovers from older/larger saves are ignored, not fatal), else every
    ``shard_*.npz`` in the dir (legacy layout — restore's own
    process_count check then guards staleness)."""
    committed = _read_shard_manifest(ckpt_dir)
    if committed is not None:
        paths = [os.path.join(ckpt_dir, name)
                 for name in committed.get("files", [])]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise CheckpointCorruptError(
                f"{ckpt_dir}: manifest references missing shard files "
                f"{[os.path.basename(p) for p in missing]}")
        return paths
    import glob

    return sorted(glob.glob(os.path.join(ckpt_dir, "shard_*.npz")))


def verify_checkpoint_sharded(ckpt_dir: str) -> dict:
    """Full integrity pass over a sharded checkpoint dir: every
    referenced shard archive readable, every array's crc32 matching,
    step/process_count consistent across shards, and the shard-file
    count matching the writer count.  Returns the (first shard's)
    manifest.  Raises :class:`CheckpointCorruptError`."""
    paths = _shard_paths(ckpt_dir)
    if not paths:
        raise CheckpointCorruptError(
            f"{ckpt_dir}: no shard files to verify")
    first = None
    for p in paths:
        m = _verify_npz(p)
        if first is None:
            first = m
        elif (m.get("step") != first.get("step")
              or m.get("process_count") != first.get("process_count")):
            raise CheckpointCorruptError(
                f"{ckpt_dir}: inconsistent shard manifests "
                f"({os.path.basename(p)}: step={m.get('step')} "
                f"process_count={m.get('process_count')} vs "
                f"step={first.get('step')} "
                f"process_count={first.get('process_count')})")
    if len(paths) != first.get("process_count"):
        raise CheckpointCorruptError(
            f"{ckpt_dir}: {len(paths)} shard files but the checkpoint "
            f"was written by {first.get('process_count')} processes")
    committed = _read_shard_manifest(ckpt_dir)
    if committed is not None and committed.get("step") != first.get("step"):
        # Overlapping saves finalized out of order: the commit says one
        # step, the surviving shard bytes are another's — ambiguous, and
        # the reason CheckpointManager serializes saves.
        raise CheckpointCorruptError(
            f"{ckpt_dir}: committed manifest is step "
            f"{committed.get('step')} but shard contents are step "
            f"{first.get('step')} — overlapping saves to one dir?")
    return first


def restore_checkpoint_sharded(ckpt_dir: str, like: Any):
    """Restore a :func:`save_checkpoint_sharded` checkpoint against a
    ``like`` tree whose leaves carry the target shardings.

    Returns ``(tree, step)``.  Each process materialises only its own
    addressable shards (``jax.make_array_from_callback`` with the
    template leaf's sharding) — no leaf is ever assembled in full on one
    host.  The mesh/process topology may differ from save time as long
    as every needed slice exists in the shard files (identical global
    shapes; slice boundaries must align, which holds for any layout
    produced by the same named-sharding rules).
    """
    paths = _shard_paths(ckpt_dir)
    if not paths:
        raise FileNotFoundError(f"no shard_*.npz under {ckpt_dir!r}")
    # Lazy index: npz entries decompress only on access (NpzFile reads the
    # zip directory up front), so building key -> file costs metadata IO
    # only and each process later materialises just the slices its own
    # sharding requests — never the whole checkpoint in host RAM.
    manifest = None
    files = []
    shards: dict = {}
    try:
        for p in paths:
            data = np.load(p, allow_pickle=False)
            files.append(data)
            m = json.loads(str(data["__manifest__"]))
            _check_manifest_version(m, p)
            if manifest is None:
                manifest = m
            elif (m.get("step") != manifest.get("step")
                  or m.get("process_count") != manifest.get("process_count")):
                raise ValueError(
                    f"inconsistent shard files under {ckpt_dir!r}: "
                    f"{os.path.basename(p)} has step={m.get('step')} "
                    f"process_count={m.get('process_count')} vs "
                    f"step={manifest.get('step')} process_count="
                    f"{manifest.get('process_count')} — torn or mixed "
                    "checkpoint")
            for key in data.files:
                if key == "__manifest__":
                    continue
                if key in shards:
                    raise ValueError(
                        f"duplicate shard {key!r} across files under "
                        f"{ckpt_dir!r} — mixed checkpoints?")
                shards[key] = data
        if len(paths) != manifest.get("process_count"):
            raise ValueError(
                f"{len(paths)} shard files under {ckpt_dir!r} but the "
                f"checkpoint was written by "
                f"{manifest.get('process_count')} processes — stale or "
                "missing shard files")

        get = lambda key: (shards[key][key]  # noqa: E731
                           if key in shards else None)
        like_flat, treedef, _ = _validate_template(manifest, like)
        out = []
        for i, (rec, tleaf) in enumerate(
                zip(manifest["leaves"], like_flat)):
            shape = tuple(rec["shape"])
            dtype = _template_dtype(tleaf)
            full = get(f"leaf_{i}|full")
            if (isinstance(tleaf, jax.Array)
                    and getattr(tleaf, "sharding", None) is not None):
                sharding = tleaf.sharding

                def cb(index, i=i, shape=shape, full=full, dtype=dtype):
                    if full is not None:
                        return np.asarray(full[index], dtype=dtype)
                    got = get(f"leaf_{i}|{_shard_key(index, shape)}")
                    if got is None:
                        got = _assemble_slice(shards, i, index, shape)
                    return np.asarray(got, dtype=dtype)

                out.append(jax.make_array_from_callback(shape, sharding, cb))
            else:
                if full is None:
                    # leaf was device-sharded at save time but the
                    # template wants a host value: stitch it together
                    full = _assemble_slice(
                        shards, i, tuple(slice(0, d) for d in shape),
                        shape)
                full = np.asarray(full, dtype=dtype)
                out.append(full if not isinstance(tleaf, jnp.ndarray)
                           else jnp.asarray(full))
        return jax.tree_util.tree_unflatten(treedef, out), manifest["step"]
    finally:
        for f in files:
            f.close()


def _assemble_slice(shards, leaf_i, index, shape):
    """Build an arbitrary requested slice of leaf ``leaf_i`` from the
    stored shard pieces (used when restore-time shard boundaries differ
    from save-time, e.g. a different mesh shape)."""
    starts = [0 if s.start is None else int(s.start) for s in index] \
        if shape else []
    stops = [shape[d] if index[d].stop is None else int(index[d].stop)
             for d in range(len(shape))]
    if not shape:
        key = f"leaf_{leaf_i}|scalar"
        npz = shards.get(key)
        if npz is None:
            raise KeyError(f"leaf {leaf_i}: scalar shard missing")
        return npz[key]
    out = None
    prefix = f"leaf_{leaf_i}|"
    for key, npz in shards.items():
        if not key.startswith(prefix) or key.endswith("|full"):
            continue
        spec = key[len(prefix):]
        if spec in ("scalar",):
            continue
        bounds = [tuple(map(int, part.split(":")))
                  for part in spec.split(",")]
        # overlap of this stored piece with the requested slice — decided
        # from the key alone, so non-overlapping pieces are never read
        inter = [(max(b0, s0), min(b1, s1))
                 for (b0, b1), (s0, s1) in zip(bounds, zip(starts, stops))]
        if any(a >= b for a, b in inter):
            continue
        data = npz[key]  # lazy: decompress only the overlapping piece
        if out is None:
            out = np.empty([b - a for a, b in zip(starts, stops)],
                           dtype=data.dtype)
            filled = np.zeros(out.shape, dtype=bool)
        src = tuple(slice(a - b0, b - b0) for (a, b), (b0, _) in
                    zip(inter, bounds))
        dst = tuple(slice(a - s0, b - s0) for (a, b), s0 in
                    zip(inter, starts))
        out[dst] = data[src]
        filled[dst] = True
    if out is None or not filled.all():
        raise KeyError(
            f"leaf {leaf_i}: stored shards do not cover requested slice "
            f"{[(a, b) for a, b in zip(starts, stops)]}")
    return out


# ---------------------------------------------------------------------------
# ZeRO (DistributedFusedAdam/LAMB) portability
# ---------------------------------------------------------------------------


def _unshard_host(chunked, param):
    """Global rank-major chunked leaf -> full leaf shaped like ``param``.

    Goes through numpy: eager jnp ops on committed partially-replicated
    arrays (a dp-sharded shard_map output on a mesh with dcn > 1)
    mis-lower in older jax (the replicated dim gets summed);
    ``np.asarray`` reads one replica correctly."""
    flat = np.asarray(chunked).ravel()
    n = int(np.prod(np.shape(param))) if np.ndim(param) else 1
    return jnp.asarray(flat[:n].reshape(np.shape(param)))


def _shard_host(full, chunked_like):
    """Full leaf -> padded rank-major layout shaped like the sharded
    global leaf."""
    target = np.shape(chunked_like)
    flat = jnp.asarray(full).ravel().astype(jnp.asarray(chunked_like).dtype)
    pad = int(np.prod(target)) - flat.size
    return jnp.pad(flat, (0, pad)).reshape(target)


def _host_group_meta(opt, leaves, idx, out_dtype):
    """Chunk metadata of one flat-bucket dtype-group with every output
    leaf forced to ``out_dtype`` (slot/master buffers are fp32 regardless
    of the model dtype)."""
    from apex_tpu.utils.tree import chunked_meta

    sub = [leaves[i] for i in idx]
    return chunked_meta(
        jax.tree_util.tree_structure(list(sub)),
        [np.shape(x) for x in sub], [out_dtype] * len(sub),
        chunk=opt.chunk)


def _gather_zero_flat(opt, state, params):
    """Flat-bucket layout gather: bucket k's global array *is* rows
    ``[k*rpb, (k+1)*rpb)`` of the logical group buffer (the tiled
    reduce-scatter order equals the rank-major out-spec order), so the
    full buffer is a concat over buckets and gathering is pure
    reshaping — same portable output as the per-leaf layout."""
    from apex_tpu.contrib.optimizers import _flat_bucket as fbk
    from apex_tpu.contrib.optimizers.distributed_fused_adam import join_fp32
    from apex_tpu.utils.tree import flatten_to_chunked, unflatten_from_chunked

    treedef, leaves, raw_groups = fbk.host_groups(params)

    # Buffers are materialized on host FIRST (np.asarray): eager jnp ops
    # on committed partially-replicated arrays (shard_map P("dp") outputs
    # on a mesh with dcn > 1) mis-lower in older jax — the partitioner
    # treats the replicated dim as unreduced and a concatenate SUMS it.
    # np.asarray reads one replica correctly; everything below is pure
    # host math.  Param leaves stay on device: only their shapes are
    # read (the remainders join below materializes its group itself).
    def unpack(groups_bufs, transform=None):
        out = list(leaves)
        for (_, idx), bufs in zip(raw_groups, groups_bufs):
            buf = jnp.asarray(
                np.concatenate([np.asarray(b) for b in bufs], axis=0))
            if transform is not None:
                buf = transform(buf, idx)
            meta = _host_group_meta(opt, leaves, idx, jnp.float32)
            for i, leaf in zip(idx, unflatten_from_chunked(buf, meta)):
                out[i] = leaf
        return jax.tree_util.tree_unflatten(treedef, out)

    slots = {name: unpack(tree) for name, tree in state.slots.items()}
    master = None
    if state.master is not None:
        if getattr(opt, "store_param_remainders", False):
            def join(lo_buf, idx):
                hi_buf, _ = flatten_to_chunked(
                    [np.asarray(leaves[i]) for i in idx], chunk=opt.chunk,
                    dtype=jnp.bfloat16, pad_rows_to=int(lo_buf.shape[0]))
                return join_fp32(hi_buf, lo_buf)
            master = unpack(state.master, transform=join)
        else:
            master = unpack(state.master)
    return {"step": state.step, "slots": slots, "master": master}


def _scatter_zero_flat(opt, portable, state_like, params):
    """Inverse of :func:`_gather_zero_flat`, re-bucketing into
    ``state_like``'s layout (whose bucket shapes encode the — possibly
    different — target dp world size)."""
    from apex_tpu.contrib.optimizers import _flat_bucket as fbk
    from apex_tpu.contrib.optimizers.distributed_fused_adam import split_fp32
    from apex_tpu.utils.tree import flatten_to_chunked

    treedef, leaves, raw_groups = fbk.host_groups(params)

    def pack(full_tree, groups_like, transform=None):
        full_leaves = treedef.flatten_up_to(full_tree)
        out = []
        for (_, idx), like in zip(raw_groups, groups_like):
            rows_total = sum(int(np.shape(b)[0]) for b in like)
            buf, _ = flatten_to_chunked(
                [full_leaves[i] for i in idx], chunk=opt.chunk,
                dtype=jnp.float32, pad_rows_to=max(rows_total, 1))
            if transform is not None:
                buf = transform(buf)
            pieces, off = [], 0
            for b in like:
                r = int(np.shape(b)[0])
                pieces.append(
                    jnp.asarray(buf[off:off + r], jnp.asarray(b).dtype))
                off += r
            out.append(pieces)
        return out

    slots = {name: pack(portable["slots"][name], state_like.slots[name])
             for name in state_like.slots}
    master = None
    if state_like.master is not None:
        transform = (lambda buf: split_fp32(buf)[1]) \
            if getattr(opt, "store_param_remainders", False) else None
        master = pack(portable["master"], state_like.master, transform)
    return type(state_like)(step=jnp.asarray(portable["step"]),
                            slots=slots, master=master)


def gather_zero_state(opt, state, params):
    """Portable (unsharded, fp32-master) state dict for a ZeRO-sharded
    optimizer — the ``state_dict(gather_on_root=True)`` analog.

    ``state`` holds *global* arrays whose leaves are the rank-major
    concatenation of per-rank chunks (the shape they have outside the
    training ``shard_map``), so gathering is pure reshaping — for both
    the per-leaf layout (one chunked array per param) and the
    flat-bucket layout (one buffer per dtype-group bucket).  The
    portable format is layout-independent, so a flat-bucket checkpoint
    restores into a per-leaf optimizer and vice versa.
    """
    from apex_tpu.contrib.optimizers.distributed_fused_adam import join_fp32

    if getattr(opt, "flat_bucket", False):
        return _gather_zero_flat(opt, state, params)

    slots = {
        name: jax.tree_util.tree_map(_unshard_host, tree, params)
        for name, tree in state.slots.items()
    }
    master = None
    if state.master is not None:
        if getattr(opt, "store_param_remainders", False):
            def join(lo, p):
                hi = jnp.asarray(p, jnp.bfloat16).ravel()
                lo_flat = jnp.asarray(lo).ravel()[: hi.size]
                return join_fp32(hi, lo_flat).reshape(np.shape(p))

            master = jax.tree_util.tree_map(join, state.master, params)
        else:
            master = jax.tree_util.tree_map(_unshard_host, state.master,
                                            params)
    return {"step": state.step, "slots": slots, "master": master}


def scatter_zero_state(opt, portable, state_like, params):
    """Inverse of :func:`gather_zero_state`: re-shard a portable state
    dict into the layout of ``state_like`` (possibly under a different
    dp world size — the point of portable ZeRO checkpoints)."""
    from apex_tpu.contrib.optimizers.distributed_fused_adam import split_fp32

    if getattr(opt, "flat_bucket", False):
        return _scatter_zero_flat(opt, portable, state_like, params)

    slots = {
        name: jax.tree_util.tree_map(
            _shard_host, portable["slots"][name], tree)
        for name, tree in state_like.slots.items()
    }
    master = None
    if state_like.master is not None:
        if getattr(opt, "store_param_remainders", False):
            def split(m32, like):
                _, lo = split_fp32(jnp.asarray(m32, jnp.float32).ravel())
                return _shard_host(lo, like)

            master = jax.tree_util.tree_map(
                split, portable["master"], state_like.master)
        else:
            master = jax.tree_util.tree_map(
                _shard_host, portable["master"], state_like.master)
    return type(state_like)(step=jnp.asarray(portable["step"]),
                            slots=slots, master=master)
