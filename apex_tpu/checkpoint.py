"""Checkpoint / resume for full training state.

Behavioral spec: the reference's checkpoint surfaces — ``amp.state_dict``
(``apex/amp/frontend.py:365-404``), the imagenet example's model+optimizer
resume (``examples/imagenet/main_amp.py:177-193``), ``FP16_Optimizer.
state_dict`` (``apex/fp16_utils/fp16_optimizer.py:212-273``), and
``DistributedFusedAdam.state_dict(gather_on_root=...)`` /
``load_state_dict`` (``apex/contrib/optimizers/distributed_fused_adam.py``)
which gather the ZeRO-sharded optimizer shards into one portable dict.

TPU-first design: a checkpoint is "any pytree, restored against a
template".  ``save_checkpoint`` flattens the tree and writes the leaves
(host numpy) plus a path manifest; ``restore_checkpoint`` unflattens
against a ``like`` tree and verifies the manifest — so params, optimizer
``OptState``s, loss-scaler state, and custom counters all ride the same
two functions (no per-class state_dict plumbing).  ZeRO portability is
handled by :func:`gather_zero_state`/:func:`scatter_zero_state`: because
the SPMD shard layout is just "rank-major padded ravel", gathering is a
host-side reshape of the global arrays — no collectives, unlike the
reference's rank-0 NCCL gather.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "save_checkpoint",
    "save_checkpoint_async",
    "restore_checkpoint",
    "gather_zero_state",
    "scatter_zero_state",
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts)


def _leaf_to_host(x) -> np.ndarray:
    """Fetch one leaf to host, including leaves sharded across *processes*
    (multi-host training): a non-fully-addressable global array is
    all-gathered over the process boundary first — the collective analog
    of the reference's rank-0 NCCL state gather
    (``distributed_fused_adam.py state_dict(gather_on_root=True)``)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))


def _snapshot(tree, step, copy_host_leaves=False):
    """Fetch every leaf to host (D2H; collective for cross-process shards)
    and build the restore-time manifest.

    ``copy_host_leaves``: ``device_get`` returns zero-copy *views* for
    leaves whose backing store is host memory — numpy leaves and
    CPU-backend ``jax.Array``s alike.  The async save needs real copies so
    in-place mutation or jit buffer *donation* after the call cannot
    corrupt the snapshot before the background write lands.  Leaves on an
    accelerator already get a fresh host buffer from the transfer and are
    never re-copied.
    """
    flat = jax.tree_util.tree_leaves_with_path(tree)

    def on_accelerator(x):
        return (isinstance(x, jax.Array)
                and all(d.platform != "cpu" for d in x.devices()))

    def to_host(x):
        host = _leaf_to_host(x)
        if copy_host_leaves and not on_accelerator(x):
            return np.array(host)
        return host

    arrays = {f"leaf_{i}": to_host(x) for i, (_, x) in enumerate(flat)}
    manifest = {
        "version": 1,
        "step": step,
        "leaves": [
            {"path": _path_str(p), "shape": list(arrays[f"leaf_{i}"].shape),
             "dtype": str(arrays[f"leaf_{i}"].dtype)}
            for i, (p, _) in enumerate(flat)
        ],
    }
    return arrays, manifest


def _write_npz(path, manifest, arrays) -> str:
    # Unique temp file in the target dir: concurrent saves to the same
    # path cannot race on a shared temp name, and os.replace stays atomic
    # (same filesystem) so there are no torn checkpoints on preemption.
    # O_CREAT with mode 0o666 lets the kernel apply the process umask
    # atomically (the file gets exactly the mode a plain open() would),
    # with no umask() probing that could race other threads.
    import uuid

    tmp = f"{path}.tmp.{uuid.uuid4().hex}"
    fd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o666)
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __manifest__=json.dumps(manifest), **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def save_checkpoint(path: str, tree: Any, step: Optional[int] = None) -> None:
    """Write ``tree`` (any pytree of arrays/scalars) to ``path`` (.npz).

    Leaves are fetched to host (works on sharded global arrays — JAX
    assembles the full array; cross-process shards are all-gathered) and
    stored with a manifest of tree paths, shapes, and dtypes for
    restore-time verification.

    Multi-host: call from **every** process (the gather is a collective);
    only process 0 writes the file, and a cross-process barrier orders the
    write before any rank returns.  ``path`` must be on a filesystem all
    hosts can read (NFS / GCS-fuse / single-host tests) — rank-0-local
    storage leaves other ranks unable to ``restore_checkpoint``.
    """
    arrays, manifest = _snapshot(tree, step)
    multi = jax.process_count() > 1
    if not multi or jax.process_index() == 0:
        _write_npz(path, manifest, arrays)
    if multi:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"save_checkpoint:{path}")


def save_checkpoint_async(path: str, tree: Any,
                          step: Optional[int] = None):
    """Overlapped checkpointing: fetch-to-host happens on the caller's
    thread (device buffers are released as soon as the copies land — the
    next train step can donate/overwrite them safely), while the
    serialization + disk write runs on a background thread.

    Returns a handle with ``result()`` (wait; re-raises write errors) and
    ``done()``.  Call ``result()`` before shutdown or the next save to the
    same path (concurrent writes cannot corrupt each other — each uses a
    unique temp file — but last-replace-wins makes the surviving file
    ambiguous).  A write failure is also logged from the worker thread,
    so it is not silent even when the caller drops the handle.
    Single-process only: the multi-host collective gather of
    :func:`save_checkpoint` must run synchronously on every rank.
    """
    if jax.process_count() > 1:
        raise ValueError(
            "save_checkpoint_async is single-process; multi-host saves "
            "need the collective gather of save_checkpoint")
    import concurrent.futures

    # sync D2H (host-numpy leaves copied), then async IO
    arrays, manifest = _snapshot(tree, step, copy_host_leaves=True)

    def _write_logged():
        try:
            return _write_npz(path, manifest, arrays)
        except BaseException:
            import logging

            logging.getLogger(__name__).exception(
                "async checkpoint write to %r failed", path)
            raise

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    future = pool.submit(_write_logged)
    pool.shutdown(wait=False)
    return future


def restore_checkpoint(path: str, like: Any):
    """Read a checkpoint into the structure of ``like``.

    Returns ``(tree, step)``.  Leaf count and per-leaf paths must match
    the template (shape mismatches raise with the offending path, the
    reference's load_state_dict strictness).
    """
    with np.load(path, allow_pickle=False) as data:
        manifest = json.loads(str(data["__manifest__"]))
        leaves = [data[f"leaf_{i}"] for i in range(len(manifest["leaves"]))]

    like_flat, treedef = jax.tree_util.tree_flatten(like)
    if len(like_flat) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, template has "
            f"{len(like_flat)}")
    like_paths = [
        _path_str(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(like)
    ]
    out = []
    for i, (rec, arr, tpath, tleaf) in enumerate(
            zip(manifest["leaves"], leaves, like_paths, like_flat)):
        if rec["path"] != tpath:
            raise ValueError(
                f"leaf {i} path mismatch: checkpoint {rec['path']!r} vs "
                f"template {tpath!r}")
        if tuple(rec["shape"]) != tuple(np.shape(tleaf)):
            raise ValueError(
                f"{tpath}: checkpoint shape {rec['shape']} vs template "
                f"{list(np.shape(tleaf))}")
        out.append(jnp.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out), manifest["step"]


# ---------------------------------------------------------------------------
# ZeRO (DistributedFusedAdam/LAMB) portability
# ---------------------------------------------------------------------------


def _unshard_host(chunked, param):
    """Global rank-major chunked leaf -> full leaf shaped like ``param``."""
    flat = jnp.asarray(chunked).ravel()
    n = int(np.prod(np.shape(param))) if np.ndim(param) else 1
    return flat[:n].reshape(np.shape(param))


def _shard_host(full, chunked_like):
    """Full leaf -> padded rank-major layout shaped like the sharded
    global leaf."""
    target = np.shape(chunked_like)
    flat = jnp.asarray(full).ravel().astype(jnp.asarray(chunked_like).dtype)
    pad = int(np.prod(target)) - flat.size
    return jnp.pad(flat, (0, pad)).reshape(target)


def gather_zero_state(opt, state, params):
    """Portable (unsharded, fp32-master) state dict for a ZeRO-sharded
    optimizer — the ``state_dict(gather_on_root=True)`` analog.

    ``state`` holds *global* arrays whose leaves are the rank-major
    concatenation of per-rank chunks (the shape they have outside the
    training ``shard_map``), so gathering is pure reshaping.
    """
    from apex_tpu.contrib.optimizers.distributed_fused_adam import join_fp32

    slots = {
        name: jax.tree_util.tree_map(_unshard_host, tree, params)
        for name, tree in state.slots.items()
    }
    master = None
    if state.master is not None:
        if getattr(opt, "store_param_remainders", False):
            def join(lo, p):
                hi = jnp.asarray(p, jnp.bfloat16).ravel()
                lo_flat = jnp.asarray(lo).ravel()[: hi.size]
                return join_fp32(hi, lo_flat).reshape(np.shape(p))

            master = jax.tree_util.tree_map(join, state.master, params)
        else:
            master = jax.tree_util.tree_map(_unshard_host, state.master,
                                            params)
    return {"step": state.step, "slots": slots, "master": master}


def scatter_zero_state(opt, portable, state_like, params):
    """Inverse of :func:`gather_zero_state`: re-shard a portable state
    dict into the layout of ``state_like`` (possibly under a different
    dp world size — the point of portable ZeRO checkpoints)."""
    from apex_tpu.contrib.optimizers.distributed_fused_adam import split_fp32

    slots = {
        name: jax.tree_util.tree_map(
            _shard_host, portable["slots"][name], tree)
        for name, tree in state_like.slots.items()
    }
    master = None
    if state_like.master is not None:
        if getattr(opt, "store_param_remainders", False):
            def split(m32, like):
                _, lo = split_fp32(jnp.asarray(m32, jnp.float32).ravel())
                return _shard_host(lo, like)

            master = jax.tree_util.tree_map(
                split, portable["master"], state_like.master)
        else:
            master = jax.tree_util.tree_map(
                _shard_host, portable["master"], state_like.master)
    return type(state_like)(step=jnp.asarray(portable["step"]),
                            slots=slots, master=master)
