"""L1-style stored-baseline training traces.

Behavioral spec: ``tests/L1/common/run_test.sh`` + ``compare.py`` in the
reference — instrumented training runs record per-iteration loss and
gradient norms, and CI diffs them against checked-in baselines, which
catches silent numerics regressions that "loss decreases" tests cannot.

Two deterministic smoke configs mirror the reference's L1 workloads:
``rn50_smoke`` (ResNet-50-style conv net, O2 policy, FusedSGD — the
imagenet config shrunk to smoke size) and ``gpt_smoke`` (standalone GPT,
FusedAdam).  Synthetic data, fixed seeds, fp32 accumulation — traces are
reproducible to fp tolerance across XLA releases on the same platform.

Regenerate baselines after an *intended* numerics change::

    python -m apex_tpu.testing.l1 record tests/L1/baselines
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["run_trace", "compare_traces", "CONFIGS"]

ITERS = 10


def _global_grad_norm(grads) -> float:
    total = sum(jnp.sum(jnp.square(jnp.asarray(g, jnp.float32)))
                for g in jax.tree_util.tree_leaves(grads))
    return float(jnp.sqrt(total))


def _trace_rn50() -> Dict[str, List[float]]:
    from apex_tpu import amp
    from apex_tpu.models import ResNet50
    from apex_tpu.optimizers import FusedSGD

    policy = amp.policy("O2")
    model = ResNet50(num_classes=10, axis_name=None,
                     dtype=policy.compute_dtype)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 32, 32, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, size=(8,)))
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params = policy.cast_to_param(variables["params"])
    stats = variables["batch_stats"]
    opt = FusedSGD(lr=0.005, momentum=0.9, weight_decay=1e-4,
                   master_weights=policy.master_weights)
    state = opt.init(params)

    def loss_fn(p, stats):
        logits, mut = model.apply(
            {"params": p, "batch_stats": stats},
            policy.cast_to_compute(x), train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(logp[jnp.arange(8), y]), mut["batch_stats"]

    @jax.jit
    def step(p, stats, state):
        (loss, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, stats)
        p, state = opt.step(grads, state, p)
        return p, stats, state, loss, grads

    losses, gnorms = [], []
    for _ in range(ITERS):
        params, stats, state, loss, grads = step(params, stats, state)
        losses.append(float(loss))
        gnorms.append(_global_grad_norm(grads))
    return {"loss": losses, "grad_norm": gnorms}


def _trace_gpt() -> Dict[str, List[float]]:
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        padded_vocab_size=128, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None)
    model = GPTModel(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
    params = model.init(jax.random.PRNGKey(2), tokens)["params"]
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, state):
        def loss_fn(p):
            return jnp.mean(model.apply({"params": p}, tokens,
                                        labels=tokens))
        loss, grads = jax.value_and_grad(loss_fn)(p)
        p, state = opt.step(grads, state, p)
        return p, state, loss, grads

    losses, gnorms = [], []
    for _ in range(ITERS):
        params, state, loss, grads = step(params, state)
        losses.append(float(loss))
        gnorms.append(_global_grad_norm(grads))
    return {"loss": losses, "grad_norm": gnorms}


CONFIGS = {"rn50_smoke": _trace_rn50, "gpt_smoke": _trace_gpt}


def run_trace(name: str) -> Dict[str, List[float]]:
    return CONFIGS[name]()


def compare_traces(got: Dict[str, List[float]],
                   baseline: Dict[str, List[float]],
                   loss_rtol: float = 1e-4,
                   grad_rtol: float = 1e-3) -> List[str]:
    """Per-iteration diff (reference ``tests/L1/common/compare.py``);
    returns a list of mismatch descriptions (empty = pass)."""
    problems = []
    for key, rtol in (("loss", loss_rtol), ("grad_norm", grad_rtol)):
        a, b = got.get(key, []), baseline.get(key, [])
        if len(a) != len(b):
            problems.append(f"{key}: {len(a)} iters vs baseline {len(b)}")
            continue
        for i, (x, y) in enumerate(zip(a, b)):
            if not np.isclose(x, y, rtol=rtol, atol=1e-7):
                problems.append(
                    f"{key}[{i}]: {x!r} vs baseline {y!r} (rtol {rtol})")
    return problems


def _main(argv):
    # Recording ALWAYS pins the test environment (CPU + 8 virtual
    # devices, matching tests/conftest.py): the virtual-device count
    # partitions the CPU thread pool, which changes fp reduction order,
    # so traces recorded under any other flags fail the comparison.
    from apex_tpu.utils.platform import force_host_device_count, pin_cpu

    force_host_device_count(8)
    pin_cpu()
    if len(argv) >= 1 and argv[0] == "record":
        outdir = argv[1] if len(argv) > 1 else "tests/L1/baselines"
        os.makedirs(outdir, exist_ok=True)
        for name in CONFIGS:
            trace = run_trace(name)
            path = os.path.join(outdir, f"{name}.json")
            with open(path, "w") as f:
                json.dump(trace, f, indent=1)
            print(f"recorded {path}: loss {trace['loss'][0]:.4f} -> "
                  f"{trace['loss'][-1]:.4f}")
    else:
        print(__doc__)


if __name__ == "__main__":
    _main(sys.argv[1:])
