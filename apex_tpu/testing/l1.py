"""L1-style stored-baseline training traces — the cross-product matrix.

Behavioral spec: ``tests/L1/common/run_test.sh`` + ``compare.py`` and
``tests/L1/cross_product/run.sh`` in the reference — instrumented training
runs record per-iteration loss / gradient norms (and loss scale), and CI
diffs them against checked-in baselines, which catches silent numerics
regressions that "loss decreases" tests cannot.  The reference sweeps
opt-level x keep-batchnorm x loss-scale; the TPU analog sweeps:

- RN50: policy (O0 / O2 / O3) x loss scale (none / static 128 / dynamic)
  x BatchNorm flavor (local BN / SyncBatchNorm over a bound dp axis) —
  the reference's ``--opt-level O{0..3} [--keep-batchnorm-fp32]
  [--loss-scale ...]`` matrix (``tests/L1/cross_product/run.sh``);
- GPT: fp32 / bf16 / fp8 (delayed-scaling e4m3 GEMMs) — the transformer
  numerics axis the reference's L1 suite covers with its BERT recipes;
- GPT 3D-parallel: one dp=2 x pp=2(xvpp=2) x tp=2+sp train trace on the
  8-virtual-device mesh, pinning the *parallel* numerics (collectives,
  pipeline rotation, vocab-parallel CE) to a stored baseline.

Synthetic data, fixed seeds, fp32 accumulation — traces are reproducible
to fp tolerance across XLA releases on the same platform.  Dynamic-scale
configs also record the per-iteration ``loss_scale`` series (growth
events land inside the 10-iteration window via a small
``growth_interval``), so a scaler-semantics regression shows up as a
trace diff, not just an eventual loss drift.

Regenerate baselines after an *intended* numerics change::

    python -m apex_tpu.testing.l1 record tests/L1/baselines
"""

from __future__ import annotations

import json
import os
import sys
from functools import partial
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["run_trace", "compare_traces", "CONFIGS"]

ITERS = 10


def _global_grad_norm(grads) -> float:
    total = sum(jnp.sum(jnp.square(jnp.asarray(g, jnp.float32)))
                for g in jax.tree_util.tree_leaves(grads))
    return float(jnp.sqrt(total))


def _make_scaler(kind):
    from apex_tpu import amp

    if kind is None:
        return None
    if kind == "dynamic":
        # growth_interval=4 puts two growth events inside the ITERS=10
        # window, so the baseline trace pins the growth schedule too
        return amp.DynamicLossScale(init_scale=2.0 ** 10, growth_interval=4)
    return amp.StaticLossScale(float(kind))


def _trace_rn50(policy_name: str = "O2", loss_scale=None,
                sync_bn: bool = False,
                optimizer: str = "sgd") -> Dict[str, List[float]]:
    """One RN50 cross-product cell.

    ``loss_scale``: ``None`` (no scaling), ``"dynamic"`` or a float
    (static).  ``sync_bn=True`` binds the dp axis over all attached
    devices via shard_map (8 virtual CPU devices under the test/record
    environment) with the batch sharded across it, so cross-replica
    Welford psums are part of the traced numerics.  ``optimizer="lamb"``
    swaps in FusedLAMB — pinning the chunked flat-buffer update's
    numerics (global-norm clip, segmented trust-ratio norms) to a stored
    trace.
    """
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.amp.scaler import all_finite
    from apex_tpu.models import ResNet50
    from apex_tpu.optimizers import FusedLAMB, FusedSGD
    from apex_tpu.parallel import collectives as cc, mesh as mesh_lib

    policy = amp.policy(policy_name)
    scaler = _make_scaler(loss_scale)
    model = ResNet50(num_classes=10, axis_name="dp" if sync_bn else None,
                     dtype=policy.compute_dtype)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 32, 32, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, size=(8,)))
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params = policy.cast_to_param(variables["params"])
    stats = variables["batch_stats"]
    if optimizer == "lamb":
        opt = FusedLAMB(lr=1e-3, weight_decay=1e-2,
                        master_weights=policy.master_weights)
    elif optimizer == "sgd":
        opt = FusedSGD(lr=0.005, momentum=0.9, weight_decay=1e-4,
                       master_weights=policy.master_weights)
    else:
        # fail loudly: a typo here would silently pin the wrong
        # optimizer's numerics under the mislabeled baseline name
        raise ValueError(f"unknown optimizer {optimizer!r}")
    state = opt.init(params)
    sstate = scaler.init() if scaler else None

    def forward(p, stats, x, y):
        logits, mut = model.apply(
            {"params": p, "batch_stats": stats},
            policy.cast_to_compute(x), train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        n = y.shape[0]
        return -jnp.mean(logp[jnp.arange(n), y]), mut["batch_stats"]

    def local_step(p, stats, state, sstate, x, y):
        def scaled_loss(p, stats):
            loss, new_stats = forward(p, stats, x, y)
            if sync_bn:
                loss = jax.lax.pmean(loss, "dp")
            scaled = scaler.scale(loss, sstate) if scaler else loss
            return scaled, (loss, new_stats)

        grads, (loss, new_stats) = jax.grad(
            scaled_loss, has_aux=True)(p, stats)
        if sync_bn:
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "dp"), grads)
        if scaler:
            finite = all_finite(grads)
            p2, state2 = opt.step(grads, state, p,
                                  grad_scale=sstate.scale,
                                  skip_update=~finite)
            sstate2 = scaler.update(sstate, finite)
            gnorm_grads = scaler.unscale(grads, sstate)
        else:
            p2, state2 = opt.step(grads, state, p)
            sstate2 = sstate
            gnorm_grads = grads
        return p2, new_stats, state2, sstate2, loss, gnorm_grads

    if sync_bn:
        mesh = mesh_lib.initialize_model_parallel()
        rep = lambda tree: jax.tree_util.tree_map(  # noqa: E731
            lambda _: P(), tree)
        dspec = P(("dcn", "dp"))

        def step_fn(p, stats, state, sstate, x, y):
            return cc.shard_over(
                local_step, mesh=mesh,
                in_specs=(rep(p), rep(stats), rep(state), rep(sstate),
                          dspec, dspec),
                out_specs=(rep(p), rep(stats), rep(state), rep(sstate),
                           P(), rep(p)),
            )(p, stats, state, sstate, x, y)

        step = jax.jit(step_fn)
    else:
        step = jax.jit(local_step)

    try:
        out: Dict[str, List[float]] = {"loss": [], "grad_norm": []}
        if scaler:
            out["loss_scale"] = []
        for _ in range(ITERS):
            params, stats, state, sstate, loss, grads = step(
                params, stats, state, sstate, x, y)
            out["loss"].append(float(loss))
            out["grad_norm"].append(_global_grad_norm(grads))
            if scaler:
                out["loss_scale"].append(float(sstate.scale))
        return out
    finally:
        if sync_bn:
            mesh_lib.destroy_model_parallel()


def _trace_gpt(dtype=None, fp8: bool = False,
               **cfg_kw) -> Dict[str, List[float]]:
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    kw = dict(cfg_kw)
    if dtype is not None:
        kw["dtype"] = dtype
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        padded_vocab_size=128, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
        fp8=fp8, **kw)
    model = GPTModel(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
    variables = model.init(jax.random.PRNGKey(2), tokens)
    params = variables["params"]
    fp8_state = dict(variables.get("fp8_meta", {}))
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, state, fp8_state):
        def loss_fn(p, fp8_state):
            if not fp8_state:
                return jnp.mean(model.apply({"params": p}, tokens,
                                            labels=tokens)), fp8_state
            losses, mut = model.apply(
                {"params": p, "fp8_meta": fp8_state}, tokens,
                labels=tokens, mutable=["fp8_meta"])
            return jnp.mean(losses), dict(mut)["fp8_meta"]

        (loss, fp8_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, fp8_state)
        p, state = opt.step(grads, state, p)
        return p, state, fp8_state, loss, grads

    losses, gnorms = [], []
    for _ in range(ITERS):
        params, state, fp8_state, loss, grads = step(
            params, state, fp8_state)
        losses.append(float(loss))
        gnorms.append(_global_grad_norm(grads))
    return {"loss": losses, "grad_norm": gnorms}


def _trace_gpt_3d() -> Dict[str, List[float]]:
    """3D-parallel (dp=2 x pp=2(xvpp=2) x tp=2+sp) GPT train trace on the
    8-virtual-device mesh — pins the *parallel* numerics (collectives,
    pipeline rotation, vocab-parallel CE) to a stored baseline, not just
    to same-run serial parity (``tests/test_gpt_3d.py``)."""
    from apex_tpu import parallel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=2,
        pipeline_model_parallel_size=2,
        virtual_pipeline_model_parallel_size=2,
    )
    try:
        cfg = TransformerConfig(
            hidden_size=32, num_layers=4, num_attention_heads=4,
            padded_vocab_size=64, max_position_embeddings=16,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_axis="tp", sequence_parallel=True,
        )
        init_fn, _, make_train_step = build_gpt_3d(
            cfg, num_chunks=2, num_microbatches=2, mesh=mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        params, specs = init_fn(jax.random.PRNGKey(0), tokens)
        opt = FusedAdam(lr=1e-3)
        state = opt.init(params)
        step = jax.jit(make_train_step(opt, specs))

        losses = []
        for _ in range(ITERS):
            params, state, loss = step(params, state, tokens)
            losses.append(float(loss))
        # grad norms are inside the sharded step; the loss series alone
        # pins the end-to-end parallel numerics
        return {"loss": losses, "grad_norm": []}
    finally:
        parallel.mesh.destroy_model_parallel()


CONFIGS = {
    # original two smoke configs (unchanged numerics, baselines kept)
    "rn50_smoke": partial(_trace_rn50, "O2", None, False),
    "gpt_smoke": partial(_trace_gpt),
    # RN50 policy x loss-scale x BN cross-product
    # (tests/L1/cross_product/run.sh analog)
    "rn50_O0": partial(_trace_rn50, "O0", None, False),
    "rn50_O2_static128": partial(_trace_rn50, "O2", 128.0, False),
    "rn50_O2_dynamic": partial(_trace_rn50, "O2", "dynamic", False),
    "rn50_O3": partial(_trace_rn50, "O3", None, False),
    "rn50_O2_syncbn": partial(_trace_rn50, "O2", None, True),
    "rn50_O2_dynamic_syncbn": partial(_trace_rn50, "O2", "dynamic", True),
    # optimizer axis: the r5 chunked flat-buffer LAMB (global-norm clip +
    # segmented trust-ratio norms) pinned end-to-end through a model
    "rn50_O2_lamb": partial(_trace_rn50, "O2", None, False,
                            optimizer="lamb"),
    # GPT numerics axis
    "gpt_bf16": partial(_trace_gpt, jnp.bfloat16),
    "gpt_fp8": partial(_trace_gpt, None, True),
    # flash-kernel numerics (Pallas interpret mode on CPU runs the same
    # kernel code the chip compiles — pins the hot kernel's math,
    # including the r4 input-dtype-matmul convention, to a stored trace)
    "gpt_flash": partial(_trace_gpt, jnp.bfloat16, False,
                         use_flash_attention=True),
    # modern-architecture axis (RoPE + GQA + SwiGLU — the LLaMA-shaped
    # stack of transformer/rope.py and standalone_transformer_lm.py)
    "gpt_modern": partial(_trace_gpt, None, False,
                          position_embedding_type="rope",
                          num_query_groups=2, swiglu=True),
    # parallel numerics axis (dp x pp(xvpp) x tp+sp on the virtual mesh)
    "gpt_3d": _trace_gpt_3d,
}


def run_trace(name: str) -> Dict[str, List[float]]:
    return CONFIGS[name]()


def compare_traces(got: Dict[str, List[float]],
                   baseline: Dict[str, List[float]],
                   loss_rtol: float = 1e-4,
                   grad_rtol: float = 1e-3) -> List[str]:
    """Per-iteration diff (reference ``tests/L1/common/compare.py``);
    returns a list of mismatch descriptions (empty = pass).  The
    ``loss_scale`` series, when present, must match exactly — scaler
    decisions are discrete."""
    problems = []
    keys = [("loss", loss_rtol), ("grad_norm", grad_rtol)]
    if "loss_scale" in baseline or "loss_scale" in got:
        keys.append(("loss_scale", 0.0))
    for key, rtol in keys:
        a, b = got.get(key, []), baseline.get(key, [])
        if len(a) != len(b):
            problems.append(f"{key}: {len(a)} iters vs baseline {len(b)}")
            continue
        for i, (x, y) in enumerate(zip(a, b)):
            if not np.isclose(x, y, rtol=rtol, atol=1e-7):
                problems.append(
                    f"{key}[{i}]: {x!r} vs baseline {y!r} (rtol {rtol})")
    return problems


def _main(argv):
    # Recording ALWAYS pins the test environment (CPU + 8 virtual
    # devices, matching tests/conftest.py): the virtual-device count
    # partitions the CPU thread pool, which changes fp reduction order,
    # so traces recorded under any other flags fail the comparison.
    from apex_tpu.utils.platform import force_host_device_count, pin_cpu

    force_host_device_count(8)
    pin_cpu()
    if len(argv) >= 1 and argv[0] == "record":
        outdir = argv[1] if len(argv) > 1 else "tests/L1/baselines"
        names = argv[2:] or list(CONFIGS)
        os.makedirs(outdir, exist_ok=True)
        for name in names:
            trace = run_trace(name)
            path = os.path.join(outdir, f"{name}.json")
            with open(path, "w") as f:
                json.dump(trace, f, indent=1)
            print(f"recorded {path}: loss {trace['loss'][0]:.4f} -> "
                  f"{trace['loss'][-1]:.4f}")
    else:
        print(__doc__)


if __name__ == "__main__":
    _main(sys.argv[1:])
