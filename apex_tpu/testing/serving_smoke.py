"""Serving-runtime smoke (ISSUE 9 satellite): the end-to-end proof.

Drives the full ``apex_tpu.serving`` stack on the virtual CPU mesh
(tp=2) and asserts the three contracts the runtime stands on:

1. **Correctness under churn** — N requests with staggered arrivals and
   varied prompt/output lengths, continuously batched (requests join
   and leave mid-flight, prompts advance through the chunked prefill)
   over a **bf16 KV cache**, must produce greedy outputs
   **token-identical** to a per-request full-forward argmax reference
   (the degraded single-rank modules over the gathered host params,
   re-running the whole prefix for every generated token — O(n²) and
   unbatched, which is exactly why the paged runtime exists).
2. **Zero decode recompiles** — the decode executable compiles once;
   every join/leave is data.  Pinned via the jit cache size.
3. **int8 cache + speculative decoding at occupancy (ISSUE 12/13)** —
   the same wave plus a template-heavy one replayed on an **int8 KV
   cache** engine with **n-gram drafting armed**
   (``speculative=SpeculativeConfig(k=2)``) and the pool deliberately
   undersized (roughly half the worst-case demand), so eviction,
   preemption-with-recompute, drafting and the fused ``[max_batch,
   k+1]`` verify all fire mid-run: every request still finishes and
   every output stream is token-identical to the bf16 plain-decode
   leg — quantization, occupancy pressure and speculation change the
   HBM story and the arrival rate, never the tokens — at 1 decode
   compile.
4. **Clean drain on SIGTERM** — a real ``SIGTERM`` mid-stream (through
   ``resilience.PreemptionGuard``) stops admissions, the in-flight
   requests keep decoding and DELIVER their full responses, the queued
   ones are cancelled (a terminal state, not a hang), and the process
   exits 0.

Run via ``scripts/serving_smoke.sh``; wired fast-tier in
``tests/test_aux_subsystems.py`` (the data-pipeline-smoke pattern).
"""

import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# platform pinning must precede any jax import (conftest pattern)
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms", "cpu")

TP = 2
VOCAB, MAX_SEQ = 64, 32


def log(msg):
    print(f"serving_smoke: {msg}", file=sys.stderr, flush=True)


def build():
    from apex_tpu import parallel
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=TP)
    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=MAX_SEQ,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=cfg.num_layers,
                                 num_microbatches=1, mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))
    return mesh, cfg, params


def make_reference(cfg, params):
    """Per-request full-forward greedy argmax over the host params."""
    from apex_tpu.ops.softmax import AttnMaskType
    from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        Embedding, ParallelTransformerLayer, parallel_lm_logits)

    host = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), params)
    embed = Embedding(cfg)
    layer = ParallelTransformerLayer(
        cfg, self_attn_mask_type=AttnMaskType.causal)
    ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon)
    L = cfg.num_layers

    def greedy(prompt, n_new):
        toks = list(prompt)
        for _ in range(n_new):
            t = jnp.asarray(np.asarray(toks, np.int32)[None, :])
            h = embed.apply({"params": host.embedding}, t)
            for vi in range(L):
                lp = jax.tree_util.tree_map(
                    lambda leaf: leaf.reshape((L,) + leaf.shape[2:])[vi],
                    host.layers)
                h = layer.apply({"params": lp}, h, None)
            h = ln.apply({"params": host.final_ln}, h)
            logits = parallel_lm_logits(
                h, host.embedding["word_embeddings"]["embedding"], cfg)
            toks.append(int(jnp.argmax(logits[-1, 0])))
        return toks[len(prompt):]

    return greedy


def main() -> int:
    from apex_tpu.observability import timeline
    from apex_tpu.observability.goodput import serving_goodput_report
    from apex_tpu.observability.metrics import (
        HeartbeatMonitor, MetricRegistry)
    from apex_tpu.resilience import PreemptionGuard
    from apex_tpu.serving import ServingConfig, ServingEngine

    mesh, cfg, params = build()
    registry = MetricRegistry()

    # Flight recorder (ISSUE 10): the smoke runs with the timeline armed
    # so the request lifecycle (submit -> admit -> prefill -> decode
    # ticks -> finish/cancel) and the per-request goodput attribution
    # are asserted end to end, not just unit-tested.  Spills to
    # APEX_TPU_TIMELINE_DIR when set (scripts/obs_smoke.sh), else ring
    # only.
    recorder = timeline.arm_from_env()
    if recorder is None:
        recorder = timeline.arm(timeline.FlightRecorder())

    # ---- phase A: staggered churn vs full-forward reference ----------
    # Heartbeat armed on the decode loop (ISSUE 10 satellite): the
    # engine beats it each tick and an explicit check_now() below
    # exercises the detection path on a healthy run (it must stay
    # silent).  The wedged-decode -> guard -> drain leg is proven
    # deterministically in tests/test_serving.py
    # (test_heartbeat_hung_decode_triggers_drain).
    heartbeat = HeartbeatMonitor(timeout_s=120.0, registry=registry)
    eng = ServingEngine(
        cfg, ServingConfig(max_batch=3, block_size=4, max_seq=MAX_SEQ,
                           prefill_len=MAX_SEQ,
                           cache_dtype=jnp.bfloat16),
        params, mesh=mesh, registry=registry, heartbeat=heartbeat)
    rng = np.random.RandomState(7)
    wave = [(rng.randint(1, VOCAB - 1, size=rng.randint(2, 14)).tolist(),
             int(rng.randint(2, 6))) for _ in range(5)]
    # staggered arrivals: two up front, the rest dripped in mid-flight
    reqs = [eng.submit(p, n) for p, n in wave[:2]]
    arrivals = iter(wave[2:])
    step = 0
    while not eng.scheduler.idle or len(reqs) < len(wave):
        if step % 2 == 0:
            nxt = next(arrivals, None)
            if nxt is not None:
                reqs.append(eng.submit(*nxt))
        eng.step()
        step += 1
        if step > 500:
            log("FAIL: phase A did not drain")
            return 1
    greedy = make_reference(cfg, params)
    for req, (prompt, n_new) in zip(reqs, wave):
        ref = greedy(prompt, n_new)
        if req.output_tokens != ref:
            log(f"FAIL: request {req.rid} {req.output_tokens} != "
                f"reference {ref}")
            return 1
    compiles = eng.decode_compile_count()
    if compiles != 1:
        log(f"FAIL: decode compiled {compiles} times across churn")
        return 1
    eng.scheduler.allocator.check()
    total = int(registry.counter("serving/tokens_generated").value)
    tpot = registry.histogram("serving/tpot_ms")
    # one explicit detection poll: beats just landed, so a healthy run
    # must not flag (check_now is the deterministic poll the monitor's
    # background thread would run)
    if heartbeat.check_now() or heartbeat.hang_count != 0 or \
            registry.gauge("heartbeat/last_step").value is None:
        log(f"FAIL: heartbeat not beating cleanly (last_step="
            f"{registry.gauge('heartbeat/last_step').value}, "
            f"hangs={heartbeat.hang_count})")
        return 1
    # Timeline + per-request goodput (ISSUE 10): every phase-A request
    # must have a complete submit -> admit -> finish lifecycle on the
    # timeline, and the attribution must close the books.
    sgp = serving_goodput_report(recorder.events())
    for req in reqs:
        row = sgp["requests"].get(req.rid)
        if row is None or row["state"] != "finished":
            log(f"FAIL: request {req.rid} lifecycle incomplete on the "
                f"timeline: {row}")
            return 1
        if abs(row["queue_wait_s"] + row["active_s"]
               - (req.t_last_token - req.t_submit)) > 0.05:
            log(f"FAIL: request {req.rid} goodput split "
                f"{row} != engine-stamped wall "
                f"{req.t_last_token - req.t_submit:.3f}s")
            return 1
    if not (sgp["goodput_fraction"] and 0.0 < sgp["goodput_fraction"] <= 1.0):
        log(f"FAIL: serving goodput_fraction {sgp['goodput_fraction']}")
        return 1
    log(f"phase A OK: {len(wave)} requests token-identical to the "
        f"full-forward reference over the bf16 cache, {total} tokens, "
        f"1 decode compile, "
        f"tpot p50={tpot.percentile(50):.1f}ms p99={tpot.percentile(99):.1f}ms, "
        f"serving goodput {sgp['goodput_fraction']:.3f} "
        f"(active {sgp['totals']['active_s']:.3f}s / queue "
        f"{sgp['totals']['queue_wait_s']:.3f}s)")

    # ---- phase A2: int8 + speculative decoding at occupancy ----------
    # (ISSUE 12 + 13 in one leg.)  The phase-A wave plus a
    # template-heavy one (repeated motifs, so the n-gram proposer
    # actually fires) on an int8-quantized cache with k=2 drafting
    # armed and the pool undersized to ~half the worst-case demand:
    # eviction, preemption/recompute, drafting and the fused k+1
    # verify all fire mid-run, and every stream must STILL be
    # token-identical to the plain bf16 decode.  The wave_s reference
    # comes from the phase-A engine (proved identical to the
    # full-forward reference above) — the exact ISSUE 13 contract:
    # speculative output == non-speculative output, bitwise.
    from apex_tpu.serving import SpeculativeConfig

    motifs = [[7, 11], [3, 9, 4]]
    wave_s = [(m * 4, 6) for m in motifs]
    refs_s = [eng.submit(p, n) for p, n in wave_s]
    eng.run_until_drained(max_steps=1000)      # plain bf16 reference

    reg8 = MetricRegistry()
    eng8 = ServingEngine(
        cfg, ServingConfig(max_batch=3, block_size=4, max_seq=MAX_SEQ,
                           prefill_len=MAX_SEQ, n_blocks=8,
                           cache_dtype=jnp.int8,
                           speculative=SpeculativeConfig(k=2)),
        params, mesh=mesh, registry=reg8)
    reqs8 = [eng8.submit(p, n) for p, n in wave + wave_s]
    eng8.run_until_drained(max_steps=2000)
    for r8, ra in zip(reqs8, reqs + refs_s):
        if r8.state.value != "finished" or \
                r8.output_tokens != ra.output_tokens:
            log(f"FAIL: int8+spec request {r8.rid} {r8.state.value} "
                f"{r8.output_tokens} != plain bf16 {ra.output_tokens}")
            return 1
    if eng8.decode_compile_count() != 1:
        log("FAIL: eviction/preemption/acceptance churn recompiled the "
            "k+1 verify step")
        return 1
    eng8.scheduler.allocator.check()
    preempts = eng8.scheduler.preemptions
    evicts = eng8.scheduler.prefix_cache.evictions
    if preempts + evicts == 0:
        log("FAIL: the undersized pool exercised neither eviction nor "
            "preemption — the occupancy leg tested nothing")
        return 1
    if eng8.spec_proposed == 0:
        log("FAIL: the template wave never drafted — the speculative "
            "leg tested nothing")
        return 1
    log(f"phase A2 OK: int8 k=2 speculative streams token-identical to "
        f"plain bf16 at 8/15-block oversubscription "
        f"({preempts} preemptions, {evicts} evictions, "
        f"{eng8.spec_accepted}/{eng8.spec_proposed} drafts accepted, "
        "1 decode compile)")

    # ---- phase B: SIGTERM drain --------------------------------------
    # Same engine (same compiled programs — phase B costs zero extra
    # compiles, and a post-drain compile would trip the count check
    # below anyway); the guard attaches mid-life exactly like a real
    # deployment installing its signal handler.
    guard = PreemptionGuard()
    try:
        eng2 = eng
        eng2.guard = guard
        # 3 fill the batch, 2 must queue behind them
        running = [eng2.submit([3, 5, 7], 6), eng2.submit([11, 13], 6),
                   eng2.submit([2, 9, 4, 6], 6)]
        eng2.step()
        queued = [eng2.submit([17, 19], 6), eng2.submit([23], 6)]
        os.kill(os.getpid(), signal.SIGTERM)   # the real preemption signal
        eng2.run_until_drained(max_steps=200)
        if not eng2.draining:
            log("FAIL: SIGTERM did not put the engine into drain")
            return 1
        for req in running:
            if req.state.value != "finished" or \
                    len(req.output_tokens) != req.max_new_tokens:
                log(f"FAIL: in-flight request {req.rid} not delivered: "
                    f"{req.state} {req.output_tokens}")
                return 1
        for req in queued:
            if req.state.value != "cancelled":
                log(f"FAIL: queued request {req.rid} not cancelled: "
                    f"{req.state}")
                return 1
        # delivered responses still match the reference post-drain
        ref = greedy([3, 5, 7], 6)
        if running[0].output_tokens != ref:
            log(f"FAIL: drained output {running[0].output_tokens} != {ref}")
            return 1
        if eng2.decode_compile_count() != 1:
            log("FAIL: the drain path recompiled the decode step")
            return 1
    finally:
        guard.uninstall()
    # drain attribution: the cancelled requests must appear on the
    # timeline as drained (wholly wasted) request-seconds
    sgp = serving_goodput_report(recorder.events())
    for req in queued:
        row = sgp["requests"].get(req.rid)
        if row is None or row["state"] != "cancelled":
            log(f"FAIL: cancelled request {req.rid} not on the timeline "
                f"as cancelled: {row}")
            return 1
    if sgp["totals"]["cancelled"] < len(queued):
        log(f"FAIL: drain totals {sgp['totals']} missing cancellations")
        return 1
    timeline.disarm()
    log("phase B OK: SIGTERM drained — in-flight delivered, queue "
        "cancelled, drain attributed on the timeline")
    print("PASS", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
