"""Crash/resume smoke trainer — the end-to-end resilience proof.

A tiny but *real* run of the 3D-parallel GPT trainer
(:mod:`apex_tpu.transformer.testing.gpt_parallel_train`, sentinel armed)
— or, with ``--zero``, the flat-bucket ZeRO data-parallel trainer
(:func:`apex_tpu.parallel.distributed.zero_data_parallel_train_step`
with ``DistributedFusedAdam(flat_bucket=True)``) — on a virtual CPU
mesh, checkpointing every step through
:class:`apex_tpu.resilience.CheckpointManager` (async sharded saves —
the pod-scale path).  ``scripts/crash_resume_smoke.sh`` runs it three
ways: uninterrupted, SIGKILLed mid-run, and resumed — and asserts the
resumed loss curve is byte-identical to the uninterrupted one
(``tests/test_crash_resume.py`` drives the script in the fast tier).

**Elastic resume (ISSUE 6)**: every save embeds the
:class:`apex_tpu.resilience.reshard.ShardingSpec` logical-state
description, and the mesh shape is a command-line choice (``--tp``,
``--pp``, ``--devices`` for dp, ``--global-batch`` to keep the input
stream mesh-independent), so a ``--resume`` may run on a DIFFERENT
dp/tp/pp layout than the run that saved: ``restore_latest`` then
reshards — layer stacks re-factored across ``[vpp, pp]``, ZeRO flat
buckets re-chunked for the new world size — bit-losslessly.
``scripts/elastic_resume_smoke.sh`` drives the kill-at-mesh-N /
resume-at-mesh-M matrix; ``--fingerprint`` writes the canonical
mesh-independent state digest (:func:`apex_tpu.resilience.reshard.
load_logical` of the newest committed checkpoint, one
``"{leaf} {sha256}"`` line each) that the harness compares bitwise
across mesh shapes.

Per-step losses are appended to ``--losses`` as ``"{step} {fp32 bits as
hex}"`` lines (flushed + fsynced per line, so a SIGKILL loses at most
the in-flight line): hex bits make the bit-exact-resume comparison a
string equality, immune to repr rounding.

SIGTERM (preemption) is handled by
:class:`apex_tpu.resilience.PreemptionGuard`: drain the in-flight async
save, take a final synchronous checkpoint, exit 0.

Determinism: tokens for step ``i`` are ``fold_in(data_key, i)`` over the
GLOBAL batch, so any resume point replays the identical input stream on
any mesh shape; CPU XLA + bit-exact checkpoint round trips make the
whole curve reproducible bit-for-bit on a FIXED mesh.  Across a mesh
change the replayed *state* is bit-identical but the step arithmetic
re-associates (different dp reduction widths, tp matmul splits), so the
elastic harness compares a killed N→M run against a clean N→M reference
rather than against a single-mesh curve.
"""

from __future__ import annotations

import argparse
import os
import sys

VOCAB = 64
SEQ = 16


def _append_loss(path: str, step: int, loss) -> None:
    import numpy as np

    with open(path, "a") as f:
        f.write(f"{step} {np.float32(loss).tobytes().hex()}\n")
        f.flush()
        os.fsync(f.fileno())


def _truncate_losses(path: str, last_step: int) -> None:
    """Keep loss lines for steps <= ``last_step`` (a crash may have
    logged steps newer than the newest durable checkpoint)."""
    if not os.path.exists(path):
        return
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines()
                 if ln and int(ln.split()[0]) <= last_step]
    with open(path, "w") as f:
        f.write("".join(ln + "\n" for ln in lines))
        f.flush()
        os.fsync(f.fileno())


def _write_fingerprint(out_path: str, mgr) -> None:
    """Canonical mesh-independent digest of the newest committed
    checkpoint: one ``"{logical leaf} {sha256 of bytes}"`` line per
    leaf, sorted — two checkpoints of the same training state saved
    under different mesh shapes must produce identical files."""
    import hashlib

    import numpy as np

    from apex_tpu.resilience import reshard

    step = next((s for s in reversed(mgr.all_steps())
                 if mgr._is_committed(s)), None)
    if step is None:
        raise SystemExit("fingerprint: no committed checkpoint")
    leaves, at = reshard.load_logical(mgr._path(step))
    lines = [f"step {at}\n"]
    for key in sorted(leaves):
        arr = np.ascontiguousarray(leaves[key])
        digest = hashlib.sha256(arr.tobytes()).hexdigest()
        lines.append(f"{key} {arr.dtype} {list(arr.shape)} {digest}\n")
    with open(out_path, "w") as f:
        f.writelines(lines)
        f.flush()
        os.fsync(f.fileno())


def _build_gpt(args, mesh, jax):
    """The 3D GPT trainer legs: returns (pack, step_fn, data_fn, spec).

    With ``--tp``/``--pp`` > 1 the model grows to 2 layers / 4 heads so
    the same logical network factors as (pp=2, vpp=1) or (pp=1, vpp=2)
    and tp in {1, 2, 4} — the elastic transitions of the ISSUE 6 matrix.
    """
    from apex_tpu.amp.scaler import DynamicLossScale
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel.distributed import replicate
    from apex_tpu.resilience import reshard, sentinel_init
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import (
        build_gpt_3d,
        gpt3d_logical_folds,
    )

    dp = mesh.shape["dp"]
    model_parallel = args.tp > 1 or args.pp > 1
    num_layers = 2 if model_parallel else 1
    pp = mesh.shape["pp"]
    if num_layers % pp:
        raise SystemExit(f"num_layers {num_layers} not divisible by "
                         f"pp {pp}")
    cfg = TransformerConfig(
        hidden_size=32, num_layers=num_layers,
        num_attention_heads=4 if model_parallel else 2,
        padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
        hidden_dropout=0.0, attention_dropout=0.0,
        tensor_axis="tp" if args.tp > 1 else None,
        sequence_parallel=args.tp > 1)
    num_microbatches = 2
    init_fn, _, make_train_step = build_gpt_3d(
        cfg, num_chunks=num_layers // pp,
        num_microbatches=num_microbatches, mesh=mesh)

    batch = args.global_batch or dp * num_microbatches
    if batch % (dp * num_microbatches):
        raise SystemExit(f"global batch {batch} not divisible by "
                         f"dp*microbatches {dp * num_microbatches}")
    data_key = jax.random.PRNGKey(7)

    def data_fn(i):
        return jax.random.randint(jax.random.fold_in(data_key, i),
                                  (batch, SEQ), 0, VOCAB)

    params, specs = init_fn(jax.random.PRNGKey(0), data_fn(0))
    opt = FusedAdam(lr=1e-2)
    scaler = DynamicLossScale()
    # Commit optimizer/sentinel state to the mesh (replicated): restore
    # places leaves by the template's sharding, and a resumed step must
    # see the same device layout as the uninterrupted run.
    opt_state = replicate(opt.init(params), mesh)
    sent = replicate(sentinel_init(scaler), mesh)
    step_fn = jax.jit(make_train_step(opt, specs, scaler=scaler))

    pack = {"params": params, "opt": opt_state, "sent": sent}
    spec = reshard.build_spec(pack, mesh=mesh,
                              folds=gpt3d_logical_folds(pack))
    return pack, step_fn, data_fn, spec


def _build_zero(args, mesh, jax):
    """The flat-bucket ZeRO leg: a small dp-sharded regression whose
    optimizer state — per-(dtype-group, bucket) ``(rows, chunk)``
    buffers — is mesh-shape-DEPENDENT, the hard case of restore-anywhere
    (the buffers must be unflattened to logical leaves and re-chunked
    for the new dp world on resume)."""
    import jax.numpy as jnp

    from apex_tpu.amp.scaler import DynamicLossScale
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel.distributed import (
        dp_shard_batch,
        replicate,
        zero_data_parallel_train_step,
        zero_init,
    )
    from apex_tpu.resilience import reshard, sentinel_init

    dp = mesh.shape["dp"]
    batch = args.global_batch or 8
    if batch % dp:
        raise SystemExit(f"global batch {batch} not divisible by dp {dp}")

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    params = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (13, 7)),
        "b": jnp.zeros((7,)),
    }
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=2)
    scaler = DynamicLossScale()
    params = replicate(params, mesh)
    opt_state = zero_init(opt, params, mesh)
    sent = replicate(sentinel_init(scaler), mesh)
    inner = zero_data_parallel_train_step(loss_fn, opt, mesh=mesh,
                                          scaler=scaler)
    data_key = jax.random.PRNGKey(11)

    def data_fn(i):
        kx, ky = jax.random.split(jax.random.fold_in(data_key, i))
        return dp_shard_batch(
            (jax.random.normal(kx, (batch, 13)),
             jax.random.normal(ky, (batch, 7))), mesh)

    def step_fn(params, opt_state, batch, sent):
        return inner(params, opt_state, batch, sent)

    pack = {"params": params, "opt": opt_state, "sent": sent}
    spec = reshard.build_spec(
        pack, mesh=mesh, zero_states=[("opt", opt, params)])
    return pack, step_fn, data_fn, spec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--losses", required=True)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--devices", type=int, default=2)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel size (gpt mode)")
    ap.add_argument("--pp", type=int, default=1,
                    help="pipeline-parallel size (gpt mode)")
    ap.add_argument("--global-batch", type=int, default=0,
                    help="fixed global batch so the input stream is "
                         "identical on every mesh shape (0 = the legacy "
                         "dp-dependent default)")
    ap.add_argument("--zero", action="store_true",
                    help="flat-bucket ZeRO trainer instead of the 3D "
                         "GPT (dp-only mesh; optimizer buffers are "
                         "mesh-shape-dependent)")
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--resume", action="store_true",
                    help="restore the latest intact checkpoint — "
                         "resharding it onto THIS run's mesh shape if "
                         "it was saved under another — and continue "
                         "from the step after it")
    ap.add_argument("--flat", action="store_true",
                    help="flat single-file layout instead of sharded")
    ap.add_argument("--fingerprint", default=None,
                    help="after the run, write the mesh-independent "
                         "logical digest of the newest committed "
                         "checkpoint to this path")
    ap.add_argument("--step-delay", type=float, default=0.0,
                    help="sleep this many seconds per step while the "
                         "async save is in flight — gives an external "
                         "killer a deterministic window (a warm "
                         "compilation cache can otherwise finish the "
                         "whole run between two poll ticks)")
    args = ap.parse_args(argv)

    # Platform pinning must precede any backend use (same contract as
    # __graft_entry__.dryrun_multichip).
    from apex_tpu.utils.platform import force_host_device_count, pin_cpu

    force_host_device_count(max(args.devices, 1))
    pin_cpu()
    import jax
    import numpy as np

    # The smoke scripts launch this trainer several times (reference,
    # crash, resume) with identical programs: a persistent compilation
    # cache next to the checkpoint dir keeps later runs warm, which is
    # what keeps the whole save->SIGKILL->resume proof in the fast tier.
    try:
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(args.ckpt_dir)), ".xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # cache is an optimization, never a failure
        print(f"crash_resume: compilation cache unavailable ({e!r})",
              file=sys.stderr)

    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.resilience import CheckpointManager, PreemptionGuard

    devices = jax.devices("cpu")[: args.devices]
    if args.zero and (args.tp > 1 or args.pp > 1):
        raise SystemExit("--zero is dp-only (tp/pp must be 1)")
    mesh = mesh_lib.initialize_model_parallel(
        tensor_model_parallel_size=args.tp,
        pipeline_model_parallel_size=args.pp, devices=devices)

    build = _build_zero if args.zero else _build_gpt
    pack, step_fn, data_fn, spec = build(args, mesh, jax)

    mgr = CheckpointManager(args.ckpt_dir, keep=args.keep,
                            sharded=not args.flat, spec=spec)

    # Flight recorder (ISSUE 10): both trainer legs (3D GPT / flat-bucket
    # ZeRO) emit the run timeline when APEX_TPU_TIMELINE_DIR is set —
    # step intervals, sentinel skips, the checkpoint save/verify/restore
    # events from the manager, and the preemption/drain tail; the spill
    # survives the SIGKILL this harness exists to inject (torn-tail-only
    # loss).  Armed before the resume path so restores are on the
    # timeline too.
    from apex_tpu.observability import timeline

    recorder = timeline.arm_from_env()

    start = 0
    if args.resume:
        try:
            restored, at = mgr.restore_latest(pack)
            pack = restored
            start = at + 1
            _truncate_losses(args.losses, at)
            print(f"crash_resume: resumed from step {at}", file=sys.stderr)
        except FileNotFoundError as e:
            # Every checkpoint was lost (e.g. the crash plus injected
            # corruption destroyed the only save): restart from scratch —
            # determinism makes even this resume bit-exact.
            _truncate_losses(args.losses, -1)
            print(f"crash_resume: no intact checkpoint ({e}); "
                  "restarting from step 0", file=sys.stderr)

    params, opt_state, sent = pack["params"], pack["opt"], pack["sent"]

    def packed(p, s, z):
        return {"params": p, "opt": s, "sent": z}

    prev_skips = int(np.asarray(sent.skipped_steps))

    import time

    guard = PreemptionGuard()
    try:
        for i in range(start, args.steps):
            t_step = time.monotonic()
            params, opt_state, sent, loss = step_fn(params, opt_state,
                                                    data_fn(i), sent)
            loss = jax.block_until_ready(loss)
            step_s = time.monotonic() - t_step
            # No finiteness assert: the armed sentinel SKIPS an overflow
            # step rather than dying, and a non-finite reported loss is
            # deterministic, so the bit-exact curve comparison still
            # holds across resume.
            if not bool(np.isfinite(np.asarray(loss))):
                print(f"crash_resume: step {i} overflowed (skipped "
                      f"by sentinel)", file=sys.stderr)
            if recorder is not None:
                # the step event can only be emitted AFTER the skip
                # verdict is known — a sentinel-skipped step must land
                # in the goodput `skipped_step` bucket, not `compute`
                skips = int(np.asarray(sent.skipped_steps))
                skipped = skips > prev_skips
                recorder.emit("step", dur_s=step_s, step=i,
                              **({"skipped": True} if skipped else {}))
                if skipped:
                    recorder.sentinel_skip(i, skips)
                prev_skips = skips
            _append_loss(args.losses, i, loss)
            mgr.save_async(packed(params, opt_state, sent), i)
            if args.step_delay > 0:
                # sleep WHILE the async writer is in flight, so an
                # external SIGKILL can land mid-save
                time.sleep(args.step_delay)
            if guard.triggered:
                # drain the in-flight async save: step i is durable once
                # wait() returns (no redundant re-save in the grace
                # window)
                if recorder is not None:
                    recorder.preemption(step=i)
                with timeline.scope("drain", step=i):
                    mgr.wait()
                if recorder is not None:
                    recorder.flush()
                print(f"crash_resume: preempted, drained at step {i}, "
                      "clean exit", file=sys.stderr)
                return 0
        mgr.wait()
        if recorder is not None:
            recorder.flush()
    finally:
        guard.uninstall()
    if args.fingerprint:
        _write_fingerprint(args.fingerprint, mgr)
    print(f"crash_resume: completed {args.steps} steps "
          f"(skipped_steps={int(sent.skipped_steps)})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
