"""Crash/resume smoke trainer — the end-to-end resilience proof.

A tiny but *real* run of the 3D-parallel GPT trainer
(:mod:`apex_tpu.transformer.testing.gpt_parallel_train`, sentinel armed)
on a virtual CPU mesh, checkpointing every step through
:class:`apex_tpu.resilience.CheckpointManager` (async sharded saves —
the pod-scale path).  ``scripts/crash_resume_smoke.sh`` runs it three
ways: uninterrupted, SIGKILLed mid-run, and resumed — and asserts the
resumed loss curve is byte-identical to the uninterrupted one
(``tests/test_crash_resume.py`` drives the script in the fast tier).

Per-step losses are appended to ``--losses`` as ``"{step} {fp32 bits as
hex}"`` lines (flushed + fsynced per line, so a SIGKILL loses at most
the in-flight line): hex bits make the bit-exact-resume comparison a
string equality, immune to repr rounding.

SIGTERM (preemption) is handled by
:class:`apex_tpu.resilience.PreemptionGuard`: drain the in-flight async
save, take a final synchronous checkpoint, exit 0.

Determinism: tokens for step ``i`` are ``fold_in(data_key, i)``, so any
resume point replays the identical input stream; CPU XLA + bit-exact
checkpoint round trips make the whole curve reproducible bit-for-bit.
"""

from __future__ import annotations

import argparse
import os
import sys

VOCAB = 64
SEQ = 16


def _append_loss(path: str, step: int, loss) -> None:
    import numpy as np

    with open(path, "a") as f:
        f.write(f"{step} {np.float32(loss).tobytes().hex()}\n")
        f.flush()
        os.fsync(f.fileno())


def _truncate_losses(path: str, last_step: int) -> None:
    """Keep loss lines for steps <= ``last_step`` (a crash may have
    logged steps newer than the newest durable checkpoint)."""
    if not os.path.exists(path):
        return
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines()
                 if ln and int(ln.split()[0]) <= last_step]
    with open(path, "w") as f:
        f.write("".join(ln + "\n" for ln in lines))
        f.flush()
        os.fsync(f.fileno())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--losses", required=True)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--devices", type=int, default=2)
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--resume", action="store_true",
                    help="restore the latest intact checkpoint and "
                         "continue from the step after it")
    ap.add_argument("--flat", action="store_true",
                    help="flat single-file layout instead of sharded")
    ap.add_argument("--step-delay", type=float, default=0.0,
                    help="sleep this many seconds per step while the "
                         "async save is in flight — gives an external "
                         "killer a deterministic window (a warm "
                         "compilation cache can otherwise finish the "
                         "whole run between two poll ticks)")
    args = ap.parse_args(argv)

    # Platform pinning must precede any backend use (same contract as
    # __graft_entry__.dryrun_multichip).
    from apex_tpu.utils.platform import force_host_device_count, pin_cpu

    force_host_device_count(args.devices)
    pin_cpu()
    import jax
    import numpy as np

    # The smoke script launches this trainer three times (reference,
    # crash, resume) with identical programs: a persistent compilation
    # cache next to the checkpoint dir keeps runs 2 and 3 warm, which is
    # what keeps the whole save->SIGKILL->resume proof in the fast tier.
    try:
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(args.ckpt_dir)), ".xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # cache is an optimization, never a failure
        print(f"crash_resume: compilation cache unavailable ({e!r})",
              file=sys.stderr)

    from apex_tpu.amp.scaler import DynamicLossScale
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.resilience import (
        CheckpointManager,
        PreemptionGuard,
        sentinel_init,
    )
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    devices = jax.devices("cpu")[: args.devices]
    mesh = mesh_lib.initialize_model_parallel(devices=devices)  # all dp
    dp = mesh.shape["dp"]

    cfg = TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=2,
        padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
        hidden_dropout=0.0, attention_dropout=0.0)
    num_microbatches = 2
    init_fn, _, make_train_step = build_gpt_3d(
        cfg, num_chunks=1, num_microbatches=num_microbatches, mesh=mesh)

    batch = dp * num_microbatches
    data_key = jax.random.PRNGKey(7)
    sample = jax.random.randint(jax.random.fold_in(data_key, 0),
                                (batch, SEQ), 0, VOCAB)
    params, specs = init_fn(jax.random.PRNGKey(0), sample)
    opt = FusedAdam(lr=1e-2)
    scaler = DynamicLossScale()
    # Commit optimizer/sentinel state to the mesh (replicated): restore
    # places leaves by the template's sharding, and a resumed step must
    # see the same device layout as the uninterrupted run.
    from apex_tpu.parallel.distributed import replicate

    opt_state = replicate(opt.init(params), mesh)
    sent = replicate(sentinel_init(scaler), mesh)
    step_fn = jax.jit(make_train_step(opt, specs, scaler=scaler))

    mgr = CheckpointManager(args.ckpt_dir, keep=args.keep,
                            sharded=not args.flat)

    def pack(p, s, z):
        return {"params": p, "opt": s, "sent": z}

    start = 0
    if args.resume:
        try:
            restored, at = mgr.restore_latest(pack(params, opt_state, sent))
            params, opt_state, sent = (restored["params"], restored["opt"],
                                       restored["sent"])
            start = at + 1
            _truncate_losses(args.losses, at)
            print(f"crash_resume: resumed from step {at}", file=sys.stderr)
        except FileNotFoundError as e:
            # Every checkpoint was lost (e.g. the crash plus injected
            # corruption destroyed the only save): restart from scratch —
            # determinism makes even this resume bit-exact.
            _truncate_losses(args.losses, -1)
            print(f"crash_resume: no intact checkpoint ({e}); "
                  "restarting from step 0", file=sys.stderr)

    guard = PreemptionGuard()
    try:
        for i in range(start, args.steps):
            tokens = jax.random.randint(jax.random.fold_in(data_key, i),
                                        (batch, SEQ), 0, VOCAB)
            params, opt_state, sent, loss = step_fn(params, opt_state,
                                                    tokens, sent)
            loss = jax.block_until_ready(loss)
            # No finiteness assert: the armed sentinel SKIPS an overflow
            # step rather than dying, and a non-finite reported loss is
            # deterministic, so the bit-exact curve comparison still
            # holds across resume.
            if not bool(np.isfinite(np.asarray(loss))):
                print(f"crash_resume: step {i} overflowed (skipped "
                      f"by sentinel)", file=sys.stderr)
            _append_loss(args.losses, i, loss)
            mgr.save_async(pack(params, opt_state, sent), i)
            if args.step_delay > 0:
                # sleep WHILE the async writer is in flight, so an
                # external SIGKILL can land mid-save
                import time

                time.sleep(args.step_delay)
            if guard.triggered:
                # drain the in-flight async save: step i is durable once
                # wait() returns (no redundant re-save in the grace
                # window)
                mgr.wait()
                print(f"crash_resume: preempted, drained at step {i}, "
                      "clean exit", file=sys.stderr)
                return 0
        mgr.wait()
    finally:
        guard.uninstall()
    print(f"crash_resume: completed {args.steps} steps "
          f"(skipped_steps={int(sent.skipped_steps)})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
