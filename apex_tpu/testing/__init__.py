"""Test/validation harnesses (L1 stored-baseline traces, fault
injection, crash/resume smoke trainer).  Compiled-HLO inspection moved
to :mod:`apex_tpu.analysis` (ISSUE 4); ``testing.hlo`` re-exports it."""

from apex_tpu.testing import faults, hlo, l1  # noqa: F401
