"""Test/validation harnesses (L1 stored-baseline traces)."""

from apex_tpu.testing import l1  # noqa: F401
