"""Test/validation harnesses (L1 stored-baseline traces, compiled-HLO
inspection, fault injection, crash/resume smoke trainer)."""

from apex_tpu.testing import faults, hlo, l1  # noqa: F401
