"""Test/validation harnesses (L1 stored-baseline traces, compiled-HLO
inspection)."""

from apex_tpu.testing import hlo, l1  # noqa: F401
