"""Fleet-serving smoke (ISSUE 11): the 3-replica fault matrix, for real.

``tests/test_fleet.py`` proves the router's policy logic over in-memory
fakes; this smoke proves the same promises over THREE real replica
processes, each hosting a real ServingEngine on its own CPU mesh, with
real signals:

1. **Failover replay** — one replica is SIGKILLed mid-decode.  The
   router sees the dead process, consumes the tokens that flushed
   before death, and replays the in-flight remainders on the survivors.
   Every request must finish with a token stream **bitwise identical**
   to the uninterrupted full-forward greedy reference.
2. **Shed on overload** — a submit flood past the fleet bound comes
   back in the typed REJECTED terminal state (counted in
   ``serving/requests_rejected``); everything admitted still finishes.
   No request, shed or kept, is ever left hanging.
3. **Zero-downtime weight rollout** — a new checkpoint lands (plus a
   corrupt newer one); the fleet rolls one replica at a time through
   the SIGTERM drain → restore-newest-VERIFIED → rejoin ladder under a
   continuous request drip.  Zero failed requests (every one reaches a
   terminal state; the drip all FINISHES, token-identical), every
   replacement reports the fallback step (the corrupt newest was
   skipped), and p99 TPOT during the roll stays bounded vs steady
   state.
4. **Health contract** — ``/healthz`` on a live replica's debug server
   answers 200 ``ok``; the SIGKILLed one stops answering at all (the
   liveness half), and the kill is visible in the router's
   ``introspect()``.
5. **Socket transport under chaos** (ISSUE 14) — three fresh replicas
   served by ``replica_serve`` daemons over loopback framed TCP, each
   behind a ``ChaosProxy``.  Mid-decode, one replica's wire is
   PARTITIONED and another's host process is SIGKILLed; the router
   (unchanged) detects both through the same ladder, replays on the
   survivor, and every stream is token-identical to the in-process
   reference.  The daemons restore the newest VERIFIED checkpoint
   through the same handshake (the phase-C fallback step), proving the
   cross-host path end to end.

Run via ``scripts/fleet_smoke.sh``; wired fast-tier in
``tests/test_aux_subsystems.py`` (the serving-smoke pattern).

``FLEET_SMOKE_PHASES`` selects phases (default ``ABCD``; the
TRACE_SMOKE_PHASES precedent, ISSUE 18 tier-budget satellite): the fast
tier runs ``ABC`` — phase D stands up a second 3-daemon socket fleet on
top of the phase A-C fleet and was the single heaviest aux-tier phase —
while the slow-tier twin runs everything.  A/B/C stay one unit (they
share the fleet and C's rollout produces the checkpoints D asserts).
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# platform pinning must precede any jax import (conftest pattern); the
# replica children inherit this env through spawn
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms", "cpu")

VOCAB, MAX_SEQ = 64, 32
N_REPLICAS = int(os.environ.get("FLEET_SMOKE_REPLICAS", "3"))
PHASES = set(os.environ.get("FLEET_SMOKE_PHASES", "ABCD").upper())


def log(msg):
    print(f"fleet_smoke: {msg}", file=sys.stderr, flush=True)


def build_cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=MAX_SEQ,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)


def init_params(cfg, mesh):
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=cfg.num_layers,
                                 num_microbatches=1, mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))
    return params


def save_ckpt(ckpt_dir, params, step, mesh):
    """One spec-carrying sharded checkpoint (what the replicas restore
    through ``restore_gpt_for_serving``)."""
    from apex_tpu.resilience import CheckpointManager, reshard
    from apex_tpu.transformer.testing.gpt_parallel_train import (
        gpt3d_logical_folds,
    )

    tree = {"params": params, "step_count": np.asarray(step)}
    spec = reshard.build_spec(tree, mesh=mesh,
                              folds=gpt3d_logical_folds(tree))
    CheckpointManager(ckpt_dir, keep=8, sharded=True,
                      spec=spec).save(tree, step)


def make_reference(cfg, params):
    """Per-request full-forward greedy argmax over the host params (the
    serving_smoke reference, verbatim in spirit)."""
    from apex_tpu.ops.softmax import AttnMaskType
    from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        Embedding, ParallelTransformerLayer, parallel_lm_logits)

    host = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), params)
    embed = Embedding(cfg)
    layer = ParallelTransformerLayer(
        cfg, self_attn_mask_type=AttnMaskType.causal)
    ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon)
    L = cfg.num_layers
    cache = {}

    def greedy(prompt, n_new):
        key = (tuple(prompt), n_new)
        if key in cache:
            return cache[key]
        toks = list(prompt)
        for _ in range(n_new):
            t = jnp.asarray(np.asarray(toks, np.int32)[None, :])
            h = embed.apply({"params": host.embedding}, t)
            for vi in range(L):
                lp = jax.tree_util.tree_map(
                    lambda leaf: leaf.reshape((L,) + leaf.shape[2:])[vi],
                    host.layers)
                h = layer.apply({"params": lp}, h, None)
            h = ln.apply({"params": host.final_ln}, h)
            logits = parallel_lm_logits(
                h, host.embedding["word_embeddings"]["embedding"], cfg)
            toks.append(int(jnp.argmax(logits[-1, 0])))
        cache[key] = toks[len(prompt):]
        return cache[key]

    return greedy


def healthz(meta, timeout=10):
    """(code, payload) from a replica's /healthz, or (None, error)."""
    url = f"http://127.0.0.1:{meta['debug_port']}/healthz"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
    except Exception as e:
        return None, repr(e)


def check_identity(router, reqs, waves, greedy, phase):
    for req, (prompt, n_new) in zip(reqs, waves):
        ref = greedy(prompt, n_new)
        if req.output_tokens != ref:
            log(f"FAIL[{phase}]: request {req.rid} (replays="
                f"{req.replays}, reschedules={req.reschedules}) "
                f"{req.output_tokens} != reference {ref}")
            return False
    return True


def main() -> int:
    import shutil
    import tempfile

    from apex_tpu import parallel
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.serving import (
        FleetRouter, ReplicaProcess, ReplicaSpec, ServingConfig)
    from apex_tpu.serving.scheduler import RequestState
    from apex_tpu.testing import faults

    workdir = tempfile.mkdtemp(prefix="apex_fleet_smoke_")
    ckpt_dir = os.path.join(workdir, "ckpt")
    router = None
    try:
        cfg = build_cfg()
        mesh = parallel.initialize_model_parallel(
            tensor_model_parallel_size=1, devices=jax.devices()[:1])
        params = init_params(cfg, mesh)
        save_ckpt(ckpt_dir, params, 1, mesh)
        greedy = make_reference(cfg, params)
        rng = np.random.RandomState(17)

        spec = ReplicaSpec(
            config=cfg,
            serving=ServingConfig(max_batch=3, block_size=4,
                                  max_seq=MAX_SEQ, prefill_len=MAX_SEQ),
            tp=1, ckpt_dir=ckpt_dir)
        names = [f"r{i}" for i in range(N_REPLICAS)]
        t0 = time.monotonic()
        replicas = [ReplicaProcess(spec, n) for n in names]
        metas = {r.name: r.wait_ready(timeout=300) for r in replicas}
        log(f"{N_REPLICAS} replicas ready in "
            f"{time.monotonic() - t0:.1f}s, serving ckpt steps "
            f"{[m['ckpt_step'] for m in metas.values()]}")
        if any(m["ckpt_step"] != 1 for m in metas.values()):
            log(f"FAIL: initial fleet not on step 1: {metas}")
            return 1

        registry = MetricRegistry(rank=0, world=1)
        router = FleetRouter(
            replicas, max_queue_depth=12, replica_queue_limit=4,
            heartbeat_timeout_s=5.0, probe_retries=3,
            probe_backoff_s=0.25, registry=registry)
        router.pump()

        # ---- health contract --------------------------------------------
        code, payload = healthz(metas["r0"])
        if code != 200 or payload.get("status") != "ok":
            log(f"FAIL: /healthz on a live replica: {code} {payload}")
            return 1
        log(f"/healthz r0: {code} {payload}")

        # ---- phase A: SIGKILL mid-decode -> failover replay -------------
        waves_a = [
            (rng.randint(1, VOCAB - 1,
                         size=rng.randint(2, 9)).tolist(),
             int(rng.randint(10, 15)))   # long streams: a wide window
            for _ in range(4)]           # to land the kill mid-decode
        reqs_a = [router.submit(p, n) for p, n in waves_a]
        victim = None
        deadline = time.monotonic() + 60
        while victim is None:
            router.pump()
            for view in router._views.values():
                mid = [r for r in view.assigned.values()
                       if 1 <= len(r.output_tokens) < r.max_new_tokens]
                if mid:
                    victim = view
                    break
            if router.idle():
                log("FAIL: phase A drained before a mid-decode kill "
                    "window opened")
                return 1
            if time.monotonic() > deadline:
                log("FAIL: no request reached mid-decode in 60s")
                return 1
            time.sleep(0.001)
        in_flight = len(victim.assigned)
        victim.client.kill()          # SIGKILL: no drain, no goodbye
        log(f"SIGKILLed {victim.name} with {in_flight} in-flight "
            "request(s) mid-decode")
        router.run_until_idle(timeout_s=120)
        if not check_identity(router, reqs_a, waves_a, greedy, "A"):
            return 1
        replays = sum(r.replays for r in reqs_a)
        snap = registry.snapshot()
        if not (victim.down and snap.get("fleet/failovers") == 1.0
                and replays >= 1):
            log(f"FAIL: failover not recorded (down={victim.down}, "
                f"failovers={snap.get('fleet/failovers')}, "
                f"replays={replays})")
            return 1
        code, payload = healthz(metas[victim.name], timeout=2)
        if code is not None:
            log(f"FAIL: dead replica still answers /healthz: {code}")
            return 1
        log(f"phase A OK: {len(waves_a)} requests token-identical "
            f"through a SIGKILL ({replays} replayed; dead /healthz "
            "refuses connections)")

        # ---- phase B: shed on overload ----------------------------------
        flood = [router.submit([int(rng.randint(1, VOCAB - 1))], 2)
                 for _ in range(24)]
        shed = [r for r in flood if r.state is RequestState.REJECTED]
        kept = [r for r in flood if r.state is not RequestState.REJECTED]
        if not shed or not kept:
            log(f"FAIL: flood of {len(flood)} split shed={len(shed)} "
                f"kept={len(kept)} (bound never engaged?)")
            return 1
        if registry.snapshot().get("serving/requests_rejected") != \
                float(len(shed)):
            log("FAIL: serving/requests_rejected != shed count")
            return 1
        router.run_until_idle(timeout_s=120)
        if not all(r.state is RequestState.FINISHED for r in kept):
            log("FAIL: admitted flood requests did not all finish")
            return 1
        sample = kept[:3]
        if not check_identity(router, sample,
                              [(list(r.prompt), r.max_new_tokens)
                               for r in sample], greedy, "B"):
            return 1
        log(f"phase B OK: {len(shed)} shed with typed REJECTED + "
            f"counter, {len(kept)} admitted all finished")

        # ---- phase C: staggered weight rollout under load ---------------
        # steady-state TPOT window first (fresh registry)
        steady_reg = MetricRegistry(rank=0, world=1)
        router.registry = steady_reg
        waves_s = [
            (rng.randint(1, VOCAB - 1,
                         size=rng.randint(2, 9)).tolist(),
             int(rng.randint(4, 7)))
            for _ in range(8)]
        reqs_s = [router.submit(p, n) for p, n in waves_s]
        router.run_until_idle(timeout_s=120)
        if not check_identity(router, reqs_s, waves_s, greedy, "steady"):
            return 1
        p99_steady = steady_reg.histogram("fleet/tpot_ms").percentile(99)

        # training "rolls forward": step 2 lands (same weights, so one
        # reference covers the whole smoke), then a CORRUPT step 3 —
        # the newest-VERIFIED restore must fall back past it
        save_ckpt(ckpt_dir, params, 2, mesh)
        save_ckpt(ckpt_dir, params, 3, mesh)
        from apex_tpu.resilience import CheckpointManager

        step3 = CheckpointManager(ckpt_dir, sharded=True).step_path(3)
        faults.corrupt_checkpoint(step3, mode="bitflip")

        def factory(name):
            return ReplicaProcess(spec, name)

        roll_reg = MetricRegistry(rank=0, world=1)
        router.registry = roll_reg
        drip, budget = [], [8]

        def on_tick():
            if budget[0] > 0 and router.total_queue_depth() < 6:
                p = rng.randint(1, VOCAB - 1,
                                size=rng.randint(2, 7)).tolist()
                drip.append((router.submit(p, 4), (p, 4)))
                budget[0] -= 1

        t_roll = time.monotonic()
        rolled = router.rollout(factory, names=names, on_tick=on_tick,
                                drain_timeout_s=90, ready_timeout_s=300)
        router.run_until_idle(timeout_s=120)
        roll_s = time.monotonic() - t_roll
        if rolled != names:
            log(f"FAIL: rollout covered {rolled}, wanted {names}")
            return 1
        # zero failed requests: every drip request FINISHED (reschedules
        # are internal), token-identical; nothing open anywhere
        for req, _ in drip:
            if req.state is not RequestState.FINISHED:
                log(f"FAIL: roll-window request {req.rid} ended "
                    f"{req.state} (zero-failed violated)")
                return 1
        if not check_identity(router, [r for r, _ in drip],
                              [w for _, w in drip], greedy, "roll"):
            return 1
        open_reqs = [r.rid for r in router.requests.values()
                     if not r.done]
        if open_reqs:
            log(f"FAIL: non-terminal requests after the roll: "
                f"{open_reqs}")
            return 1
        # every replacement restored the newest VERIFIED step: the
        # corrupt step 3 was skipped, step 2 serves
        new_steps = {name: (view.meta or {}).get("ckpt_step")
                     for name, view in router._views.items()}
        if any(s != 2 for s in new_steps.values()):
            log(f"FAIL: rolled fleet not on the fallback step 2: "
                f"{new_steps}")
            return 1
        code, payload = healthz(router._views["r0"].meta)
        if code != 200:
            log(f"FAIL: rolled replica /healthz: {code} {payload}")
            return 1
        p99_roll = roll_reg.histogram("fleet/tpot_ms").percentile(99)
        # bounded, not unchanged: a roll removes 1/N of fleet capacity
        # and replays queued work, so give it generous-but-real headroom
        # over the CPU mesh's noisy steady state
        bound_ms = max(8.0 * (p99_steady or 0.0), 500.0)
        if p99_roll is None or p99_roll > bound_ms:
            log(f"FAIL: p99 TPOT during the roll {p99_roll}ms exceeds "
                f"bound {bound_ms:.0f}ms (steady {p99_steady}ms)")
            return 1
        log(f"phase C OK: staggered roll of {len(names)} replicas in "
            f"{roll_s:.1f}s under load — {len(drip)} drip requests all "
            f"finished token-identical, corrupt newest skipped "
            f"(fleet on step 2), p99 TPOT {p99_roll:.1f}ms during the "
            f"roll vs {p99_steady:.1f}ms steady (bound "
            f"{bound_ms:.0f}ms)")

        snap = router.introspect()
        log(f"final fleet state: {json.dumps(snap['replicas'])}")
        router.close()        # free the mp fleet's processes before the
        router = None         # socket fleet spawns its own engines

        # ---- phase D: socket transport through chaos (ISSUE 14) ---------
        # Three fresh replicas behind replica_serve daemons on loopback
        # framed TCP, each wire through a ChaosProxy; one replica
        # PARTITIONED and another SIGKILLed mid-decode — the router is
        # byte-for-byte the one that drove phases A-C, which is the
        # point: the contract is transport-agnostic.
        if "D" not in PHASES:
            log(f"phase D skipped (FLEET_SMOKE_PHASES="
                f"{''.join(sorted(PHASES))})")
            print("PASS", file=sys.stderr, flush=True)
            return 0
        from apex_tpu.data._producer import reap_process
        from apex_tpu.serving.transport import (
            SocketTransport, start_replica_server)
        from apex_tpu.testing.faults import ChaosProxy

        t_d = time.monotonic()
        sock_names = ["s0", "s1", "s2"]
        procs, proxies = {}, {}
        sock_router = None
        try:
            started = {n: start_replica_server(spec, n,
                                               addr_timeout_s=300)
                       for n in sock_names}
            procs = {n: p for n, (p, _) in started.items()}
            proxies = {n: ChaosProxy(addr)
                       for n, (_, addr) in started.items()}
            clients = [SocketTransport(n, proxies[n].address,
                                       backoff_initial_s=0.05,
                                       ping_every_s=0.2)
                       for n in sock_names]
            metas_d = {c.name: c.wait_ready(timeout=300)
                       for c in clients}
            log(f"3 socket replicas ready in "
                f"{time.monotonic() - t_d:.1f}s, ckpt steps "
                f"{[m['ckpt_step'] for m in metas_d.values()]}")
            if any(m["ckpt_step"] != 2 for m in metas_d.values()):
                log(f"FAIL: socket fleet not on the fallback step 2: "
                    f"{metas_d}")
                return 1
            reg_d = MetricRegistry(rank=0, world=1)
            sock_router = FleetRouter(
                clients, max_queue_depth=12, replica_queue_limit=4,
                heartbeat_timeout_s=2.0, probe_retries=2,
                probe_backoff_s=0.25, registry=reg_d)
            waves_d = [
                (rng.randint(1, VOCAB - 1,
                             size=rng.randint(2, 9)).tolist(),
                 int(rng.randint(10, 15)))
                for _ in range(4)]
            reqs_d = [sock_router.submit(p, n) for p, n in waves_d]
            partitioned = killed = None
            deadline = time.monotonic() + 90
            while partitioned is None or killed is None:
                sock_router.pump()
                for view in sock_router._views.values():
                    if view.down:
                        continue
                    mid = [r for r in view.assigned.values()
                           if 1 <= len(r.output_tokens)
                           < r.max_new_tokens]
                    if not mid:
                        continue
                    if partitioned is None:
                        partitioned = view.name
                        proxies[view.name].partition()
                    elif killed is None and view.name != partitioned:
                        killed = view.name
                        procs[view.name].kill()   # SIGKILL the host
                if sock_router.idle():
                    log("FAIL: phase D drained before both faults "
                        "landed mid-decode")
                    return 1
                if time.monotonic() > deadline:
                    log(f"FAIL: no mid-decode fault window in 90s "
                        f"(partitioned={partitioned}, killed={killed})")
                    return 1
                time.sleep(0.001)
            log(f"partitioned {partitioned}'s wire, SIGKILLed "
                f"{killed}'s host, both mid-decode")
            sock_router.run_until_idle(timeout_s=180)
            if not check_identity(sock_router, reqs_d, waves_d, greedy,
                                  "D"):
                return 1
            snap_d = reg_d.snapshot()
            down = {n: v.down for n, v in sock_router._views.items()}
            if not (down[partitioned] and down[killed]
                    and snap_d.get("fleet/failovers") == 2.0):
                log(f"FAIL: socket failovers not recorded "
                    f"(down={down}, "
                    f"failovers={snap_d.get('fleet/failovers')})")
                return 1
            replays_d = sum(r.replays for r in reqs_d)
            log(f"phase D OK: {len(waves_d)} streams token-identical "
                f"over framed TCP through a partition + a SIGKILL "
                f"({replays_d} replayed; socket fleet on step 2) in "
                f"{time.monotonic() - t_d:.1f}s")
        finally:
            if sock_router is not None:
                sock_router.close()
            for proxy in proxies.values():
                proxy.close()
            for n, p in procs.items():
                try:
                    p.terminate()      # SIGTERM: guard drain, exit 0
                except Exception:
                    pass
                reap_process(p, 15.0, what=f"socket replica {n}")

        print("PASS", file=sys.stderr, flush=True)
        return 0
    finally:
        if router is not None:
            router.close()
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
