"""Distributed-tracing smoke (ISSUE 15): the tracing plane, for real.

``tests/test_trace.py`` proves the stitcher over synthesized spills;
this smoke proves the whole plane over THREE real ``replica_serve``
daemons on loopback framed TCP, with tracing armed in every process
and a real SIGKILL mid-decode:

**Phase A — one merged trace through a kill.**  Requests flow through
the socket fleet; one daemon's host process is SIGKILLed while a
request is mid-decode.  After the fleet drains, the spill directory
(router + 3 replica files, the victim's torn at the kill) merges
strictly into one trace per request — and the killed request's single
trace spans BOTH replicas (attempts >= 2) with ``failover_replay``
time attributed and the books exactly closed (overcommit 0,
unattributed 0).

**Phase B — hop sums vs the router-side stopwatch.**  Every request's
hop-bucket sum must equal its trace wall-clock exactly AND match an
independent host stopwatch around submit→terminal within 2% (+a small
absolute cushion for sub-100ms streams) — the per-request goodput
books checked against an outside clock, not just against themselves.

**Phase C — the aggregation plane.**  ``/fleet/statusz`` on a
DebugServer wrapping the router serves per-tenant SLO percentiles and
merged replica state over HTTP, and ``scripts/trace_report.py``
(subprocess — the operator's actual entry point) parses the spill dir
strictly and exits 0.

Run via ``scripts/trace_smoke.sh``; wired fast-tier in
``tests/test_aux_subsystems.py`` (the fleet-smoke pattern).
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms", "cpu")

VOCAB, MAX_SEQ = 64, 32
REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(f"trace_smoke: {msg}", file=sys.stderr, flush=True)


def build_cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=MAX_SEQ,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)


def main() -> int:
    import shutil
    import tempfile

    from apex_tpu.data._producer import reap_process
    from apex_tpu.observability import timeline
    from apex_tpu.observability.debug_server import DebugServer
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.observability.trace import arm_process, merge_dir
    from apex_tpu.serving import FleetRouter, ReplicaSpec, ServingConfig
    from apex_tpu.serving.scheduler import RequestState
    from apex_tpu.serving.transport import (
        SocketTransport, start_replica_server)

    workdir = tempfile.mkdtemp(prefix="apex_trace_smoke_")
    trace_dir = os.path.join(workdir, "trace")
    rng = np.random.RandomState(23)
    router = None
    srv = None
    procs = {}
    try:
        recorder = arm_process(trace_dir, "router", "router")
        spec = ReplicaSpec(
            config=build_cfg(),
            serving=ServingConfig(max_batch=3, block_size=4,
                                  max_seq=MAX_SEQ, prefill_len=MAX_SEQ),
            tp=1, ckpt_dir=None, debug_server=False,
            timeline_dir=trace_dir, timeline_tick_every=1,
            history_every_s=0.05)
        names = ["s0", "s1", "s2"]
        t0 = time.monotonic()
        started = {n: start_replica_server(spec, n, addr_timeout_s=300)
                   for n in names}
        procs = {n: p for n, (p, _) in started.items()}
        clients = [SocketTransport(n, addr, backoff_initial_s=0.05,
                                   ping_every_s=0.05)
                   for n, (_, addr) in started.items()]
        for c in clients:
            c.wait_ready(timeout=300)
        log(f"3 traced socket replicas ready in "
            f"{time.monotonic() - t0:.1f}s")
        registry = MetricRegistry(rank=0, world=1)
        # longitudinal history + a deliberately loose SLO (ISSUE 20):
        # the real fleet exercises the sample/export/ingest wire and
        # the evaluator's snapshot cadence; the huge objective keeps
        # the burn at zero so slo_report's --check gate must pass
        from apex_tpu.observability.slo import SLOPolicy

        router = FleetRouter(clients, max_queue_depth=24,
                             replica_queue_limit=3,
                             heartbeat_timeout_s=2.0, probe_retries=2,
                             probe_backoff_s=0.25, registry=registry,
                             history_every_s=0.05,
                             slo_policies=[SLOPolicy(
                                 name="smoke-ttft",
                                 metric="fleet/ttft_ms:p99",
                                 objective=1e9,
                                 fast_window_s=1.0, slow_window_s=5.0,
                                 compliance_window_s=60.0)])

        # ---- traced wave + SIGKILL mid-decode -----------------------
        waves = [(rng.randint(1, VOCAB - 1,
                              size=rng.randint(2, 8)).tolist(),
                  int(rng.randint(10, 15))) for _ in range(5)]
        stopwatch = {}
        reqs = []
        for prompt, n_new in waves:
            t_sub = time.monotonic()
            req = router.submit(prompt, n_new, tenant="acme")
            stopwatch[req.rid] = [t_sub, None]
            reqs.append(req)
        if any(r.trace_id is None for r in reqs):
            log("FAIL: armed router minted no trace_id")
            return 1

        killed = None
        deadline = time.monotonic() + 90
        while True:
            router.pump()
            now = time.monotonic()
            for req in reqs:
                if req.done and stopwatch[req.rid][1] is None:
                    stopwatch[req.rid][1] = now
            if killed is None:
                for view in router._views.values():
                    mid = [r for r in view.assigned.values()
                           if 1 <= len(r.output_tokens)
                           < r.max_new_tokens]
                    if mid and not view.down:
                        killed = view.name
                        procs[killed].kill()   # SIGKILL the host
                        log(f"SIGKILLed {killed} mid-decode "
                            f"({len(mid)} in flight)")
                        break
            if all(r.done for r in reqs):
                break
            if now > deadline:
                log(f"FAIL: wave not terminal in 90s (killed={killed})")
                return 1
            time.sleep(0.001)
        if killed is None:
            log("FAIL: wave drained before a mid-decode kill window")
            return 1
        if not all(r.state is RequestState.FINISHED for r in reqs):
            log(f"FAIL: non-finished states "
                f"{[r.state for r in reqs]}")
            return 1
        survivors = sum(r.replays for r in reqs)
        if survivors < 1:
            log("FAIL: the kill produced no failover replay")
            return 1

        # ---- phase C first (the router must still be live) ----------
        srv = DebugServer(registry=registry, engine=router).start()
        with urllib.request.urlopen(srv.url("/fleet/statusz"),
                                    timeout=10) as resp:
            plane = json.loads(resp.read())
        slo = plane["slo"]["tenants"].get("acme")
        if (resp.status != 200 or slo is None
                or slo["finished"] != len(reqs)
                or slo["ttft_ms"]["p99"] is None):
            log(f"FAIL: /fleet/statusz SLO plane: {plane}")
            return 1
        if not plane["totals"]["failovers"] >= 1:
            log(f"FAIL: failover not on the plane: {plane['totals']}")
            return 1
        log(f"phase C OK: /fleet/statusz serves acme SLO "
            f"(ttft p99 {slo['ttft_ms']['p99']:.1f}ms, "
            f"{slo['finished']} finished) + "
            f"{plane['totals']['failovers']} failover")

        # ---- drain the fleet so every spill closes cleanly ----------
        router.close()
        router = None
        for n, p in procs.items():
            try:
                p.terminate()          # SIGTERM: guard drain, run_end
            except Exception:
                pass
            reap_process(p, 20.0, what=f"traced replica {n}")
        procs = {}
        timeline.disarm()
        recorder.flush()

        # ---- phase A: strict merge, one trace through the kill ------
        report = merge_dir(trace_dir, strict=True)
        traces = report["traces"]
        by_rid = {rec["rid"]: rec for rec in traces.values()}
        if sorted(by_rid) != sorted(r.rid for r in reqs):
            log(f"FAIL: merged rids {sorted(by_rid)} != submitted "
                f"{sorted(r.rid for r in reqs)}")
            return 1
        killed_traces = [rec for rec in traces.values()
                         if rec["attempts"] >= 2]
        if not killed_traces:
            log("FAIL: no merged trace shows a re-dispatch")
            return 1
        for rec in traces.values():
            if rec["state"] != "finished":
                log(f"FAIL: trace {rec['trace_id']} state "
                    f"{rec['state']}")
                return 1
            if rec["overcommit_s"] != 0 or rec["unattributed_s"] != 0:
                log(f"FAIL: books not closed: {rec}")
                return 1
        for rec in killed_traces:
            if len(rec["replicas"]) < 2:
                log(f"FAIL: replayed trace stayed on one replica: "
                    f"{rec['replicas']}")
                return 1
            if rec["hops"]["failover_replay"] <= 0:
                log(f"FAIL: no failover_replay time attributed: "
                    f"{rec['hops']}")
                return 1
        log(f"phase A OK: {len(traces)} merged traces, "
            f"{len(killed_traces)} spanning both replicas through the "
            f"SIGKILL (failover_replay "
            f"{killed_traces[0]['hops']['failover_replay']:.3f}s), "
            "books closed exactly")

        # ---- phase B: hop sums vs the router-side stopwatch ---------
        for req in reqs:
            rec = by_rid[req.rid]
            hop_sum = sum(rec["hops"].values())
            if abs(hop_sum - rec["wall_s"]) > 1e-5:
                log(f"FAIL: hop sum {hop_sum} != wall {rec['wall_s']}")
                return 1
            t_sub, t_done = stopwatch[req.rid]
            watch = t_done - t_sub
            # 2% + a small absolute cushion (the stopwatch brackets the
            # submit call and the post-pump done observation)
            if abs(hop_sum - watch) > 0.02 * watch + 0.015:
                log(f"FAIL: rid {req.rid} hop sum {hop_sum:.4f}s vs "
                    f"stopwatch {watch:.4f}s exceeds 2%")
                return 1
        log(f"phase B OK: {len(reqs)} requests' hop sums match the "
            "router stopwatch within 2%")

        # ---- the operator entry point parses the same dir -----------
        cli = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_report.py"),
             trace_dir, "--check"],
            capture_output=True, timeout=120)
        if cli.returncode != 0:
            log(f"FAIL: trace_report.py rc={cli.returncode}: "
                f"{cli.stderr.decode(errors='replace')[-500:]}")
            return 1
        if b"check ok" not in cli.stderr:
            log("FAIL: trace_report.py --check printed no verdict: "
                f"{cli.stderr.decode(errors='replace')[-500:]}")
            return 1
        log("trace_report.py output (--check passed):\n"
            + cli.stdout.decode(errors="replace"))

        # ---- the SLO plane's operator entry point (ISSUE 20) --------
        slo_cli = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "slo_report.py"),
             trace_dir, "--check"],
            capture_output=True, timeout=120)
        if slo_cli.returncode != 0:
            log(f"FAIL: slo_report.py rc={slo_cli.returncode}: "
                f"{slo_cli.stderr.decode(errors='replace')[-500:]}")
            return 1
        if b"check ok" not in slo_cli.stderr:
            log("FAIL: slo_report.py --check printed no verdict: "
                f"{slo_cli.stderr.decode(errors='replace')[-500:]}")
            return 1
        log("slo_report.py output (--check passed):\n"
            + slo_cli.stdout.decode(errors="replace"))

        # ---- tier gating (ISSUE 17 satellite) -----------------------
        # Phase D stands up a SECOND fleet (4 more daemons, 4 more
        # engine compiles) and was the single slowest fast-tier phase;
        # TRACE_SMOKE_PHASES=ABC keeps the kill/merge/statusz coverage
        # in the fast tier and defers the disagg leg to the slow tier
        # (tests/test_aux_subsystems.py runs both).
        phases = os.environ.get("TRACE_SMOKE_PHASES", "ABCD").upper()
        if "D" not in phases:
            log(f"phase D skipped (TRACE_SMOKE_PHASES={phases})")
            print("PASS", file=sys.stderr, flush=True)
            return 0

        # ---- phase D: the disaggregated 2-prefill/2-decode fleet ----
        # (ISSUE 16) — the kv_migrate hop on REAL daemons: prefill
        # replicas admit, KV runs stream to the decode side, and the
        # merged traces must carry kv_migrate time while the books
        # still close against the host stopwatch.
        import dataclasses

        trace_dir2 = os.path.join(workdir, "trace_disagg")
        recorder2 = arm_process(trace_dir2, "router", "router")
        roles = {"p0": "prefill", "p1": "prefill",
                 "d0": "decode", "d1": "decode"}
        t0 = time.monotonic()
        started2 = {
            n: start_replica_server(
                dataclasses.replace(spec, role=role,
                                    timeline_dir=trace_dir2),
                n, addr_timeout_s=300)
            for n, role in roles.items()}
        procs = {n: p for n, (p, _) in started2.items()}
        clients2 = [SocketTransport(n, addr, backoff_initial_s=0.05,
                                    ping_every_s=0.05)
                    for n, (_, addr) in started2.items()]
        for c in clients2:
            c.wait_ready(timeout=300)
        log(f"2-prefill/2-decode fleet ready in "
            f"{time.monotonic() - t0:.1f}s")
        registry2 = MetricRegistry(rank=0, world=1)
        router = FleetRouter(clients2, max_queue_depth=24,
                             replica_queue_limit=3,
                             heartbeat_timeout_s=2.0, probe_retries=2,
                             probe_backoff_s=0.25, registry=registry2)
        waves2 = [(rng.randint(1, VOCAB - 1,
                               size=rng.randint(2, 8)).tolist(),
                   int(rng.randint(12, 16))) for _ in range(4)]
        stopwatch2 = {}
        reqs2 = []
        for prompt, n_new in waves2:
            t_sub = time.monotonic()
            req = router.submit(prompt, n_new, tenant="acme")
            stopwatch2[req.rid] = [t_sub, None]
            reqs2.append(req)
        deadline = time.monotonic() + 120
        while True:
            router.pump()
            now = time.monotonic()
            for req in reqs2:
                if req.done and stopwatch2[req.rid][1] is None:
                    stopwatch2[req.rid][1] = now
            if all(r.done for r in reqs2):
                break
            if now > deadline:
                log("FAIL: disagg wave not terminal in 120s")
                return 1
            time.sleep(0.001)
        if not all(r.state is RequestState.FINISHED for r in reqs2):
            log(f"FAIL: disagg states {[r.state for r in reqs2]}")
            return 1
        # let the trailing kv_acks land before tearing the fleet down
        t_settle = time.monotonic() + 5
        while router._migrations and time.monotonic() < t_settle:
            router.pump()
            time.sleep(0.001)
        snap2 = registry2.snapshot()
        if snap2.get("fleet/kv_migrate_completed", 0.0) < 1:
            log(f"FAIL: no completed migration (started "
                f"{snap2.get('fleet/kv_migrate_started', 0.0)})")
            return 1
        if snap2.get("fleet/failovers", 0.0) != 0:
            log("FAIL: disagg wave tripped a failover")
            return 1
        router.close()
        router = None
        for n, p in procs.items():
            try:
                p.terminate()
            except Exception:
                pass
            reap_process(p, 20.0, what=f"disagg replica {n}")
        procs = {}
        timeline.disarm()
        recorder2.flush()
        report2 = merge_dir(trace_dir2, strict=True)
        by_rid2 = {rec["rid"]: rec
                   for rec in report2["traces"].values()}
        migrated_traces = 0
        for req in reqs2:
            rec = by_rid2[req.rid]
            if rec["state"] != "finished":
                log(f"FAIL: disagg trace {rec['trace_id']} state "
                    f"{rec['state']}")
                return 1
            if rec["overcommit_s"] != 0 or rec["unattributed_s"] != 0:
                log(f"FAIL: disagg books not closed: {rec}")
                return 1
            hop_sum = sum(rec["hops"].values())
            if abs(hop_sum - rec["wall_s"]) > 1e-5:
                log(f"FAIL: disagg hop sum {hop_sum} != wall "
                    f"{rec['wall_s']}")
                return 1
            t_sub, t_done = stopwatch2[req.rid]
            watch = t_done - t_sub
            if abs(hop_sum - watch) > 0.02 * watch + 0.015:
                log(f"FAIL: disagg rid {req.rid} hop sum "
                    f"{hop_sum:.4f}s vs stopwatch {watch:.4f}s "
                    "exceeds 2%")
                return 1
            if rec["hops"]["kv_migrate"] > 0:
                migrated_traces += 1
                if len(rec["replicas"]) < 2:
                    log(f"FAIL: migrated trace stayed on one "
                        f"replica: {rec['replicas']}")
                    return 1
        if migrated_traces < 1:
            log("FAIL: no merged trace carries kv_migrate time")
            return 1
        cli2 = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_report.py"),
             trace_dir2],
            capture_output=True, timeout=120)
        if cli2.returncode != 0:
            log(f"FAIL: trace_report.py (disagg) rc="
                f"{cli2.returncode}: "
                f"{cli2.stderr.decode(errors='replace')[-500:]}")
            return 1
        log(f"phase D OK: {len(reqs2)} requests through the "
            f"2-prefill/2-decode fleet, {migrated_traces} traces "
            "carrying kv_migrate time, hop sums within 2% of the "
            "stopwatch, books closed")

        print("PASS", file=sys.stderr, flush=True)
        return 0
    finally:
        timeline.disarm()
        if srv is not None:
            srv.close()
        if router is not None:
            router.close()
        from apex_tpu.data._producer import reap_process
        for n, p in procs.items():
            try:
                p.terminate()
            except Exception:
                pass
            reap_process(p, 15.0, what=f"traced replica {n}")
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
