"""Input-pipeline smoke: every layer of apex_tpu.data, end to end.

Driven by ``scripts/data_pipeline_smoke.sh`` (and the fast tier through
``tests/test_aux_subsystems.py``): builds a small synthetic JPEG tree
and a packed LM token stream, pushes both through the production stack —
process-pool decode, ``DataService`` loader process, double-buffered
``prefetch_to_device`` — and asserts the two properties a smoke can
prove cheaply:

- **nonzero overlap**: a paced consumer's steady-state stall through the
  double-buffered prefetcher is well under the synchronous (depth=0)
  pull time on the same loader — decode/transfer really do hide under
  the consumer's step;
- **clean shutdown**: after ``close()``, no loader worker processes and
  no service process survive (``multiprocessing.active_children()``
  empty), and the process exits 0 without leaked threads wedging
  interpreter teardown.
"""

from __future__ import annotations

import os
import sys
import time

if __name__ == "__main__":  # runnable as a plain script path
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))))


def _build_jpeg_tree(root: str, n_classes: int = 2, per_class: int = 48,
                     side: int = 224) -> None:
    import numpy as np
    from PIL import Image

    rng = np.random.RandomState(0)
    for c in range(n_classes):
        d = os.path.join(root, f"class_{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 256, (side, side, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.jpg"),
                                      quality=90)


def _service_factory(prefix: str, consumed: int):
    """Module-level (picklable) DataService factory for the LM stream."""
    from apex_tpu.data import PackedSequenceDataset, PackedSequenceLoader

    return PackedSequenceLoader(PackedSequenceDataset(prefix),
                                local_batch=4, consumed_samples=consumed)


def main(work: str) -> int:
    import multiprocessing as mp

    import numpy as np

    from apex_tpu.data import (
        DataService,
        ImageFolder,
        ImageFolderLoader,
        pack_token_documents,
        prefetch_to_device,
        segment_loss_mask,
        synthetic_token_documents,
    )
    from apex_tpu.observability.metrics import MetricRegistry

    os.makedirs(work, exist_ok=True)
    jpeg_root = os.path.join(work, "jpegs")
    _build_jpeg_tree(jpeg_root)
    ds = ImageFolder(jpeg_root)

    # --- image leg: process-pool decode + double-buffered prefetch -----
    def stall_at(depth: int) -> float:
        # 2 workers on a 16-image 224px batch: several ms of real decode
        # per batch, so the overlap assertion has margin over timer
        # jitter (a paced consumer hides it entirely at depth 2; a
        # synchronous depth-0 pull pays it at next())
        reg = MetricRegistry(rank=0, world=1)
        with ImageFolderLoader(ds, local_batch=16, image_size=128, seed=1,
                               workers=2, backend="process") as loader:
            loader.warm_up()
            dev = prefetch_to_device(loader, depth=depth,
                                     place=lambda b: b, registry=reg)
            try:
                next(dev)  # cold batch
                total = 0.0
                for _ in range(2):
                    time.sleep(0.05)  # the "train step"
                    t0 = time.perf_counter()
                    next(dev)
                    total += time.perf_counter() - t0
                return total / 2 * 1e3
            finally:
                dev.close(close_source=False)

    sync_ms = stall_at(0)
    overlapped_ms = stall_at(2)
    print(f"image leg: stall {overlapped_ms:.2f} ms double-buffered vs "
          f"{sync_ms:.2f} ms synchronous", file=sys.stderr)
    assert overlapped_ms < sync_ms, (
        "no overlap: double-buffered stall did not beat synchronous "
        f"({overlapped_ms:.2f} >= {sync_ms:.2f} ms)")

    # --- LM leg: packed token stream through a DataService -------------
    prefix = os.path.join(work, "lm", "train")
    docs = synthetic_token_documents(64, vocab=256, mean_len=48, seed=2)
    sds = pack_token_documents(docs, prefix, seq_len=64, eos_id=255)
    import functools

    with DataService(functools.partial(_service_factory, prefix)) as svc:
        dev = prefetch_to_device(svc, depth=2, place=lambda b: b)
        n_tok = 0
        t0 = time.perf_counter()
        for _ in range(6):  # crosses the ~12-batch epoch? no: stays in it
            tokens, segments = next(dev)
            assert tokens.shape == (4, 64) and segments.shape == (4, 64)
            m = segment_loss_mask(segments)
            assert 0.0 < float(np.mean(m)) <= 1.0
            n_tok += tokens.size
        dt = time.perf_counter() - t0
        dev.close()  # passthrough closes the service too
    print(f"lm leg: {n_tok / dt:.0f} tokens/sec through "
          "DataService -> prefetch_to_device", file=sys.stderr)

    # --- clean shutdown -------------------------------------------------
    deadline = time.monotonic() + 15.0
    while mp.active_children() and time.monotonic() < deadline:
        time.sleep(0.1)
    leftover = mp.active_children()
    assert not leftover, f"leaked child processes: {leftover}"
    print("data_pipeline_smoke OK: overlap proven, shutdown clean",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else
                  os.path.join("/tmp", "apex_tpu_data_smoke")))
