"""Fault injection — make the failure paths testable on a laptop.

The resilience layer (:mod:`apex_tpu.resilience`) claims to survive
non-finite gradients, torn/corrupted checkpoints, dying async writers,
flaky filesystems, and SIGTERM preemption.  Claims proven by inspection
rot; this module injects each failure deterministically so the fast tier
drives save→kill→resume and corrupt→fallback→resume end to end:

- :func:`poison_grads` — jit-safe NaN/Inf injection into a gradient tree
  at a chosen step (a ``jnp.where`` on the step counter: the injection
  itself compiles into the train step, so the sentinel is tested inside
  the very program it guards);
- :func:`bitflip_file` / :func:`truncate_file` /
  :func:`corrupt_checkpoint` — storage damage (single flipped bit in the
  array payload, torn tail) that per-array checksums must catch;
- :func:`transient_os_errors` — a wrapped filesystem raising
  ``OSError`` from the first N matching operations (the NFS/GCS-fuse
  blip the manager's retry-with-backoff exists for), scoped by path
  prefix so only checkpoint traffic is hit;
- :func:`hung_writes` — park async checkpoint writers on an event, so a
  test can kill/abandon a writer provably mid-flight and assert no torn
  checkpoint becomes visible;
- :func:`simulate_sigterm` — deliver a real SIGTERM to the process (the
  preemption grace signal), driving
  :class:`apex_tpu.resilience.PreemptionGuard`;
- :class:`ChaosProxy` — a TCP proxy between the fleet router and a
  socket replica (ISSUE 14) injecting the failures a real network
  throws: partition, half-open (accept-then-silence), slow link, torn
  frame, crc-corrupt frame, and reconnect churn — each deterministic
  and healable, so the socket transport's contracts are driven, not
  asserted;
- :class:`flapping_replica` — scripted up/down churn on a ChaosProxy
  link or a test double (ISSUE 18): deterministic edges on an injected
  clock, so the autopilot's flap-quarantine is driven by the same fake
  clock that drives its decisions.

Everything restores global state on exit; the context managers are
reentrancy-hostile by design (one fault at a time — compose scenarios
sequentially, as production failures arrive).
"""

from __future__ import annotations

import contextlib
import errno
import os
import signal
import socket
import threading
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "poison_grads",
    "bitflip_file",
    "truncate_file",
    "corrupt_checkpoint",
    "transient_os_errors",
    "hung_writes",
    "simulate_sigterm",
    "ChaosProxy",
    "flapping_replica",
]


# ---------------------------------------------------------------------------
# Non-finite gradients
# ---------------------------------------------------------------------------


def poison_grads(grads, *, step, at_step, kind: str = "nan",
                 leaf: int = 0):
    """Return ``grads`` with leaf ``leaf`` filled with NaN/Inf when
    ``step == at_step`` — pure jnp, so it stages into the jitted train
    step (``step`` may be a traced counter).  ``kind``: ``"nan"``,
    ``"inf"``, or ``"-inf"``."""
    bad = {"nan": np.nan, "inf": np.inf, "-inf": -np.inf}[kind]
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    x = leaves[leaf]
    leaves[leaf] = jnp.where(jnp.asarray(step) == at_step,
                             jnp.full_like(x, bad), x)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# Storage corruption
# ---------------------------------------------------------------------------


def bitflip_file(path: str, *, frac: float = 0.75, bit: int = 3) -> int:
    """Flip one bit inside an ARRAY PAYLOAD of an ``.npz`` checkpoint
    (not zip metadata, which nothing checksums): the data offset is read
    from the zip directory, targeting the last non-manifest entry.  For
    non-zip files, flips at ``frac`` of the file.  Returns the byte
    offset flipped.  The damage must trip both zipfile's entry CRC and
    the manifest crc32."""
    import zipfile

    size = os.path.getsize(path)
    off = min(size - 1, max(0, int(size * frac)))
    try:
        with zipfile.ZipFile(path) as zf:
            infos = [i for i in zf.infolist()
                     if i.filename != "__manifest__.npy"] or zf.infolist()
            info = infos[-1]
            with open(path, "rb") as f:
                # local header: 26..28 hold name/extra lengths; payload
                # starts after the 30-byte header + name + extra.
                f.seek(info.header_offset + 26)
                n, m = np.frombuffer(f.read(4), dtype="<u2")
            data_start = info.header_offset + 30 + int(n) + int(m)
            # skip the ~100-byte .npy header too: land in raw values
            off = min(data_start + max(128, info.compress_size // 2),
                      data_start + info.compress_size - 1)
    except Exception:
        pass  # not a zip (or torn already): positional flip
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ (1 << bit)]))
    return off


def truncate_file(path: str, *, keep_frac: float = 0.5) -> None:
    """Tear the file's tail off — the torn-write shape a crashed
    non-atomic writer (or a lying filesystem) produces.  For ``.npz``
    this destroys the zip central directory: the archive does not even
    open."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, int(size * keep_frac)))


def corrupt_checkpoint(path: str, *, mode: str = "bitflip",
                       shard: int = 0) -> str:
    """Damage a checkpoint: ``path`` may be a flat ``.npz`` file or a
    sharded checkpoint directory (then ``shard_{shard}.npz`` inside it
    is hit).  ``mode``: ``"bitflip"`` or ``"truncate"``.  Returns the
    file actually damaged."""
    target = path
    if os.path.isdir(path):
        target = os.path.join(path, f"shard_{shard}.npz")
    if mode == "bitflip":
        bitflip_file(target)
    elif mode == "truncate":
        truncate_file(target)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return target


# ---------------------------------------------------------------------------
# Flaky / hung filesystem
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def transient_os_errors(times: int, *, path_prefix: str,
                        op: str = "replace",
                        err: int = errno.EIO):
    """Make ``os.<op>`` (default the atomic-rename commit point) raise
    ``OSError(err)`` for the first ``times`` calls whose first argument
    starts with ``path_prefix``.  Later calls pass through — the
    *transient* failure the manager's retry-with-backoff absorbs.
    ``path_prefix`` is REQUIRED so only the intended traffic is hit:
    unrelated subsystems rename files too (e.g. the persistent XLA
    compilation cache), and an unscoped fault would be consumed by them,
    silently blunting the test.  Yields a counter object with
    ``.failed`` (injected-failure count).
    """
    real = getattr(os, op)
    lock = threading.Lock()

    class _Counter:
        failed = 0

    counter = _Counter()

    def flaky(*args, **kwargs):
        src = os.fspath(args[0]) if args else ""
        with lock:
            inject = (counter.failed < times
                      and str(src).startswith(path_prefix))
            if inject:
                counter.failed += 1
        if inject:
            raise OSError(err, f"injected transient {op} failure "
                               f"#{counter.failed}", str(src))
        return real(*args, **kwargs)

    setattr(os, op, flaky)
    try:
        yield counter
    finally:
        setattr(os, op, real)


class _HangHandle:
    """Controls writers parked by :func:`hung_writes`."""

    def __init__(self):
        self._gate = threading.Event()
        self.entered = threading.Event()  # a writer reached the gate

    def release(self) -> None:
        """Let parked (and all future) writers proceed."""
        self._gate.set()


@contextlib.contextmanager
def hung_writes(*, path_prefix: str = ""):
    """Park every checkpoint write whose destination starts with
    ``path_prefix`` on a gate *before any byte is written*.  The test
    now provably holds a writer mid-flight: abandon it, overlap another
    save, or ``release()`` it.  On context exit the gate opens (no
    writer leaks parked)."""
    from apex_tpu import checkpoint as ckpt

    handle = _HangHandle()
    real = ckpt._write_npz

    def gated(path, manifest, arrays):
        if str(path).startswith(path_prefix):
            handle.entered.set()
            handle._gate.wait()
        return real(path, manifest, arrays)

    ckpt._write_npz = gated
    try:
        yield handle
    finally:
        handle.release()
        ckpt._write_npz = real


# ---------------------------------------------------------------------------
# Network faults (ISSUE 14)
# ---------------------------------------------------------------------------


class _ProxyPair:
    """One bridged connection (client sock + upstream sock)."""

    def __init__(self, client: socket.socket, upstream: socket.socket):
        self.client = client
        self.upstream = upstream
        self._closed = threading.Event()

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        for s in (self.client, self.upstream):
            try:
                s.close()
            except OSError:
                pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class ChaosProxy:
    """TCP chaos between a fleet router and one socket replica.

    Listens on an ephemeral loopback port (``.address``); every
    accepted connection is bridged to ``upstream`` (a
    ``TransportServer`` / ``replica_serve`` daemon).  The
    upstream→client direction is **frame-aware** — it parses the public
    ``serving.transport`` header (version, length, crc) without ever
    deserializing a body — so faults land with byte precision:

    - :meth:`partition` — existing connections severed, new connects
      accepted-then-closed: total silence, the router's heartbeat
      ladder must produce the down verdict;
    - :meth:`half_open` — new connections accept but nothing flows
      (the classic accept-then-silence black hole): the client's hello
      deadline must churn through it;
    - :meth:`slow` — every frame/chunk delayed by ``delay_s``: RTT
      degrades, heartbeats still arrive — placement must *demote*, not
      fail;
    - :meth:`tear_next_frame` — the next replica→router frame is cut
      mid-body and the connection dropped: a torn frame the decoder
      must detect, never deserialize;
    - :meth:`corrupt_next_frame` — one bit flipped in the next frame's
      body: the crc must catch it;
    - :meth:`drop_connections` — severs at a *frame boundary*
      (reconnect churn): the session seq-replay must make it lossless;
    - :meth:`heal` — back to transparent pass-through.

    All controls are thread-safe and take effect at the next frame.
    """

    def __init__(self, upstream: Tuple[str, int], *,
                 listen_host: str = "127.0.0.1"):
        # the ONE header definition — parsing boundaries from a copy
        # would silently drift if the framing ever changed
        from apex_tpu.serving.transport import FRAME_HEADER

        self._HEADER = FRAME_HEADER
        self.upstream = (upstream[0], int(upstream[1]))
        self._lock = threading.Lock()
        self._mode = "pass"              # pass | partition | half_open
        self._delay_s = 0.0
        self._tear = 0                   # one-shot counters
        self._corrupt = 0
        self._cut = False                # boundary-cut flag (churn)
        self._pairs: list = []
        self._stalled: list = []         # half-open holds
        self._closed = False
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((listen_host, 0))
        lsock.listen(8)
        lsock.settimeout(0.2)
        self._lsock = lsock
        self.address: Tuple[str, int] = lsock.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chaos-proxy-accept",
            daemon=True)
        self._accept_thread.start()

    # ------------------------------------------------------------- controls

    def partition(self) -> None:
        with self._lock:
            self._mode = "partition"
        self._kill_pairs()

    def half_open(self) -> None:
        with self._lock:
            self._mode = "half_open"

    def slow(self, delay_s: float) -> None:
        with self._lock:
            self._mode = "pass"
            self._delay_s = float(delay_s)

    def tear_next_frame(self) -> None:
        with self._lock:
            self._tear += 1

    def corrupt_next_frame(self) -> None:
        with self._lock:
            self._corrupt += 1

    def drop_connections(self, *, wait_s: float = 5.0) -> None:
        """Sever every live connection at the next replica→router frame
        boundary (reconnect churn: a loss the session layer must absorb
        without a failover)."""
        with self._lock:
            if not self._pairs:
                return
            self._cut = True
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            with self._lock:
                if all(p.closed for p in self._pairs):
                    break
            time.sleep(0.005)
        with self._lock:
            self._cut = False
            self._pairs = [p for p in self._pairs if not p.closed]

    def heal(self) -> None:
        with self._lock:
            self._mode = "pass"
            self._delay_s = 0.0
        # release half-open holds so the client's next attempt bridges
        for s in self._drain_stalled():
            try:
                s.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._lsock.close()
        except OSError:
            pass
        self._kill_pairs()
        for s in self._drain_stalled():
            try:
                s.close()
            except OSError:
                pass

    def __enter__(self) -> "ChaosProxy":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ plumbing

    def _drain_stalled(self) -> list:
        with self._lock:
            stalled, self._stalled = self._stalled, []
        return stalled

    def _kill_pairs(self) -> None:
        with self._lock:
            pairs, self._pairs = self._pairs, []
        for p in pairs:
            p.close()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                client, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                mode = self._mode
            if mode == "partition":
                try:
                    client.close()
                except OSError:
                    pass
                continue
            if mode == "half_open":
                with self._lock:
                    self._stalled.append(client)   # held, never bridged
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
            except OSError:
                try:
                    client.close()
                except OSError:
                    pass
                continue
            # both relay legs disable Nagle, same as the real transport
            # endpoints: a store-and-forward proxy that batches small
            # frames behind delayed ACKs would change the very timing
            # the fault tests are probing
            for s in (client, up):
                try:
                    s.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
                except OSError:
                    pass
            pair = _ProxyPair(client, up)
            with self._lock:
                self._pairs.append(pair)
            threading.Thread(target=self._pump_raw, args=(pair,),
                             daemon=True).start()
            threading.Thread(target=self._pump_frames, args=(pair,),
                             daemon=True).start()

    def _fault_gate(self, pair: _ProxyPair) -> bool:
        """Per-frame/chunk mode check; True = stop pumping this pair."""
        while True:
            with self._lock:
                mode, delay, cut = self._mode, self._delay_s, self._cut
            if pair.closed or self._closed or mode == "partition" or cut:
                pair.close()
                return True
            if mode == "half_open":
                time.sleep(0.01)         # stall — silence, not EOF
                continue
            if delay > 0:
                time.sleep(delay)
            return False

    def _pump_raw(self, pair: _ProxyPair) -> None:
        """router → replica: raw chunk forwarding."""
        try:
            while True:
                data = pair.client.recv(65536)
                if not data:
                    break
                if self._fault_gate(pair):
                    return
                pair.upstream.sendall(data)
        except OSError:
            pass
        finally:
            pair.close()

    def _recv_exact(self, sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise EOFError
            buf += chunk
        return buf

    def _pump_frames(self, pair: _ProxyPair) -> None:
        """replica → router: frame-aware, so torn/corrupt/cut land at
        byte-exact positions."""
        try:
            while True:
                header = self._recv_exact(pair.upstream,
                                          self._HEADER.size)
                _, length, _ = self._HEADER.unpack(header)
                body = self._recv_exact(pair.upstream, length)
                if self._fault_gate(pair):
                    return
                frame = header + body
                with self._lock:
                    tear = self._tear > 0
                    if tear:
                        self._tear -= 1
                    corrupt = (not tear) and self._corrupt > 0
                    if corrupt:
                        self._corrupt -= 1
                if tear:
                    # half a frame then FIN: torn mid-body, the decoder
                    # must refuse to deserialize what did arrive
                    pair.client.sendall(frame[:max(
                        self._HEADER.size + 1, len(frame) // 2)])
                    pair.close()
                    return
                if corrupt:
                    flipped = bytearray(frame)
                    flipped[-1] ^= 0x10   # body byte: crc must catch it
                    frame = bytes(flipped)
                pair.client.sendall(frame)
        except (OSError, EOFError):
            pass
        finally:
            pair.close()


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------


def simulate_sigterm(pid: Optional[int] = None) -> None:
    """Deliver a real SIGTERM (the preemption grace signal) to ``pid``
    (default: this process).  With a
    :class:`apex_tpu.resilience.PreemptionGuard` installed this sets the
    drain flag; without one, default signal disposition applies — so
    install the guard first."""
    os.kill(os.getpid() if pid is None else pid, signal.SIGTERM)


# ---------------------------------------------------------------------------
# Flapping replica (ISSUE 18)
# ---------------------------------------------------------------------------


class flapping_replica:
    """Scripted up/down churn — the fault the SLO autopilot's
    quarantine exists for.  Wraps anything with a down/up actuator
    pair and toggles it on a deterministic schedule read off an
    injected clock:

    - a :class:`ChaosProxy` link: ``down = partition``, ``up = heal``
      (auto-detected);
    - a test double (e.g. the fleet tests' ``FakeReplica``): pass
      ``down=replica.fail, up=replica.revive`` (or any callables);
      ``fail``/``revive`` attribute pairs are auto-detected too.

    The schedule is pure arithmetic on the clock — first :meth:`tick`
    pins ``t0``; edges land at ``t0 + k * period_s`` and each edge
    flips the state (even k → down, odd k → up), so the same fake
    clock replays the same churn run after run.  A driver loop calls
    :meth:`tick` as often as it likes; missed edges are applied in
    order on the next call.  ``max_flaps`` bounds the churn: after
    that many down-edges the replica is brought (and stays) up, so a
    test can assert the autopilot quarantined it *during* the storm
    and releases it after back-off.

    ``flaps`` counts down-edges applied so far; :meth:`stop` ends the
    churn and restores up.
    """

    def __init__(self, target=None, *, down=None, up=None,
                 period_s: float = 1.0, max_flaps: Optional[int] = None,
                 clock=time.monotonic):
        if target is not None:
            if down is None:
                down = getattr(target, "partition", None) or \
                    getattr(target, "fail", None)
            if up is None:
                up = getattr(target, "heal", None) or \
                    getattr(target, "revive", None)
        if down is None or up is None:
            raise TypeError(
                "flapping_replica needs a down/up actuator pair "
                "(ChaosProxy, a fail/revive double, or explicit "
                "down=/up= callables)")
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s}")
        self._down, self._up = down, up
        self.period_s = float(period_s)
        self.max_flaps = max_flaps
        self._clock = clock
        self._t0: Optional[float] = None
        self._edges = 0          # schedule edges consumed
        self.flaps = 0           # down-edges applied
        self.is_down = False
        self._stopped = False

    def tick(self) -> bool:
        """Apply every schedule edge at or before ``clock()``; returns
        the current down-ness.  Call from the same loop that pumps the
        router/autopilot."""
        if self._stopped:
            return self.is_down
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        while self._t0 + self._edges * self.period_s <= now:
            if self.max_flaps is not None and \
                    self.flaps >= self.max_flaps:
                self.stop()
                return self.is_down
            self._edges += 1
            if self.is_down:
                self._up()
                self.is_down = False
            else:
                self._down()
                self.is_down = True
                self.flaps += 1
        return self.is_down

    def stop(self) -> None:
        """End the churn and leave the replica up."""
        self._stopped = True
        if self.is_down:
            self._up()
            self.is_down = False

    def __enter__(self) -> "flapping_replica":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
