"""Fault injection — make the failure paths testable on a laptop.

The resilience layer (:mod:`apex_tpu.resilience`) claims to survive
non-finite gradients, torn/corrupted checkpoints, dying async writers,
flaky filesystems, and SIGTERM preemption.  Claims proven by inspection
rot; this module injects each failure deterministically so the fast tier
drives save→kill→resume and corrupt→fallback→resume end to end:

- :func:`poison_grads` — jit-safe NaN/Inf injection into a gradient tree
  at a chosen step (a ``jnp.where`` on the step counter: the injection
  itself compiles into the train step, so the sentinel is tested inside
  the very program it guards);
- :func:`bitflip_file` / :func:`truncate_file` /
  :func:`corrupt_checkpoint` — storage damage (single flipped bit in the
  array payload, torn tail) that per-array checksums must catch;
- :func:`transient_os_errors` — a wrapped filesystem raising
  ``OSError`` from the first N matching operations (the NFS/GCS-fuse
  blip the manager's retry-with-backoff exists for), scoped by path
  prefix so only checkpoint traffic is hit;
- :func:`hung_writes` — park async checkpoint writers on an event, so a
  test can kill/abandon a writer provably mid-flight and assert no torn
  checkpoint becomes visible;
- :func:`simulate_sigterm` — deliver a real SIGTERM to the process (the
  preemption grace signal), driving
  :class:`apex_tpu.resilience.PreemptionGuard`.

Everything restores global state on exit; the context managers are
reentrancy-hostile by design (one fault at a time — compose scenarios
sequentially, as production failures arrive).
"""

from __future__ import annotations

import contextlib
import errno
import os
import signal
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "poison_grads",
    "bitflip_file",
    "truncate_file",
    "corrupt_checkpoint",
    "transient_os_errors",
    "hung_writes",
    "simulate_sigterm",
]


# ---------------------------------------------------------------------------
# Non-finite gradients
# ---------------------------------------------------------------------------


def poison_grads(grads, *, step, at_step, kind: str = "nan",
                 leaf: int = 0):
    """Return ``grads`` with leaf ``leaf`` filled with NaN/Inf when
    ``step == at_step`` — pure jnp, so it stages into the jitted train
    step (``step`` may be a traced counter).  ``kind``: ``"nan"``,
    ``"inf"``, or ``"-inf"``."""
    bad = {"nan": np.nan, "inf": np.inf, "-inf": -np.inf}[kind]
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    x = leaves[leaf]
    leaves[leaf] = jnp.where(jnp.asarray(step) == at_step,
                             jnp.full_like(x, bad), x)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# Storage corruption
# ---------------------------------------------------------------------------


def bitflip_file(path: str, *, frac: float = 0.75, bit: int = 3) -> int:
    """Flip one bit inside an ARRAY PAYLOAD of an ``.npz`` checkpoint
    (not zip metadata, which nothing checksums): the data offset is read
    from the zip directory, targeting the last non-manifest entry.  For
    non-zip files, flips at ``frac`` of the file.  Returns the byte
    offset flipped.  The damage must trip both zipfile's entry CRC and
    the manifest crc32."""
    import zipfile

    size = os.path.getsize(path)
    off = min(size - 1, max(0, int(size * frac)))
    try:
        with zipfile.ZipFile(path) as zf:
            infos = [i for i in zf.infolist()
                     if i.filename != "__manifest__.npy"] or zf.infolist()
            info = infos[-1]
            with open(path, "rb") as f:
                # local header: 26..28 hold name/extra lengths; payload
                # starts after the 30-byte header + name + extra.
                f.seek(info.header_offset + 26)
                n, m = np.frombuffer(f.read(4), dtype="<u2")
            data_start = info.header_offset + 30 + int(n) + int(m)
            # skip the ~100-byte .npy header too: land in raw values
            off = min(data_start + max(128, info.compress_size // 2),
                      data_start + info.compress_size - 1)
    except Exception:
        pass  # not a zip (or torn already): positional flip
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ (1 << bit)]))
    return off


def truncate_file(path: str, *, keep_frac: float = 0.5) -> None:
    """Tear the file's tail off — the torn-write shape a crashed
    non-atomic writer (or a lying filesystem) produces.  For ``.npz``
    this destroys the zip central directory: the archive does not even
    open."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, int(size * keep_frac)))


def corrupt_checkpoint(path: str, *, mode: str = "bitflip",
                       shard: int = 0) -> str:
    """Damage a checkpoint: ``path`` may be a flat ``.npz`` file or a
    sharded checkpoint directory (then ``shard_{shard}.npz`` inside it
    is hit).  ``mode``: ``"bitflip"`` or ``"truncate"``.  Returns the
    file actually damaged."""
    target = path
    if os.path.isdir(path):
        target = os.path.join(path, f"shard_{shard}.npz")
    if mode == "bitflip":
        bitflip_file(target)
    elif mode == "truncate":
        truncate_file(target)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return target


# ---------------------------------------------------------------------------
# Flaky / hung filesystem
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def transient_os_errors(times: int, *, path_prefix: str,
                        op: str = "replace",
                        err: int = errno.EIO):
    """Make ``os.<op>`` (default the atomic-rename commit point) raise
    ``OSError(err)`` for the first ``times`` calls whose first argument
    starts with ``path_prefix``.  Later calls pass through — the
    *transient* failure the manager's retry-with-backoff absorbs.
    ``path_prefix`` is REQUIRED so only the intended traffic is hit:
    unrelated subsystems rename files too (e.g. the persistent XLA
    compilation cache), and an unscoped fault would be consumed by them,
    silently blunting the test.  Yields a counter object with
    ``.failed`` (injected-failure count).
    """
    real = getattr(os, op)
    lock = threading.Lock()

    class _Counter:
        failed = 0

    counter = _Counter()

    def flaky(*args, **kwargs):
        src = os.fspath(args[0]) if args else ""
        with lock:
            inject = (counter.failed < times
                      and str(src).startswith(path_prefix))
            if inject:
                counter.failed += 1
        if inject:
            raise OSError(err, f"injected transient {op} failure "
                               f"#{counter.failed}", str(src))
        return real(*args, **kwargs)

    setattr(os, op, flaky)
    try:
        yield counter
    finally:
        setattr(os, op, real)


class _HangHandle:
    """Controls writers parked by :func:`hung_writes`."""

    def __init__(self):
        self._gate = threading.Event()
        self.entered = threading.Event()  # a writer reached the gate

    def release(self) -> None:
        """Let parked (and all future) writers proceed."""
        self._gate.set()


@contextlib.contextmanager
def hung_writes(*, path_prefix: str = ""):
    """Park every checkpoint write whose destination starts with
    ``path_prefix`` on a gate *before any byte is written*.  The test
    now provably holds a writer mid-flight: abandon it, overlap another
    save, or ``release()`` it.  On context exit the gate opens (no
    writer leaks parked)."""
    from apex_tpu import checkpoint as ckpt

    handle = _HangHandle()
    real = ckpt._write_npz

    def gated(path, manifest, arrays):
        if str(path).startswith(path_prefix):
            handle.entered.set()
            handle._gate.wait()
        return real(path, manifest, arrays)

    ckpt._write_npz = gated
    try:
        yield handle
    finally:
        handle.release()
        ckpt._write_npz = real


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------


def simulate_sigterm(pid: Optional[int] = None) -> None:
    """Deliver a real SIGTERM (the preemption grace signal) to ``pid``
    (default: this process).  With a
    :class:`apex_tpu.resilience.PreemptionGuard` installed this sets the
    drain flag; without one, default signal disposition applies — so
    install the guard first."""
    os.kill(os.getpid() if pid is None else pid, signal.SIGTERM)
