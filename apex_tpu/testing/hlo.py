"""Compiled-HLO inspection: prove an optimization survived jit.

The collective-matmul rings
(:mod:`apex_tpu.transformer.tensor_parallel.overlap`) are only worth their
code if the compiled program still contains the decomposed
``collective-permute`` chain — XLA is free to pattern-match a ring back
into one monolithic ``all-gather`` (its own collective-matmul pass works in
the opposite direction), and a silent re-fusion would make the overlap
tests vacuously pass on values while measuring nothing.  These helpers
compile a function exactly as the tests run it and count opcodes in the
optimized HLO text, so assertions hold on every jax version the shims
support (the ``lower().compile().as_text()`` pipeline is stable across
0.4.x–0.7.x).

Async collective pairs (``all-gather-start``/``-done``,
``collective-permute-start``/``-done``) count as ONE op under their base
opcode: the start/done split is a backend scheduling detail, not an extra
collective on the wire.
"""

from __future__ import annotations

import collections
import re

__all__ = ["compiled_hlo", "hlo_op_counts", "count_hlo_ops"]

# `%name = shape opcode(operands...)` — the opcode is the first
# bare-word-followed-by-paren after the `=` (the shape, even a tuple shape
# like `(f32[4], u32[])`, never puts a letter token directly against an
# opening paren).
_OPCODE = re.compile(r"([a-z][a-z0-9-]*)\(")


def compiled_hlo(fn, *args, **kwargs) -> str:
    """Optimized HLO text of ``jit(fn)`` at these arguments.

    ``fn`` is compiled exactly as it would execute (same shapes, same
    shardings if the arguments carry them); pass an already-``jit``-ed or
    ``shard_over``-ed callable freely — ``jax.jit`` of a jitted function
    is the same cache entry.
    """
    import jax

    return jax.jit(fn).lower(*args, **kwargs).compile().as_text()


def hlo_op_counts(hlo_text: str) -> "collections.Counter[str]":
    """Opcode -> occurrence count over every instruction in ``hlo_text``,
    with ``-start``/``-done`` async halves folded into their base opcode
    (the pair is one collective; counting both would double it)."""
    counts: collections.Counter = collections.Counter()
    for line in hlo_text.splitlines():
        _, eq, rhs = line.partition(" = ")
        if not eq:
            continue
        m = _OPCODE.search(rhs)
        if m is None:
            continue
        op = m.group(1)
        if op.endswith("-done"):
            continue
        if op.endswith("-start"):
            op = op[: -len("-start")]
        counts[op] += 1
    return counts


def count_hlo_ops(hlo_text: str, opcode: str) -> int:
    """Occurrences of ``opcode`` (e.g. ``"collective-permute"``,
    ``"all-gather"``) in compiled HLO, async pairs counted once."""
    return hlo_op_counts(hlo_text)[opcode]
