"""Back-compat shim: the compiled-HLO helpers moved to
:mod:`apex_tpu.analysis.hlo` (ISSUE 4 hoisted them into the static-
analysis subsystem, where the opcode counting gained a structured
per-computation parse and the rule-based checks live).  Existing
imports keep working; new code should import from
``apex_tpu.analysis``.
"""

from apex_tpu.analysis.hlo import (  # noqa: F401
    compiled_hlo,
    count_hlo_ops,
    hlo_op_counts,
    parse_hlo,
)

__all__ = ["compiled_hlo", "hlo_op_counts", "count_hlo_ops", "parse_hlo"]
