"""Mid-epoch kill/resume proof for the input pipeline (ISSUE 8).

The loaders claim *exact* mid-epoch resume: checkpoint the device
prefetcher's ``consumed_samples`` through
:class:`apex_tpu.resilience.CheckpointManager`, SIGKILL the process at
any instant, rebuild loader + wrapper from the restored counter, and the
delivered sample stream continues with **no skipped and no duplicated
samples** — for both loader families (the online-decode
``ImageFolderLoader`` and the decode-free packed loaders, here the LM
``PackedSequenceLoader``).  Claims proven by inspection rot; this module
is the script ``tests/test_data_resume.py`` (and
``scripts/data_pipeline_smoke.sh``) drives end to end:

- ``--phase run``     — stream batches through
  ``loader -> prefetch_to_device``, append each delivered batch's
  sha256 (of its raw bytes) + its post-delivery ``consumed_samples`` to
  ``--stream`` (fsynced per line, the crash_resume.py discipline), save
  ``{"consumed_samples": n}`` via ``CheckpointManager`` after every
  batch, and **SIGKILL ourselves** after ``--kill-after`` batches —
  deliberately mid-epoch (the harness sizes the epoch so the kill never
  lands on an epoch boundary).
- ``--phase resume``  — ``restore_latest`` the counter, truncate the
  stream file to batches the checkpoint covers (a crash may have logged
  a batch newer than the last durable save — exactly crash_resume.py's
  ``_truncate_losses``), rebuild loader + wrapper from it, and stream
  the remaining batches.
- ``--phase ref``     — the uninterrupted reference: same dataset, same
  total batches, no kill.

The caller compares the killed+resumed stream file to the reference's
byte-for-byte: equality holds only if resume replayed exactly the
undelivered batches (a skip or a duplicate shifts every subsequent
hash).
"""

from __future__ import annotations

import argparse
import hashlib
import os
import signal
import sys

if __name__ == "__main__":  # runnable as a plain script path
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))))


def _append(path: str, consumed: int, digest: str) -> None:
    with open(path, "a") as f:
        f.write(f"{consumed} {digest}\n")
        f.flush()
        os.fsync(f.fileno())


def _truncate(path: str, consumed: int) -> None:
    """Drop stream lines newer than the restored checkpoint."""
    if not os.path.exists(path):
        return
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines()
                 if ln and int(ln.split()[0]) <= consumed]
    with open(path, "w") as f:
        f.write("".join(ln + "\n" for ln in lines))
        f.flush()
        os.fsync(f.fileno())


def _batch_digest(batch) -> str:
    h = hashlib.sha256()
    import numpy as np

    for leaf in batch:
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _make_image_dataset(root: str):
    """Deterministic tiny JPEG tree (created once per work dir)."""
    import numpy as np
    from PIL import Image

    from apex_tpu.data import ImageFolder

    marker = os.path.join(root, ".complete")
    if not os.path.exists(marker):
        rng = np.random.RandomState(0)
        for c in range(2):
            d = os.path.join(root, f"class_{c}")
            os.makedirs(d, exist_ok=True)
            for i in range(24):
                arr = rng.randint(0, 256, (48, 56, 3), dtype=np.uint8)
                Image.fromarray(arr).save(os.path.join(d, f"{i}.jpg"),
                                          quality=92)
        with open(marker, "w") as f:
            f.write("ok")
    return ImageFolder(root)


def _make_sequence_dataset(prefix: str):
    from apex_tpu.data import (
        PackedSequenceDataset,
        pack_token_documents,
        synthetic_token_documents,
    )

    if not os.path.exists(prefix + ".json"):
        docs = synthetic_token_documents(64, vocab=64, mean_len=24, seed=3)
        return pack_token_documents(docs, prefix, seq_len=32, eos_id=63)
    return PackedSequenceDataset(prefix)


def _make_loader(family: str, work: str, consumed: int):
    if family == "image":
        ds = _make_image_dataset(os.path.join(work, "jpegs"))
        from apex_tpu.data import ImageFolderLoader

        return ImageFolderLoader(ds, local_batch=2, data_parallel_size=2,
                                 image_size=16, seed=7, prefetch=2,
                                 consumed_samples=consumed)
    if family == "sequence":
        ds = _make_sequence_dataset(os.path.join(work, "seq", "train"))
        from apex_tpu.data import PackedSequenceLoader

        return PackedSequenceLoader(ds, local_batch=2,
                                    data_parallel_size=2, seed=7,
                                    prefetch=2, consumed_samples=consumed)
    raise ValueError(f"unknown family {family!r}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--family", choices=["image", "sequence"], required=True)
    p.add_argument("--work", required=True)
    p.add_argument("--phase", choices=["run", "resume", "ref"],
                   required=True)
    p.add_argument("--stream", required=True,
                   help="delivered-batch hash log (append)")
    p.add_argument("--total-batches", type=int, default=13,
                   help="batches the full (ref / killed+resumed) stream "
                        "delivers; deliberately NOT a multiple of the "
                        "batches-per-epoch so the run crosses an epoch "
                        "boundary mid-stream")
    p.add_argument("--kill-after", type=int, default=5,
                   help="run phase: deliver this many batches, then "
                        "SIGKILL ourselves (mid-epoch)")
    args = p.parse_args(argv)

    import numpy as np

    from apex_tpu.data import prefetch_to_device
    from apex_tpu.resilience import CheckpointManager

    os.makedirs(args.work, exist_ok=True)
    ckpt_dir = os.path.join(args.work, f"ckpt_{args.family}")
    mgr = CheckpointManager(ckpt_dir, keep=2)

    consumed = 0
    if args.phase == "resume":
        tree, _ = mgr.restore_latest({"consumed_samples": np.int64(0)})
        consumed = int(tree["consumed_samples"])
        _truncate(args.stream, consumed)

    loader = _make_loader(args.family, args.work, consumed)
    per_batch = loader.local_batch * loader.dp
    done = consumed // per_batch  # batches already on the stream log

    # the device wrapper: placement is a plain device_put (no mesh) —
    # the H2D hop is part of the pipeline under test
    dev = prefetch_to_device(loader, depth=2)
    # the wrapper is per-epoch like the loaders: re-wrap on exhaustion
    step = done
    try:
        while step < args.total_batches:
            try:
                batch = next(dev)
            except StopIteration:
                dev.close(close_source=False)  # keep the decode pool
                dev = prefetch_to_device(loader, depth=2)
                continue
            host = tuple(np.asarray(x) for x in batch)
            _append(args.stream, dev.consumed_samples, _batch_digest(host))
            mgr.save({"consumed_samples": np.int64(dev.consumed_samples)},
                     step)
            step += 1
            if args.phase == "run" and step - done >= args.kill_after:
                # the mid-epoch SIGKILL: no cleanup, no atexit — the
                # process dies with decode futures and device transfers
                # in flight (crash_resume_smoke's kill shape, aimed at
                # the data path)
                print(f"data_resume: SIGKILL after {step} batches",
                      file=sys.stderr, flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
    finally:
        dev.close()
    print(f"data_resume: {args.phase} done, {step} batches, "
          f"consumed={loader.consumed_samples}", file=sys.stderr,
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
