"""Host-side continuous-batching scheduler.

The state machine the engine drives once per step:

    WAITING --admit (slot + blocks free)--> RUNNING --eos / budget /
        max_seq--> FINISHED
    WAITING --drain--> CANCELLED
    submit() while draining --> REJECTED   (refused at the door)

- **Admission** is all-or-nothing per request: a free decode slot AND
  the request's *worst-case* block count
  (``blocks_for(min(prompt + max_new_tokens, max_seq))``) must both be
  available.  Reserving the worst case up front means a running
  request can never fail a mid-flight block append — the pool is a
  hard admission control, not an eviction policy (documented trade:
  lower occupancy than optimistic allocation + preemption, but no
  request ever restarts).  Blocks are fixed-size so this is a pure
  counter check — fragmentation cannot strand capacity
  (``kv_cache.BlockAllocator``).
- **Slots** are indices into the engine's fixed ``[max_batch]`` decode
  arrays; a request keeps one slot from admission to finish.  Churn
  rewrites the slot's row of the block-table/length arrays — data,
  never shape, which is what the zero-recompile contract rests on.
- **Draining** (preemption): no further admissions; RUNNING requests
  decode to completion and deliver their responses; WAITING requests
  are cancelled immediately (the submitter sees a terminal state, not
  a hang) — the serving analog of the PR 3 drain-then-exit.  A submit
  that arrives *during* the drain is REJECTED, not cancelled: the two
  terminal states answer different routing questions (see
  ``RequestState``), and the engine counts them separately
  (``serving/requests_cancelled`` vs ``serving/requests_rejected``).
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import itertools
import time
from typing import Deque, List, Optional, Sequence

import numpy as np

from apex_tpu.serving.kv_cache import BlockAllocator, KVCacheConfig

__all__ = ["Request", "RequestState", "Scheduler"]


class RequestState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    CANCELLED = "cancelled"
    # refused at the door (submitted into a drain window, or shed by the
    # fleet router on overload) — distinguishable from CANCELLED, which
    # means "accepted, then drained out of the queue": a router that
    # sees REJECTED re-routes the request to another replica, while a
    # CANCELLED request was an accepted casualty of this engine's drain
    REJECTED = "rejected"


@dataclasses.dataclass
class Request:
    """One generation request and its live serving state."""

    rid: int
    prompt: np.ndarray                  # int32 [prompt_len]
    max_new_tokens: int
    eos_id: Optional[int] = None

    state: RequestState = RequestState.WAITING
    output_tokens: List[int] = dataclasses.field(default_factory=list)
    blocks: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    cache_len: int = 0                  # tokens currently in the paged cache

    # wall-clock marks for the latency metrics (engine-stamped)
    t_submit: float = 0.0
    t_first_token: Optional[float] = None
    t_last_token: Optional[float] = None

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.REJECTED)

    @property
    def last_token(self) -> int:
        if self.output_tokens:
            return self.output_tokens[-1]
        return int(self.prompt[-1])


class Scheduler:
    """Slot + block bookkeeping for the continuous batch."""

    def __init__(self, cache: KVCacheConfig, max_batch: int):
        self.cache = cache
        self.max_batch = max_batch
        self.allocator = BlockAllocator(cache.n_blocks)
        self.slots: List[Optional[Request]] = [None] * max_batch
        self.waiting: Deque[Request] = collections.deque()
        self._ids = itertools.count()
        self.draining = False

    # ------------------------------------------------------------- submit

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None) -> Request:
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token list")
        if prompt.size >= self.cache.max_seq:
            raise ValueError(
                f"prompt of {prompt.size} tokens does not fit max_seq="
                f"{self.cache.max_seq} with room to generate")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = Request(rid=next(self._ids), prompt=prompt,
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      t_submit=time.monotonic())
        need = self._worst_case_blocks(req)
        if need > self.allocator.n_blocks:
            # admission is the only allocation point, so a request the
            # WHOLE pool cannot cover would sit at the head of the FIFO
            # queue forever, starving everything behind it — reject it
            # at the door instead
            raise ValueError(
                f"request needs {need} blocks worst-case "
                f"(prompt {prompt.size} + max_new_tokens "
                f"{max_new_tokens}) but the arena has only "
                f"{self.allocator.n_blocks}; raise n_blocks or lower "
                "max_new_tokens")
        if self.draining:
            # a submit that lands in the drain window is refused with a
            # typed terminal state, NOT accepted-then-cancelled: the
            # caller (a fleet router, a retrying client) must be able to
            # tell "this engine would never have run it" from "it was
            # queued and the drain killed it"
            req.state = RequestState.REJECTED
            return req
        self.waiting.append(req)
        return req

    # -------------------------------------------------------------- admit

    def _worst_case_blocks(self, req: Request) -> int:
        horizon = min(len(req.prompt) + req.max_new_tokens,
                      self.cache.max_seq)
        return self.cache.blocks_for(horizon)

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.slots) if r is None]

    def admit(self) -> List[Request]:
        """Move WAITING requests into free slots while capacity lasts
        (FIFO — no request starves behind a later, smaller one).
        Returns the newly-admitted requests; the engine prefills them."""
        admitted: List[Request] = []
        if self.draining:
            return admitted
        free = self.free_slots()
        while self.waiting and free:
            req = self.waiting[0]
            need = self._worst_case_blocks(req)
            if not self.allocator.can_alloc(need):
                break
            self.waiting.popleft()
            req.blocks = self.allocator.alloc(need, owner=req.rid)
            req.slot = free.pop(0)
            req.state = RequestState.RUNNING
            req.cache_len = 0
            self.slots[req.slot] = req
            admitted.append(req)
        return admitted

    # ------------------------------------------------------------- finish

    def finish(self, req: Request) -> None:
        """Release a RUNNING request's slot and blocks."""
        if req.state is not RequestState.RUNNING:
            raise ValueError(f"finish() on {req.state} request {req.rid}")
        self.allocator.free(req.blocks, owner=req.rid)
        req.blocks = []
        self.slots[req.slot] = None
        req.slot = None
        req.state = RequestState.FINISHED
        req.t_last_token = time.monotonic()

    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def drain(self) -> List[Request]:
        """Stop admissions and cancel the queue; running requests keep
        their slots (the engine decodes them to completion).  Returns
        the cancelled requests."""
        self.draining = True
        cancelled = list(self.waiting)
        self.waiting.clear()
        for req in cancelled:
            req.state = RequestState.CANCELLED
        return cancelled

    @property
    def idle(self) -> bool:
        return not self.waiting and all(r is None for r in self.slots)
