"""Host-side continuous-batching scheduler — admission by *actual*
occupancy.

The state machine the engine drives once per step::

    WAITING --admit (slot + first-chunk blocks)--> RUNNING
        RUNNING (prefilling: cache_len < prefill_target)
        RUNNING (decoding) --eos / budget / max_seq--> FINISHED
    RUNNING --pool pressure--> WAITING   (preempted: blocks freed,
                                          recompute-on-readmit)
    WAITING --drain--> CANCELLED
    submit() while draining --> REJECTED   (refused at the door)

PR 8 admitted by **worst-case reservation** — a request held
``blocks_for(prompt + max_new_tokens)`` from admission to finish, so
the pool ran far below real occupancy (most requests never reach their
horizon, and the reserved tail blocks sat idle).  This scheduler closes
that gap the way production engines do:

- **Admission** needs a free decode slot and blocks for the request's
  *first prefill chunk only* — after the prefix cache
  (:class:`~apex_tpu.serving.kv_cache.PrefixCache`) has been consulted:
  shared prompt-prefix blocks are refcount-incremented, not
  re-allocated or re-computed.  Fixed-size blocks keep this a pure
  counter check (fragmentation cannot strand capacity).
- **Growth is on demand**: a request crossing into a new block during
  prefill or decode allocates it then.  When the free list is empty the
  scheduler first **evicts** least-recently-used prefix-cache blocks
  (finished requests' cached KV — capacity held only as an
  optimization), and only then **preempts**: the *newest-admitted*
  victim frees every block (its cached full blocks are first indexed
  into the prefix cache, so its work is not lost) and returns to the
  front of the queue.  On readmission it *recomputes* — its prompt plus
  every token it already emitted replays through the ordinary chunked
  prefill path (the PR 10 fleet-replay mechanics, one process inward) —
  and typically hits its own just-cached blocks, so the recompute
  prefills only what eviction actually took.
- Victims are always strictly newer than the request growing, so the
  oldest running request can never be preempted: it finishes, frees
  its blocks, and everything behind it readmits — every admitted
  request terminates even at heavy pool oversubscription (pinned at 2x
  in ``tests/test_serving.py``).
- The submit-time guard keeps one hard reservation rule: a request
  whose worst case exceeds the WHOLE pool is rejected at the door (it
  could otherwise preempt the fleet forever and still never finish).
- ``admission="reserve"`` keeps the PR 8 worst-case policy as the A/B
  baseline (bench ``serving_occupancy.vs_reserve``): no sharing, no
  growth, no preemption — admission is the whole horizon or nothing.

**Slots** are indices into the engine's fixed ``[max_batch]`` decode
arrays; a request keeps one slot from admission to finish or
preemption.  Churn rewrites the slot's row of the block-table/length
arrays — data, never shape, which is what the zero-recompile contract
rests on.

**Draining** (preemption of the whole engine): no further admissions;
RUNNING requests decode to completion and deliver their responses;
WAITING requests — including preempted ones, whose partial streams were
already delivered — are cancelled immediately (the submitter sees a
terminal state, not a hang).  A submit that arrives *during* the drain
is REJECTED, not cancelled: the two terminal states answer different
routing questions (see ``RequestState``), and the engine counts them
separately (``serving/requests_cancelled`` vs
``serving/requests_rejected``).
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import itertools
import time
from typing import Deque, List, Optional, Sequence

import numpy as np

from apex_tpu.serving.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    PrefixCache,
)
from apex_tpu.serving.sampling import SamplingParams

__all__ = ["Request", "RequestState", "Scheduler", "trace_fields"]


def trace_fields(req) -> dict:
    """Trace-context kwargs for a request's timeline events (ISSUE 15):
    ``{trace_id, attempt}`` when the request rides a fleet trace, empty
    otherwise — an untraced spill carries no null clutter and is byte-
    compatible with the pre-tracing schema."""
    if req.trace_id is None:
        return {}
    return {"trace_id": req.trace_id, "attempt": req.trace_attempt}


class RequestState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    CANCELLED = "cancelled"
    # refused at the door (submitted into a drain window, or shed by the
    # fleet router on overload) — distinguishable from CANCELLED, which
    # means "accepted, then drained out of the queue": a router that
    # sees REJECTED re-routes the request to another replica, while a
    # CANCELLED request was an accepted casualty of this engine's drain
    REJECTED = "rejected"


@dataclasses.dataclass
class Request:
    """One generation request and its live serving state."""

    rid: int
    prompt: np.ndarray                  # int32 [prompt_len]
    max_new_tokens: int
    eos_id: Optional[int] = None
    sampling: SamplingParams = dataclasses.field(
        default_factory=SamplingParams)

    state: RequestState = RequestState.WAITING
    output_tokens: List[int] = dataclasses.field(default_factory=list)
    blocks: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    cache_len: int = 0                  # tokens currently in the paged cache
    prefill_target: int = 0             # tokens the prefill must cover
    hit_blocks: int = 0                 # prefix-cache blocks shared (last admit)
    pc_blocks: int = 0                  # full blocks chain-hashed so far
    pc_hash: int = 0                    # chain hash after block pc_blocks-1
    preemptions: int = 0                # times evicted back to the queue
    admit_seq: int = -1                 # admission order (victim selection)
    spec_fails: int = 0                 # consecutive all-rejected proposals
    #                                     (speculative back-off; ISSUE 13)
    spec_quiet: int = 0                 # backed-off ticks since the last
    #                                     probe (re-arm cadence)
    # distributed-tracing context (ISSUE 15): the fleet-wide id this
    # request's timeline events carry, and which dispatch attempt this
    # engine-local incarnation is — None/0 outside a traced fleet (the
    # engine's events then stay rid-keyed and process-local, exactly
    # the pre-tracing shape)
    trace_id: Optional[str] = None
    trace_attempt: int = 0

    # wall-clock marks for the latency metrics (engine-stamped)
    t_submit: float = 0.0
    t_first_token: Optional[float] = None
    t_last_token: Optional[float] = None

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.REJECTED)

    @property
    def prefilling(self) -> bool:
        """RUNNING but with prompt tokens still to land in the cache."""
        return (self.state is RequestState.RUNNING
                and self.cache_len < self.prefill_target)

    @property
    def last_token(self) -> int:
        if self.output_tokens:
            return self.output_tokens[-1]
        return int(self.prompt[-1])

    def sequence_tokens(self) -> List[int]:
        """Every token this request has: prompt + emitted stream (the
        readmission wire, and the content key of its cache blocks)."""
        return list(map(int, self.prompt)) + self.output_tokens


class Scheduler:
    """Slot + block bookkeeping for the continuous batch."""

    def __init__(self, cache: KVCacheConfig, max_batch: int, *,
                 chunk_tokens: Optional[int] = None,
                 admission: str = "occupancy",
                 prefix_caching: bool = True):
        if admission not in ("occupancy", "reserve"):
            raise ValueError(
                f"admission must be 'occupancy' or 'reserve', got "
                f"{admission!r}")
        self.cache = cache
        self.max_batch = max_batch
        self.admission = admission
        self.chunk_tokens = chunk_tokens or cache.max_seq
        self.allocator = BlockAllocator(cache.n_blocks)
        # reserve mode cannot share (a reservation is exclusive by
        # definition), so the cache only exists under occupancy admission
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.allocator, cache.block_size)
            if prefix_caching and admission == "occupancy" else None)
        self.slots: List[Optional[Request]] = [None] * max_batch
        self.waiting: Deque[Request] = collections.deque()
        self._ids = itertools.count()
        self._admit_seq = itertools.count()
        self.draining = False
        self.preemptions = 0            # lifetime count (engine flushes)

    # ------------------------------------------------------------- submit

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               sampling: Optional[SamplingParams] = None) -> Request:
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token list")
        if prompt.size >= self.cache.max_seq:
            raise ValueError(
                f"prompt of {prompt.size} tokens does not fit max_seq="
                f"{self.cache.max_seq} with room to generate")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = Request(rid=next(self._ids), prompt=prompt,
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      sampling=sampling or SamplingParams(),
                      t_submit=time.monotonic())
        need = self._worst_case_blocks(req)
        if need > self.allocator.n_blocks:
            # the one reservation rule occupancy admission keeps: a
            # request the WHOLE pool cannot cover would either starve
            # the FIFO head forever (reserve mode) or preempt every
            # neighbour and still never finish (occupancy mode) —
            # reject it at the door instead
            raise ValueError(
                f"request needs {need} blocks worst-case "
                f"(prompt {prompt.size} + max_new_tokens "
                f"{max_new_tokens}) but the arena has only "
                f"{self.allocator.n_blocks}; raise n_blocks or lower "
                "max_new_tokens")
        if self.draining:
            # a submit that lands in the drain window is refused with a
            # typed terminal state, NOT accepted-then-cancelled: the
            # caller (a fleet router, a retrying client) must be able to
            # tell "this engine would never have run it" from "it was
            # queued and the drain killed it"
            req.state = RequestState.REJECTED
            return req
        self.waiting.append(req)
        return req

    # -------------------------------------------------------------- admit

    def _worst_case_blocks(self, req: Request) -> int:
        horizon = min(len(req.prompt) + req.max_new_tokens,
                      self.cache.max_seq)
        return self.cache.blocks_for(horizon)

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.slots) if r is None]

    def _ensure_free(self, n: int) -> bool:
        """Raise ``n_free`` to ``n`` by evicting prefix-cache LRU blocks
        (capacity held only as an optimization — the whole deficit is
        swept in one pass); False when the cache runs out first."""
        deficit = n - self.allocator.n_free
        if deficit > 0 and self.prefix_cache is not None:
            self.prefix_cache.evict_many(deficit)
        return self.allocator.n_free >= n

    def admit(self) -> List[Request]:
        """Move WAITING requests into free slots while capacity lasts
        (FIFO — no request starves behind a later, smaller one).
        Returns the newly-admitted requests; the engine prefills them.

        Occupancy admission: consult the prefix cache (shared blocks
        are refcounted, their tokens never recomputed), then require
        blocks for the first prefill chunk only — evicting cached
        blocks to make room, but never preempting (running requests
        outrank arrivals).  Reserve admission (the PR 8 baseline):
        the whole worst-case horizon or nothing."""
        admitted: List[Request] = []
        if self.draining:
            return admitted
        free = self.free_slots()
        while self.waiting and free:
            req = self.waiting[0]
            wire = req.sequence_tokens()
            if self.admission == "reserve":
                need = self._worst_case_blocks(req)
                if not self.allocator.can_alloc(need):
                    break
                shared: List[int] = []
            else:
                shared = []
                if self.prefix_cache is not None:
                    # cap: always leave >= 1 token to recompute — the
                    # recompute emits the request's next sampled token,
                    # and it keeps every write on private blocks
                    shared = self.prefix_cache.lookup(
                        wire, req.rid,
                        max_blocks=(len(wire) - 1)
                        // self.cache.block_size)
                hit_len = len(shared) * self.cache.block_size
                chunk = min(len(wire) - hit_len, self.chunk_tokens)
                need = self.cache.blocks_for(hit_len + chunk) - len(shared)
                if not self._ensure_free(need):
                    # not even the first chunk fits: the FIFO head
                    # blocks (hand the shared refs back — the entries
                    # stay cached for the retry — and roll the hit
                    # count back: nothing was *served*, and a head
                    # stuck behind a full pool for N ticks must not
                    # inflate serving/prefix_cache_hits N times)
                    if shared:
                        self.allocator.free(shared, owner=req.rid)
                        self.prefix_cache.hits -= len(shared)
                    break
            self.waiting.popleft()
            req.blocks = shared + self.allocator.alloc(need, owner=req.rid)
            req.hit_blocks = len(shared)
            req.pc_blocks = 0
            req.pc_hash = 0
            req.cache_len = len(shared) * self.cache.block_size
            req.prefill_target = len(wire)
            req.slot = free.pop(0)
            req.state = RequestState.RUNNING
            req.admit_seq = next(self._admit_seq)
            self.slots[req.slot] = req
            admitted.append(req)
        return admitted

    def admit_imported(self, prompt: Sequence[int], max_new_tokens: int,
                       eos_id: Optional[int] = None,
                       sampling: Optional[SamplingParams] = None, *,
                       cache_len: int, n_blocks: int) -> Request:
        """Admit a request whose KV for ``prompt[:cache_len]`` is about
        to be *imported* (KV-block migration, ISSUE 16) instead of
        computed here.

        Allocates blocks covering the whole prefill target (the
        imported run plus the remaining-tail blocks, so the chunked
        prefill of the uncovered tokens never scatters out of range),
        binds a slot immediately — the migrated payload is already
        committed to this host, parking it behind the FIFO would strand
        device memory — and returns the RUNNING request with
        ``cache_len`` pre-seeded.  The engine scatters the payload into
        ``req.blocks[:n_blocks]`` and the ordinary chunked-prefill path
        covers ``prompt[cache_len:]`` (for a migration that is exactly
        the last wire token — the same recompute-one-token shape as a
        prefix-cache hit), which is what makes the continued stream
        bitwise the failover-replay stream.  Raises ``ValueError`` /
        :class:`~apex_tpu.serving.kv_cache.OutOfBlocksError` when slot
        or pool capacity is missing (the caller degrades to
        re-prefill); a drain window returns a REJECTED request, exactly
        like :meth:`submit`."""
        from apex_tpu.serving.kv_cache import OutOfBlocksError

        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token list")
        if prompt.size >= self.cache.max_seq:
            raise ValueError(
                f"imported prompt of {prompt.size} tokens does not fit "
                f"max_seq={self.cache.max_seq} with room to generate")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0 < cache_len < prompt.size:
            raise ValueError(
                f"imported cache_len {cache_len} must cover part of the "
                f"{prompt.size}-token prompt (>= 1 token recomputed)")
        if n_blocks != self.cache.blocks_for(cache_len):
            raise ValueError(
                f"imported run of {n_blocks} blocks does not cover "
                f"cache_len {cache_len} (block_size "
                f"{self.cache.block_size})")
        req = Request(rid=next(self._ids), prompt=prompt,
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      sampling=sampling or SamplingParams(),
                      t_submit=time.monotonic())
        if self._worst_case_blocks(req) > self.allocator.n_blocks:
            raise ValueError(
                "imported request exceeds the whole pool worst-case")
        if self.draining:
            req.state = RequestState.REJECTED
            return req
        free = self.free_slots()
        if not free:
            raise ValueError("no free decode slot for the imported "
                             "request")
        if self.admission == "reserve":
            need = self._worst_case_blocks(req)
        else:
            need = self.cache.blocks_for(prompt.size)
        if not self._ensure_free(need):
            raise OutOfBlocksError(
                f"imported request needs {need} blocks, only "
                f"{self.allocator.n_free} free after eviction")
        req.blocks = self.allocator.alloc(need, owner=req.rid)
        req.hit_blocks = 0
        req.pc_blocks = 0
        req.pc_hash = 0
        req.cache_len = int(cache_len)
        req.prefill_target = prompt.size
        req.slot = free[0]
        req.state = RequestState.RUNNING
        req.admit_seq = next(self._admit_seq)
        self.slots[req.slot] = req
        # NB the imported run is NOT indexed into the prefix cache here:
        # its content has not landed in the arena yet.  The engine calls
        # :meth:`note_imported` after the batched scatter.
        return req

    def note_imported(self, req: Request) -> None:
        """Index an imported request's landed run into the prefix cache
        (called by the engine after the batched scatter — indexing
        before the device put lands would let a same-tick hit share
        garbage blocks)."""
        self._index_into_cache(req)

    # ------------------------------------------------------------- growth

    def try_grow_to(self, req: Request, n_tokens: int, *,
                    preempt: bool = True) -> int:
        """Grow ``req.blocks`` toward covering ``n_tokens`` of cache,
        taking blocks on demand: free list first, then prefix-cache
        eviction, then (``preempt=True``) preemption of strictly
        *newer* requests.  Returns the token count the request's blocks
        now cover — a newer request with nothing left to preempt simply
        waits its turn (the engine skips its chunk/decode this tick),
        while the oldest running request always reaches its target
        (everything else is evictable or preemptable), which is what
        makes every admitted request terminate under oversubscription.

        ``preempt=False`` stops the ladder at eviction — the engine's
        *speculative* growth (blocks for drafted tokens, ISSUE 13) uses
        this: drafting is an optimization and must never pay for itself
        by throwing away a neighbour's computed KV."""
        target = self.cache.blocks_for(n_tokens)
        while len(req.blocks) < target:
            want = target - len(req.blocks)
            if self._ensure_free(1):
                got = self.allocator.alloc(
                    min(want, self.allocator.n_free), owner=req.rid)
                req.blocks.extend(got)
                continue
            if not preempt:
                break
            victim = self._pick_victim(exclude=req)
            if victim is None:
                break
            self.preempt(victim)
        return len(req.blocks) * self.cache.block_size

    def _pick_victim(self, exclude: Request) -> Optional[Request]:
        """Newest-admitted running request other than ``exclude`` —
        preempting strictly newer work is what guarantees the oldest
        request always completes (no preemption livelock)."""
        candidates = [r for r in self.slots
                      if r is not None and r is not exclude
                      and r.admit_seq > exclude.admit_seq]
        if not candidates:
            return None
        return max(candidates, key=lambda r: r.admit_seq)

    def preempt(self, req: Request) -> None:
        """Evict a RUNNING request back to the queue: its full cache
        blocks are first indexed into the prefix cache (the work
        already done is kept as *evictable* capacity, and the
        readmission usually hits it), every block ref is released, and
        the request returns to the FRONT of the queue to recompute —
        prompt + emitted tokens replay through the ordinary chunked
        prefill path on readmission."""
        if req.state is not RequestState.RUNNING:
            raise ValueError(f"preempt() on {req.state} request {req.rid}")
        from apex_tpu.observability import timeline

        self._index_into_cache(req)
        self.allocator.free(req.blocks, owner=req.rid)
        req.blocks = []
        self.slots[req.slot] = None
        req.slot = None
        req.cache_len = 0
        req.prefill_target = 0
        req.state = RequestState.WAITING
        req.preemptions += 1
        self.preemptions += 1
        self.waiting.appendleft(req)
        timeline.emit("request_preempt", rid=req.rid,
                      tokens=len(req.output_tokens),
                      **trace_fields(req))

    def _index_into_cache(self, req: Request) -> None:
        if self.prefix_cache is None:
            return
        # content actually in the arena: the first cache_len tokens of
        # the stream (the last sampled token is emitted before it is
        # written, so it is NOT cache content yet).  The chain-hash
        # cursor rides the request, so each full block is hashed ONCE
        # per admission however many chunks the prompt takes.
        n_full = min(req.cache_len // self.cache.block_size,
                     len(req.blocks))
        if n_full <= req.pc_blocks:
            return
        req.pc_hash = self.prefix_cache.insert(
            req.sequence_tokens()[:req.cache_len], req.blocks,
            req.cache_len, start_block=req.pc_blocks,
            prev_hash=req.pc_hash)
        req.pc_blocks = n_full

    def note_prefilled(self, req: Request, n_tokens: int) -> None:
        """Account a prefill chunk landing in the arena; newly complete
        full blocks become shareable prefix-cache entries (a same-tick
        arrival with the same template already hits them)."""
        req.cache_len += n_tokens
        self._index_into_cache(req)

    # ------------------------------------------------------------- finish

    def finish(self, req: Request) -> None:
        """Release a RUNNING request's slot and blocks; its full blocks
        stay behind as prefix-cache entries (evictable capacity — a
        follow-up request extending this stream prefills almost
        nothing)."""
        if req.state is not RequestState.RUNNING:
            raise ValueError(f"finish() on {req.state} request {req.rid}")
        self._index_into_cache(req)
        self.allocator.free(req.blocks, owner=req.rid)
        req.blocks = []
        self.slots[req.slot] = None
        req.slot = None
        req.state = RequestState.FINISHED
        req.t_last_token = time.monotonic()

    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def drain(self) -> List[Request]:
        """Stop admissions and cancel the queue (including preempted
        requests — their partial streams were already delivered);
        running requests keep their slots (the engine decodes them to
        completion).  Returns the cancelled requests."""
        self.draining = True
        cancelled = list(self.waiting)
        self.waiting.clear()
        for req in cancelled:
            req.state = RequestState.CANCELLED
        return cancelled

    @property
    def idle(self) -> bool:
        return not self.waiting and all(r is None for r in self.slots)

    def kv_occupancy(self) -> float:
        """Fraction of the pool holding live or cached KV (the number
        worst-case reservation kept artificially low)."""
        return 1.0 - self.allocator.n_free / self.allocator.n_blocks
