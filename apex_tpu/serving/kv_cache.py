"""Paged KV cache: pooled block arena + host-side block allocator.

The vLLM paging model mapped onto the repo's sharded-state conventions:

- **Device side** — one pooled arena per K and per V, shape
  ``[n_layers, n_blocks, block_size, kv_heads, head_dim]`` (each layer's
  slice is the ``[n_blocks, block, heads, head_dim]`` arena of the
  design), held as a *global array* sharded over the tensor-parallel
  axis on the heads dim — the same chop as the tensor-parallel
  attention heads, so every tp rank owns the cache rows of exactly the
  heads it computes.  The arena is **donated** through the decode step
  (``jax.jit(..., donate_argnums=...)``) so XLA updates it in place: a
  non-donated cache would double the single largest HBM tenant of a
  serving chip (analyzer entry ``serving_decode``, rule APX204, audits
  exactly this).
- **Host side** — :class:`BlockAllocator`: a free list of physical
  block ids with ownership tracking.  Allocation is O(1) per block and
  *fragmentation-free by construction*: blocks are fixed-size and any
  free block can serve any request, so the only admission question is
  ``n_free >= blocks_needed`` — never "is there a contiguous run".
  Invariants (every block is free XOR owned by exactly one request;
  double-free and foreign-free raise) are checked by
  :meth:`BlockAllocator.check` and pinned in ``tests/test_serving.py``.

The per-request *block table* (logical block index -> physical block
id) lives with the scheduler's request records; the engine packs the
tables of the active slots into one ``[max_batch, max_blocks]`` int32
device argument each step — churn changes the table *values*, never
any shape, which is what keeps the decode step compile-stable.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "KVCacheConfig",
    "BlockAllocator",
    "OutOfBlocksError",
    "init_kv_arena",
    "arena_partition_spec",
]


class OutOfBlocksError(RuntimeError):
    """The arena cannot serve the requested number of blocks.

    Admission control is expected to check :meth:`BlockAllocator.can_alloc`
    first; hitting this during a decode append means the operator sized
    ``n_blocks`` below ``max_batch * max_blocks_per_request``.
    """


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape of the paged cache.

    ``kv_heads`` is the *global* K/V head count (``config.query_groups``
    of the served model); under tensor parallelism each rank holds
    ``kv_heads / tp`` of them.  ``max_seq`` rounds up to whole blocks;
    ``max_blocks_per_request`` is the per-request block-table width.
    """

    n_layers: int
    n_blocks: int
    block_size: int
    kv_heads: int
    head_dim: int
    max_seq: int
    dtype: Any = np.float32

    def __post_init__(self):
        if self.block_size < 1 or self.n_blocks < 1:
            raise ValueError(
                f"block_size ({self.block_size}) and n_blocks "
                f"({self.n_blocks}) must be positive")
        if self.max_seq < 1:
            raise ValueError(f"max_seq must be positive, got {self.max_seq}")

    @property
    def max_blocks_per_request(self) -> int:
        return -(-self.max_seq // self.block_size)

    def blocks_for(self, n_tokens: int) -> int:
        """Number of blocks a sequence of ``n_tokens`` occupies."""
        return -(-n_tokens // self.block_size)


def arena_partition_spec(tp_axis: Optional[str]):
    """PartitionSpec of one arena: heads (dim 3) sharded over ``tp``."""
    from jax.sharding import PartitionSpec as P

    return P(None, None, None, tp_axis, None)


def init_kv_arena(cfg: KVCacheConfig, mesh=None, tp_axis: Optional[str] = "tp"
                  ) -> Tuple[Any, Any]:
    """Allocate the zeroed ``(k, v)`` arenas as sharded global arrays.

    Shape ``[n_layers, n_blocks, block_size, kv_heads, head_dim]``,
    heads sharded over ``tp_axis`` when a mesh is given (the same axis
    the attention heads are column-parallel over, so the cache rows a
    rank reads in the paged kernel are exactly the rows it owns).
    """
    import jax
    import jax.numpy as jnp

    shape = (cfg.n_layers, cfg.n_blocks, cfg.block_size, cfg.kv_heads,
             cfg.head_dim)
    k = jnp.zeros(shape, cfg.dtype)
    v = jnp.zeros(shape, cfg.dtype)
    if mesh is not None and tp_axis is not None:
        from jax.sharding import NamedSharding

        if cfg.kv_heads % mesh.shape[tp_axis]:
            raise ValueError(
                f"kv_heads ({cfg.kv_heads}) not divisible by tp "
                f"({mesh.shape[tp_axis]})")
        sharding = NamedSharding(mesh, arena_partition_spec(tp_axis))
        k = jax.device_put(k, sharding)
        v = jax.device_put(v, sharding)
    return k, v


class BlockAllocator:
    """Free-list allocator over the physical block pool.

    LIFO free list (recently-freed blocks are reused first — their HBM
    pages are the warmest) plus an ownership map for invariant checking.
    NOT thread-safe: the scheduler owns it from one thread, matching the
    engine's single-threaded step loop.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be positive, got {n_blocks}")
        self.n_blocks = n_blocks
        self._free: List[int] = list(range(n_blocks - 1, -1, -1))
        self._owner: Dict[int, Any] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_owned(self) -> int:
        return len(self._owner)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int, owner: Any = None) -> List[int]:
        """Take ``n`` blocks for ``owner``; raises :class:`OutOfBlocksError`
        (allocating nothing) when fewer than ``n`` are free."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            raise OutOfBlocksError(
                f"requested {n} blocks, only {len(self._free)} of "
                f"{self.n_blocks} free")
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._owner[b] = owner
        return blocks

    def free(self, blocks: Sequence[int], owner: Any = None) -> None:
        """Return blocks to the pool.  A block that is already free
        (double free) or owned by someone else raises — silently
        recycling a live request's cache rows is the worst failure mode
        a paged cache has."""
        for b in blocks:
            if b not in self._owner:
                raise ValueError(f"double free of block {b}")
            if self._owner[b] != owner:
                raise ValueError(
                    f"block {b} owned by {self._owner[b]!r}, freed by "
                    f"{owner!r}")
        for b in blocks:
            del self._owner[b]
            self._free.append(b)

    def check(self) -> None:
        """Assert the pool invariant: free and owned partition the pool
        (no leak, no double ownership, no phantom ids)."""
        free = set(self._free)
        owned = set(self._owner)
        if len(free) != len(self._free):
            raise AssertionError("duplicate ids on the free list")
        if free & owned:
            raise AssertionError(
                f"blocks both free and owned: {sorted(free & owned)}")
        if free | owned != set(range(self.n_blocks)):
            raise AssertionError(
                f"pool leak: {self.n_blocks - len(free) - len(owned)} "
                "blocks neither free nor owned")
