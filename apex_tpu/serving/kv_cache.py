"""Paged KV cache: pooled block arena + host-side block allocator.

The vLLM paging model mapped onto the repo's sharded-state conventions:

- **Device side** — one pooled arena per K and per V, shape
  ``[n_layers, n_blocks, block_size, kv_heads, head_dim]`` (each layer's
  slice is the ``[n_blocks, block, heads, head_dim]`` arena of the
  design), held as a *global array* sharded over the tensor-parallel
  axis on the heads dim — the same chop as the tensor-parallel
  attention heads, so every tp rank owns the cache rows of exactly the
  heads it computes.  The arena is **donated** through the decode step
  (``jax.jit(..., donate_argnums=...)``) so XLA updates it in place: a
  non-donated cache would double the single largest HBM tenant of a
  serving chip (analyzer entry ``serving_decode``, rule APX204, audits
  exactly this).  With an **int8** cache (``cache_dtype=jnp.int8``) a
  pair of fp32 *scale arenas* ``[n_layers, n_blocks, block_size,
  kv_heads]`` rides along — one symmetric scale per cached K/V vector,
  stored block-major beside its block (1/``head_dim`` of the cache's
  own footprint) and dequantized inside the paged-attention kernel.
- **Host side** — :class:`BlockAllocator`: a free list of physical
  block ids with **refcounted** ownership.  Allocation is O(1) per
  block and *fragmentation-free by construction*: blocks are fixed-size
  and any free block can serve any request, so the only admission
  question is ``n_free >= blocks_needed`` — never "is there a
  contiguous run".  Copy-on-write prefix sharing rides on the
  refcounts: :meth:`BlockAllocator.share` adds a holder to a live
  block, and :meth:`BlockAllocator.free` *decrements* — the block
  returns to the pool only when its last holder lets go.  (Writes never
  target shared blocks in this engine: prefix hits are block-aligned
  and always leave >= 1 prompt token to recompute, so the private tail
  a request appends into starts past every shared block — the copy
  step of classic CoW is unreachable by construction, and the
  refcounts ARE the invariant.)  Invariants (every block is free XOR
  held by >= 1 owner; double-free and foreign-free raise) are checked
  by :meth:`BlockAllocator.check` and pinned in ``tests/test_serving.py``.
- :class:`PrefixCache` — the token-hash index over shared blocks.  A
  full block of a request's sequence is keyed by the *chain hash* of
  every token up to and including that block, so a lookup walks
  block-sized strides of a new prompt and shares the longest cached
  prefix (capped so at least one token is always recomputed — the
  recompute produces the first sampled token, and it keeps writes off
  shared blocks).  Entries hold their own refcount on the block; a
  finished request's blocks therefore survive it *as cache*, and the
  eviction sweep (:meth:`PrefixCache.evict_one`, LRU) is what finally
  returns them to the free list when the pool runs dry.

The per-request *block table* (logical block index -> physical block
id) lives with the scheduler's request records; the engine packs the
tables of the active slots into one ``[max_batch, max_blocks]`` int32
device argument each step — churn changes the table *values*, never
any shape, which is what keeps the decode step compile-stable.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

__all__ = [
    "KVCacheConfig",
    "BlockAllocator",
    "OutOfBlocksError",
    "PrefixCache",
    "CACHE_OWNER",
    "EXPORT_OWNER",
    "KVExport",
    "ExportLedger",
    "init_kv_arena",
    "arena_partition_spec",
    "scale_partition_spec",
]

# the PrefixCache's own hold on a shared block (distinct from any
# request id, so foreign-free checks see the cache as just another
# owner — freeing a cached block with a request's id raises)
CACHE_OWNER = "<prefix-cache>"

# prefix of the composite owner a mid-migration export pin holds blocks
# under: ``(EXPORT_OWNER, rid)`` — distinct from both the request id and
# CACHE_OWNER, so the source request can finish (its own refs free) while
# the exported run stays pinned until the decode side acks receipt
EXPORT_OWNER = "<kv-export>"


class OutOfBlocksError(RuntimeError):
    """The arena cannot serve the requested number of blocks.

    Admission control is expected to check :meth:`BlockAllocator.can_alloc`
    first; hitting this during a decode append means the scheduler's
    grow path (evict, then preempt) failed to raise ``n_free`` — a bug,
    since the submit-time whole-pool check guarantees any single
    request fits."""


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape of the paged cache.

    ``kv_heads`` is the *global* K/V head count (``config.query_groups``
    of the served model); under tensor parallelism each rank holds
    ``kv_heads / tp`` of them.  ``max_seq`` rounds up to whole blocks;
    ``max_blocks_per_request`` is the per-request block-table width.
    ``dtype`` is the arena storage dtype; ``int8`` additionally
    allocates the per-vector scale arenas (:attr:`quantized`).
    """

    n_layers: int
    n_blocks: int
    block_size: int
    kv_heads: int
    head_dim: int
    max_seq: int
    dtype: Any = np.float32

    def __post_init__(self):
        if self.block_size < 1 or self.n_blocks < 1:
            raise ValueError(
                f"block_size ({self.block_size}) and n_blocks "
                f"({self.n_blocks}) must be positive")
        if self.max_seq < 1:
            raise ValueError(f"max_seq must be positive, got {self.max_seq}")

    @property
    def quantized(self) -> bool:
        """True when the arena stores int8 (scale arenas ride along)."""
        return np.dtype(self.dtype) == np.dtype(np.int8)

    @property
    def max_blocks_per_request(self) -> int:
        return -(-self.max_seq // self.block_size)

    def blocks_for(self, n_tokens: int) -> int:
        """Number of blocks a sequence of ``n_tokens`` occupies."""
        return -(-n_tokens // self.block_size)


def arena_partition_spec(tp_axis: Optional[str]):
    """PartitionSpec of one arena: heads (dim 3) sharded over ``tp``."""
    from jax.sharding import PartitionSpec as P

    return P(None, None, None, tp_axis, None)


def scale_partition_spec(tp_axis: Optional[str]):
    """PartitionSpec of one int8 scale arena
    ``[n_layers, n_blocks, block_size, kv_heads]`` — the same heads
    chop as the arena it scales (a rank dequantizes only rows it owns)."""
    from jax.sharding import PartitionSpec as P

    if tp_axis is None:
        return P()
    return P(None, None, None, tp_axis)


def init_kv_arena(cfg: KVCacheConfig, mesh=None, tp_axis: Optional[str] = "tp"
                  ) -> Tuple[Any, ...]:
    """Allocate the zeroed arenas as sharded global arrays.

    Returns ``(k, v)`` — or ``(k, v, k_scales, v_scales)`` for an int8
    cache — with shape ``[n_layers, n_blocks, block_size, kv_heads,
    head_dim]`` (scales drop the trailing ``head_dim``), heads sharded
    over ``tp_axis`` when a mesh is given (the same axis the attention
    heads are column-parallel over, so the cache rows a rank reads in
    the paged kernel are exactly the rows it owns).
    """
    import jax
    import jax.numpy as jnp

    shape = (cfg.n_layers, cfg.n_blocks, cfg.block_size, cfg.kv_heads,
             cfg.head_dim)
    arenas = [jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)]
    specs = [arena_partition_spec(tp_axis)] * 2
    if cfg.quantized:
        sshape = shape[:-1]
        # on a size-1 tp axis the scale placement is spelled replicated
        # (P() — what jit emits for the step outputs there, so the
        # engine's arena round trip stays jit-cache-stable; at tp > 1
        # the named spec round-trips intact either way)
        s_axis = tp_axis
        if mesh is not None and s_axis is not None \
                and mesh.shape[s_axis] == 1:
            s_axis = None
        arenas += [jnp.ones(sshape, jnp.float32),
                   jnp.ones(sshape, jnp.float32)]
        specs += [scale_partition_spec(s_axis)] * 2
    if mesh is not None and tp_axis is not None:
        from jax.sharding import NamedSharding

        if cfg.kv_heads % mesh.shape[tp_axis]:
            raise ValueError(
                f"kv_heads ({cfg.kv_heads}) not divisible by tp "
                f"({mesh.shape[tp_axis]})")
        arenas = [jax.device_put(a, NamedSharding(mesh, s))
                  for a, s in zip(arenas, specs)]
    return tuple(arenas)


class BlockAllocator:
    """Refcounted free-list allocator over the physical block pool.

    LIFO free list (recently-freed blocks are reused first — their HBM
    pages are the warmest) plus a per-block holder set: a block is free
    XOR held by one or more owners (a request id, or the prefix cache's
    :data:`CACHE_OWNER`).  :meth:`share` is the copy-on-write incref —
    a prefix hit adds the hitting request as a holder; :meth:`free` is
    the decref — the block returns to the pool only when the last
    holder releases it.  NOT thread-safe: the scheduler owns it from
    one thread, matching the engine's single-threaded step loop.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be positive, got {n_blocks}")
        self.n_blocks = n_blocks
        self._free: List[int] = list(range(n_blocks - 1, -1, -1))
        self._holders: Dict[int, Set[Any]] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_owned(self) -> int:
        """Blocks with at least one holder (shared blocks count once)."""
        return len(self._holders)

    def refcount(self, block: int) -> int:
        """Holder count of ``block`` (0 = free)."""
        return len(self._holders.get(block, ()))

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int, owner: Any = None) -> List[int]:
        """Take ``n`` fresh (refcount-1) blocks for ``owner``; raises
        :class:`OutOfBlocksError` (allocating nothing) when fewer than
        ``n`` are free."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            raise OutOfBlocksError(
                f"requested {n} blocks, only {len(self._free)} of "
                f"{self.n_blocks} free")
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._holders[b] = {owner}
        return blocks

    def share(self, block: int, owner: Any) -> None:
        """Copy-on-write incref: add ``owner`` as a holder of a live
        block (a prefix-cache hit, or the cache registering a freshly
        prefilled block).  Sharing a free block or double-sharing by
        the same owner raises — both would corrupt the refcount."""
        holders = self._holders.get(block)
        if holders is None:
            raise ValueError(f"cannot share free block {block}")
        if owner in holders:
            raise ValueError(
                f"owner {owner!r} already holds block {block}")
        holders.add(owner)

    def free(self, blocks: Sequence[int], owner: Any = None) -> None:
        """Release ``owner``'s hold on each block.  A shared block
        merely *decrements* (the other holders — the prefix cache, a
        sibling request — keep it live); the last release returns it to
        the pool.  A block that is already free (double free) or not
        held by ``owner`` (foreign free) raises — silently recycling a
        live request's cache rows is the worst failure mode a paged
        cache has."""
        for b in blocks:
            holders = self._holders.get(b)
            if holders is None:
                raise ValueError(f"double free of block {b}")
            if owner not in holders:
                raise ValueError(
                    f"block {b} owned by {sorted(map(repr, holders))}, "
                    f"freed by {owner!r}")
        for b in blocks:
            holders = self._holders[b]
            holders.discard(owner)
            if not holders:
                del self._holders[b]
                self._free.append(b)

    def check(self) -> None:
        """Assert the pool invariant: free and held partition the pool
        (no leak, no double ownership, no phantom ids, no empty holder
        sets)."""
        free = set(self._free)
        held = set(self._holders)
        if len(free) != len(self._free):
            raise AssertionError("duplicate ids on the free list")
        if free & held:
            raise AssertionError(
                f"blocks both free and held: {sorted(free & held)}")
        if free | held != set(range(self.n_blocks)):
            raise AssertionError(
                f"pool leak: {self.n_blocks - len(free) - len(held)} "
                "blocks neither free nor held")
        empties = [b for b, h in self._holders.items() if not h]
        if empties:
            raise AssertionError(f"held blocks with no holders: {empties}")


@dataclasses.dataclass
class KVExport:
    """One migrating block run, pinned on the source until acked.

    ``blocks`` is the prefix-order physical run covering ``cache_len``
    tokens of ``tokens`` (the request's wire sequence at export time —
    kept so an acked run can be indexed into the prefix cache under its
    chain hash).  The pin holds every block under the composite owner
    ``(EXPORT_OWNER, rid)``; the exporting request's own refs free
    normally when it leaves the scheduler."""

    rid: Any
    blocks: List[int]
    tokens: List[int]
    cache_len: int

    @property
    def owner(self) -> Tuple[str, Any]:
        return (EXPORT_OWNER, self.rid)


class ExportLedger:
    """Pin-until-ack bookkeeping for KV-block migration (ISSUE 16).

    The refcount story of a migration, on the source replica:

    1. :meth:`pin` — every block of the run gains the export owner
       (refcount +1).  The exporting request then leaves the scheduler
       and its own refs free normally; the run survives at refcount 1.
    2. The blocks stream over the wire.  Nothing here can recycle them:
       the pin is a first-class holder, so ``BlockAllocator.check()``
       stays free-XOR-held at every step.
    3. :meth:`release` on the decode side's ack — the run's *full*
       blocks are indexed into the prefix cache (the cache increfs
       before the pin decrefs, so no block ever transits through free),
       turning the shipped prefill into evictable local capacity; the
       partial tail block and, on a failed migration, every block just
       free back to the pool.

    A source that dies mid-migration leaks nothing *by construction*:
    the ledger and pool die with the process, and the decode side either
    committed (it owns its own imported copies) or degrades to
    re-prefill through the router's replay path.  ``release`` is
    idempotent — a duplicate or stale ack (router retry after a
    reconnect) is a no-op, never a double free."""

    def __init__(self, allocator: BlockAllocator,
                 prefix_cache: Optional["PrefixCache"] = None):
        self.allocator = allocator
        self.prefix_cache = prefix_cache
        self._pins: Dict[Any, KVExport] = {}

    def __len__(self) -> int:
        return len(self._pins)

    def pin(self, rid: Any, blocks: Sequence[int],
            tokens: Sequence[int], cache_len: int) -> KVExport:
        """Pin ``blocks`` (the run covering ``cache_len`` tokens) under
        the export owner.  One outstanding export per request id."""
        if rid in self._pins:
            raise ValueError(f"request {rid!r} already has an export "
                             "in flight")
        exp = KVExport(rid=rid, blocks=list(blocks),
                       tokens=[int(t) for t in tokens],
                       cache_len=int(cache_len))
        pinned = []
        try:
            for b in exp.blocks:
                self.allocator.share(b, exp.owner)
                pinned.append(b)
        except ValueError:
            # roll the partial pin back before re-raising: the ledger
            # never holds a half-pinned run
            for b in pinned:
                self.allocator.free([b], owner=exp.owner)
            raise
        self._pins[rid] = exp
        return exp

    def release(self, rid: Any, *, to_cache: bool = True) -> int:
        """Drop the pin on ``rid``'s run.  ``to_cache=True`` (the ack
        path) first indexes the run's full blocks into the prefix
        cache, so the shipped prefill stays hittable locally; the
        failed-migration path (``to_cache=False``) and the partial tail
        block free straight back to the pool.  Returns the number of
        blocks that went into the cache; unknown/duplicate ids are a
        no-op (0)."""
        exp = self._pins.pop(rid, None)
        if exp is None:
            return 0
        cached = 0
        if to_cache and self.prefix_cache is not None:
            before = self.prefix_cache.n_blocks
            self.prefix_cache.insert(exp.tokens, exp.blocks, exp.cache_len)
            cached = self.prefix_cache.n_blocks - before
        self.allocator.free(exp.blocks, owner=exp.owner)
        return cached

    def release_all(self, *, to_cache: bool = False) -> None:
        """Drop every outstanding pin (drain/shutdown path)."""
        for rid in list(self._pins):
            self.release(rid, to_cache=to_cache)

    def check(self) -> None:
        """Every pinned block must be live and held by its export
        owner (the ledger's half of the free-XOR-held invariant)."""
        for exp in self._pins.values():
            for b in exp.blocks:
                holders = self.allocator._holders.get(b)
                if not holders or exp.owner not in holders:
                    raise AssertionError(
                        f"export pin of {exp.rid!r} lost block {b}")


class PrefixCache:
    """Token-hash index of shareable full blocks (copy-on-write prefix
    caching).

    Each entry maps the *chain hash* of a sequence's first
    ``(i + 1) * block_size`` tokens to the physical block holding
    tokens ``[i * block_size, (i + 1) * block_size)`` of that sequence.
    The chain construction means a lookup needs no trie: walk the new
    prompt block by block, rehashing cumulatively, and stop at the
    first miss — every hit is automatically content- AND
    position-consistent with the whole prefix before it.

    The cache holds its own refcount (:data:`CACHE_OWNER`) on every
    indexed block, which is what lets blocks outlive the request that
    wrote them.  ``evict_one`` frees the least-recently-used entry
    whose block the cache is the *sole* holder of — evicting a block a
    live request still shares would free no capacity and lose a hot
    prefix, so such entries are skipped (they re-enter the evictable
    set when their last sharer finishes).
    """

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self.allocator = allocator
        self.block_size = block_size
        # insertion/touch order == LRU order (move_to_end on every hit)
        self._entries: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()
        self.hits = 0            # blocks served from cache (lifetime)
        self.evictions = 0       # entries evicted for capacity (lifetime)

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def n_blocks(self) -> int:
        return len(self._entries)

    def _block_hash(self, prev_hash: int, tokens: Sequence[int],
                    i: int) -> int:
        """Chain hash of full block ``i`` given the previous block's."""
        chunk = tuple(int(t) for t in
                      tokens[i * self.block_size:(i + 1) * self.block_size])
        return hash((prev_hash, chunk))

    def lookup(self, tokens: Sequence[int], owner: Any,
               *, max_blocks: Optional[int] = None) -> List[int]:
        """Share the longest cached prefix of ``tokens`` with ``owner``.

        Walks full blocks, hashing incrementally and stopping at the
        first miss (O(hit) host work, never O(prompt)).  The cap is
        ENFORCED here, not trusted to callers: at most
        ``(len(tokens) - 1) // block_size`` blocks are ever shared, so
        at least one token is always left to recompute — the recompute
        yields the request's next sampled token, and it keeps every
        write on private blocks (the invariant the whole CoW design
        rests on; a block-aligned prompt fully served from cache would
        otherwise append into a shared block).  ``max_blocks`` can only
        tighten it.  Returns the shared physical blocks in prefix
        order; the caller owns a refcount on each (released through
        the ordinary ``free``)."""
        shared: List[int] = []
        cap = (len(tokens) - 1) // self.block_size
        if max_blocks is not None:
            cap = min(cap, max_blocks)
        h = 0
        for i in range(cap):
            h = self._block_hash(h, tokens, i)
            block = self._entries.get(h)
            if block is None:
                break
            self.allocator.share(block, owner)
            self._entries.move_to_end(h)
            shared.append(block)
        self.hits += len(shared)
        return shared

    def insert(self, tokens: Sequence[int], blocks: Sequence[int],
               upto_tokens: int, *, start_block: int = 0,
               prev_hash: int = 0) -> int:
        """Index the full blocks of ``tokens[:upto_tokens]`` (the part
        whose K/V is already *written* to the arena — indexing a block
        whose content has not landed would let a same-tick hit read
        garbage).  Already-indexed keys are skipped: the first physical
        copy of a prefix wins and duplicates free normally with their
        writer.

        ``start_block``/``prev_hash`` resume the chain where a previous
        call stopped (the scheduler threads them through the request,
        so a prompt advanced chunk by chunk hashes each block ONCE per
        admission instead of re-hashing the whole prefix per chunk).
        Returns the chain hash after the last indexed block — the next
        call's ``prev_hash``."""
        n_full = min(upto_tokens // self.block_size, len(blocks))
        h = prev_hash
        for i in range(start_block, n_full):
            h = self._block_hash(h, tokens, i)
            if h in self._entries:
                continue
            self.allocator.share(blocks[i], CACHE_OWNER)
            self._entries[h] = blocks[i]
        return h

    def evictable(self) -> int:
        """Blocks an eviction sweep could return to the pool right now
        (cache is the sole holder)."""
        return sum(1 for b in self._entries.values()
                   if self.allocator.refcount(b) == 1)

    def evict_many(self, n: int) -> int:
        """Free up to ``n`` LRU sole-holder entries in ONE sweep;
        returns how many blocks went back to the pool.  Entries still
        shared with a live request are skipped (evicting them would
        free no capacity and lose a hot prefix) — and skipped once,
        not once per needed block: the scheduler asks for its whole
        deficit at a time, so pool pressure costs one pass over the
        pinned prefix, not ``n``."""
        freed = 0
        for key in list(self._entries):
            if freed >= n:
                break
            block = self._entries[key]
            if self.allocator.refcount(block) == 1:
                del self._entries[key]
                self.allocator.free([block], owner=CACHE_OWNER)
                self.evictions += 1
                freed += 1
        return freed

    def evict_one(self) -> Optional[int]:
        """Free the LRU sole-holder entry; returns its block id, or
        ``None`` when nothing is evictable (every cached block is
        shared with a live request, or the cache is empty)."""
        for key, block in self._entries.items():
            if self.allocator.refcount(block) == 1:
                del self._entries[key]
                self.allocator.free([block], owner=CACHE_OWNER)
                self.evictions += 1
                return block
        return None

    def check(self) -> None:
        """Every indexed block must be live and held by the cache."""
        for key, block in self._entries.items():
            if self.allocator.refcount(block) < 1:
                raise AssertionError(
                    f"cache entry {key} indexes free block {block}")
