"""apex_tpu.serving — continuous-batching decode runtime (ISSUE 9).

The inference-side twin of the training stack: the repo trains GPT at
every parallelism and restores checkpoints onto arbitrary meshes; this
package turns those checkpoints into a *serving* runtime —

- :mod:`.kv_cache` — paged/block KV cache: a pooled
  ``[n_blocks, block, heads, head_dim]`` device arena per layer with a
  host-side :class:`~apex_tpu.serving.kv_cache.BlockAllocator` handing
  fixed-size blocks to requests (the vLLM paging model), sharded over
  the existing ``tp`` axis alongside the tensor-parallel heads.
- :mod:`.paged_attention` — the fused Pallas decode kernel:
  gather-from-block-table (scalar-prefetch index maps, so skipped and
  out-of-range blocks never move HBM bytes) + online-softmax attention
  over the cache in ONE kernel, next to the unfused XLA lowering it is
  A/B'd against (bench ``serving.vs_unfused``).
- :mod:`.fused_ops` — the fused dequant/residual/norm epilogue on the
  decode hot path (one VMEM-resident kernel instead of three
  elementwise+reduction HLOs — the operation-fusion paper's decode
  finding, PAPERS.md arxiv 2502.17728).
- :mod:`.model` — prefill/decode split over the *training* layers:
  chunked prefill through the paged multi-query kernel, decode a
  fixed-shape ``[max_batch, spec_width]`` step reusing
  ``ColumnParallelLinear``/``RowParallelLinear`` and RoPE — the
  speculative k+1 verify when drafting is on (ISSUE 13), the classic
  one-token tick when it is not.
- :mod:`.speculative` — self-speculative n-gram / prompt-lookup
  drafting (no second model): host-side proposals verified in-graph
  with per-slot adaptive back-off; rejection rollback is O(1) pointer
  and length moves on the paged cache (never a KV copy).
- :mod:`.scheduler` / :mod:`.engine` — continuous (in-flight)
  batching: requests join and leave mid-flight with ZERO decode-step
  recompiles (all churn is data, never shape), latency
  percentiles/tokens-per-sec through the PR 5 metrics registry, and
  draining on preemption via ``resilience.PreemptionGuard``.
- :mod:`.loader` — restore-from-training-checkpoint through the PR 6
  ``ShardingSpec`` reshard layer (train on mesh N, serve on mesh M).
- :mod:`.lora` — batched multi-LoRA serving (ISSUE 17): per-tenant
  low-rank adapters in a refcounted paged *adapter arena* (the
  BlockAllocator/LRU machinery applied to weights), gathered per batch
  slot inside the one compiled decode/prefill step via the same
  scalar-prefetch index-map trick the paged kernels use — N adapters
  in one batch, zero recompiles, ``adapter_id=None`` bitwise the bare
  engine.
- :mod:`.replica` / :mod:`.fleet` — the fleet layer (ISSUE 11): N
  engine replicas as separate spawned processes (own mesh, own arenas,
  data-service process lifecycle) behind a host-side
  :class:`~apex_tpu.serving.fleet.FleetRouter` with SLO-aware admission
  (priority classes, weighted tenant fairness, typed shed-on-overload),
  failover replay (SIGKILLed replica's in-flight requests re-prefix on
  survivors, greedy-token-identical), and zero-downtime weight rollout
  through the SIGTERM drain + newest-VERIFIED restore.
- :mod:`.transport` — the router↔replica wire made explicit (ISSUE
  14): the Transport duck type the router consumes, with the
  in-process mp-queue shape (``ReplicaProcess``) and a cross-host
  framed-TCP shape — length-prefixed version+crc32 frames (torn or
  corrupted frames are detected and classified as replica failure,
  never deserialized), a :class:`~apex_tpu.serving.transport.
  SocketTransport` client with jittered-backoff reconnect + lossless
  session replay + bounded-outbox backpressure + link-RTT pings, and
  a :func:`~apex_tpu.serving.transport.replica_serve` host daemon
  wrapping the existing replica worker lifecycle.
- :mod:`.autopilot` — the SLO autopilot (ISSUE 18): a jax-free control
  loop beside ``FleetRouter.pump()`` that scales (spawn/drain through
  the ready-handshake and SIGTERM-drain paths, flap quarantine under
  capped back-off), retunes (trace attribution → live engine/router
  knobs via acked broadcast), and canaries every knob change on one
  replica with a paired median-of-ratios A/B judge + automatic
  rollback — every decision a typed timeline event on an injectable
  clock.

See ``docs/serving.md`` for the architecture and cookbook.
"""

from apex_tpu.serving.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    OutOfBlocksError,
    PrefixCache,
    init_kv_arena,
)
from apex_tpu.serving.paged_attention import (
    paged_attention_decode,
    paged_attention_decode_unfused,
    paged_prefill_attention,
    paged_prefill_attention_unfused,
)
from apex_tpu.serving.lora import (
    AdapterArena,
    LoRAConfig,
    OutOfAdapterSlotsError,
    init_adapter_weights,
    restore_adapter_for_serving,
)
from apex_tpu.serving.sampling import SamplingParams
from apex_tpu.serving.scheduler import Request, RequestState, Scheduler
from apex_tpu.serving.speculative import (
    NGramProposer,
    SpeculativeConfig,
    ngram_propose,
)
from apex_tpu.serving.engine import ServingConfig, ServingEngine
from apex_tpu.serving.loader import restore_gpt_for_serving
from apex_tpu.serving.replica import ReplicaProcess, ReplicaSpec
from apex_tpu.serving.fleet import FleetRequest, FleetRouter
from apex_tpu.serving.autopilot import (
    AutopilotConfig,
    FleetAutopilot,
    trace_attribution,
)
from apex_tpu.serving.transport import (
    SocketTransport,
    TransportError,
    TransportServer,
    replica_serve,
    start_replica_server,
)

__all__ = [
    "AdapterArena",
    "AutopilotConfig",
    "BlockAllocator",
    "FleetAutopilot",
    "FleetRequest",
    "FleetRouter",
    "KVCacheConfig",
    "LoRAConfig",
    "NGramProposer",
    "OutOfAdapterSlotsError",
    "OutOfBlocksError",
    "PrefixCache",
    "ReplicaProcess",
    "ReplicaSpec",
    "Request",
    "RequestState",
    "SamplingParams",
    "Scheduler",
    "ServingConfig",
    "ServingEngine",
    "SocketTransport",
    "SpeculativeConfig",
    "TransportError",
    "TransportServer",
    "init_adapter_weights",
    "init_kv_arena",
    "replica_serve",
    "restore_adapter_for_serving",
    "start_replica_server",
    "ngram_propose",
    "paged_attention_decode",
    "paged_attention_decode_unfused",
    "paged_prefill_attention",
    "paged_prefill_attention_unfused",
    "restore_gpt_for_serving",
    "trace_attribution",
]
