"""Fused dequant/residual/norm epilogue for the decode hot path.

Between the attention (or MLP) row-parallel projection and the next
sublayer sit four ops: the skip-bias add, the residual add, an upcast
of the projection output from the wire/compute dtype, and a LayerNorm.
In the unfused XLA lowering each is its own elementwise/reduction HLO
over an HBM round trip — at decode shapes (``[max_batch, hidden]``,
one token per slot) that chain is pure memory latency, the exact
profile the operation-fusion paper (PAPERS.md arxiv 2502.17728) finds
dominating the decode step.

:func:`fused_residual_norm` does all four in ONE Pallas kernel: the row
is read once into VMEM, dequantized (upcast to fp32), bias- and
residual-added, normalized against the fp32 statistics, and both
outputs (the normed row for the next GEMM and the new residual for the
next skip connection) written back — two reads, two writes, zero
intermediates in HBM.  Forward-only by design: this is the serving hot
path, nothing differentiates it (the training twin is
:mod:`apex_tpu.ops.pallas_norm`, which carries the custom VJP).

The unfused twin :func:`residual_norm_unfused` is the A/B baseline and
the parity reference (``tests/test_serving.py``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["fused_residual_norm", "residual_norm_unfused"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _kernel(x_ref, res_ref, b_ref, w_ref, beta_ref, y_ref, new_res_ref, *,
            eps: float, has_bias: bool):
    # dequant: wire dtype (bf16 projection output) -> fp32, in VMEM
    x = x_ref[...].astype(jnp.float32)
    if has_bias:
        x = x + b_ref[...].astype(jnp.float32)
    r = x + res_ref[...].astype(jnp.float32)
    mean = jnp.mean(r, axis=-1, keepdims=True)
    rc = r - mean
    var = jnp.mean(rc * rc, axis=-1, keepdims=True)
    y = rc * jax.lax.rsqrt(var + eps)
    y = y * w_ref[...].astype(jnp.float32) + beta_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    new_res_ref[...] = r.astype(new_res_ref.dtype)


def fused_residual_norm(x, residual, weight, bias_ln, *, bias=None,
                        eps: float = 1e-5, block_rows: int = 256
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``normed, new_residual = LN(x [+ bias] + residual), x [+ bias] + residual``.

    ``x``/``residual``: ``[..., hidden]`` (leading dims flattened to
    rows); ``weight``/``bias_ln``: the LayerNorm affine params
    (``scale``/``bias`` of :class:`~apex_tpu.normalization.FusedLayerNorm`);
    ``bias``: optional skip-bias of the preceding row-parallel linear
    (``skip_bias_add`` convention).  Outputs keep ``x``'s dtype for
    ``normed`` and ``residual``'s dtype for the carried residual.
    """
    orig_shape = x.shape
    hidden = orig_shape[-1]
    rows = 1
    for s in orig_shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, hidden)
    res2 = residual.reshape(rows, hidden)
    has_bias = bias is not None
    b = (jnp.zeros((hidden,), x.dtype) if bias is None
         else bias.reshape(hidden))
    block_rows = min(block_rows, rows)
    grid = (pl.cdiv(rows, block_rows),)
    row_spec = pl.BlockSpec((block_rows, hidden), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((hidden,), lambda i: (0,))
    y, new_res = pl.pallas_call(
        functools.partial(_kernel, eps=eps, has_bias=has_bias),
        grid=grid,
        in_specs=[row_spec, row_spec, vec_spec, vec_spec, vec_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, hidden), x.dtype),
            jax.ShapeDtypeStruct((rows, hidden), residual.dtype),
        ],
        interpret=_interpret(),
    )(x2, res2, b, weight, bias_ln)
    return y.reshape(orig_shape), new_res.reshape(orig_shape)


def residual_norm_unfused(x, residual, weight, bias_ln, *, bias=None,
                          eps: float = 1e-5):
    """The separate-ops lowering (A/B baseline, parity reference)."""
    r = x if bias is None else x + bias
    r = (r + residual).astype(jnp.float32)
    mean = jnp.mean(r, axis=-1, keepdims=True)
    rc = r - mean
    var = jnp.mean(rc * rc, axis=-1, keepdims=True)
    y = rc * jax.lax.rsqrt(var + eps)
    y = y * weight.astype(jnp.float32) + bias_ln.astype(jnp.float32)
    return y.astype(x.dtype), r.astype(residual.dtype)
