"""Restore training checkpoints onto the serving mesh.

The PR 6 elastic-restore layer already proves a GPT train state moves
bit-losslessly between meshes; serving is "mesh M" with two twists:

- the serving mesh has ``pp=1`` (the decode step runs every layer on
  every rank — a 1-token pipeline would be all bubble), so the
  training-side ``[vpp, pp, ...]`` layer stack re-factors to
  ``[L, 1, ...]``.  Both layouts are row-major views of the same
  virtual-stage-major logical ``[L]`` stack (``gpt3d_logical_folds``),
  so the re-factor is a pure reshape — bit-lossless by construction.
- serving needs only the **params subtree** of the saved train state.
  ``restore_resharded`` templates the whole tree (leaf-count checked),
  so this loader goes through :func:`~apex_tpu.resilience.reshard.
  load_logical` instead — the mesh-independent ``{path: leaf}`` view
  (folds merged, ZeRO buckets expanded) — and places just the
  ``params/...`` leaves onto the serving template.  An optimizer-state
  layout change can therefore never break a rollout.

Verification and corrupt-fallback mirror ``restore_latest``: every
candidate is checksum-verified before reading, and a corrupt newest
checkpoint falls back to the previous committed one.

Cookbook (docs/serving.md has the long form)::

    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=SERVE_TP)       # serving mesh
    params, specs = restore_gpt_for_serving(ckpt_dir, config, mesh=mesh)
    engine = ServingEngine(config, ServingConfig(...), params, mesh=mesh)
"""

from __future__ import annotations

import logging
from typing import Tuple

__all__ = ["restore_gpt_for_serving", "serving_like"]

logger = logging.getLogger(__name__)


def serving_like(config, mesh, *, tp_axis: str = "tp", seed: int = 0):
    """A serving-mesh ``(params, specs)`` template for ``config``.

    Built by ``build_gpt_3d``'s own init on the serving mesh (pp=1, so
    ``num_chunks = num_layers`` and the stack lands as ``[L, 1, ...]``)
    — the one source of truth for shapes, shardings and pytree
    structure, so the restore template can never drift from what the
    engine consumes.
    """
    import jax

    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    if mesh.shape["pp"] != 1:
        raise ValueError(
            f"serving mesh must have pp=1 (got pp={mesh.shape['pp']}); "
            "a 1-token decode step has no pipeline to fill")
    init_fn, _, _ = build_gpt_3d(
        config, num_chunks=config.num_layers, num_microbatches=1,
        mesh=mesh, tp_axis=tp_axis)
    sample = jax.numpy.zeros((2, 2), jax.numpy.int32)
    return init_fn(jax.random.PRNGKey(seed), sample)


def _place_subtree(logical: dict, like, prefix: str):
    """Map logical ``{path: np.ndarray}`` leaves under ``prefix/`` onto
    the template tree (reshape-only placement with the template's
    shardings)."""
    import jax
    import numpy as np

    from apex_tpu.checkpoint import CheckpointCorruptError, _path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    out = []
    for p, tleaf in flat:
        path = f"{prefix}/{_path_str(p)}"
        if path not in logical:
            raise CheckpointCorruptError(
                f"checkpoint has no leaf {path!r} (saved tree does not "
                f"carry the served params under {prefix!r}?)")
        host = logical[path]
        tgt_shape = tuple(np.shape(tleaf))
        if int(np.prod(host.shape)) != int(np.prod(tgt_shape)):
            raise CheckpointCorruptError(
                f"{path}: logical shape {list(host.shape)} cannot "
                f"reshape to serving shape {list(tgt_shape)}")
        host = np.ascontiguousarray(host).reshape(tgt_shape).astype(
            tleaf.dtype, copy=False)
        if isinstance(tleaf, jax.Array):
            out.append(jax.make_array_from_callback(
                tgt_shape, tleaf.sharding, lambda idx, h=host: h[idx]))
        else:
            out.append(host)
    return jax.tree_util.tree_unflatten(treedef, out)


def restore_gpt_for_serving(ckpt_dir: str, config, *, mesh=None,
                            tp_axis: str = "tp", key: str = "params",
                            sharded: bool = True, verify: bool = True,
                            with_step: bool = False
                            ) -> Tuple[object, ...]:
    """Restore the newest intact GPT checkpoint onto the serving mesh.

    ``ckpt_dir`` is a :class:`~apex_tpu.resilience.CheckpointManager`
    directory whose checkpoints hold the train state as a mapping with
    the :class:`GPT3DParams` under ``key`` (the 3D trainer convention);
    every other entry (optimizer state, sentinel) is ignored.  Returns
    ``(params, specs)`` with the layer stack in the canonical
    ``[L, 1, ...]`` serving form, resharded from whatever
    ``(vpp, pp, tp, dp)`` layout the checkpoint was trained on.

    ``with_step=True`` returns ``(params, specs, step)`` — a fleet
    replica reports the step it actually serves in its handshake, so a
    rollout that fell back past a corrupt newest checkpoint is visible
    to the router and the operator, not silent (ISSUE 11).
    """
    from apex_tpu import checkpoint as ckpt
    from apex_tpu.observability.spans import span
    from apex_tpu.resilience import CheckpointManager, reshard

    like_params, specs = serving_like(config, mesh_or_registered(mesh),
                                      tp_axis=tp_axis)
    mgr = CheckpointManager(ckpt_dir, sharded=sharded)
    failures = []
    with span("serving/restore"):
        for step in reversed(mgr.all_steps()):
            try:
                if verify:
                    mgr.verify(step)
                logical, _ = reshard.load_logical(mgr.step_path(step))
                params = _place_subtree(logical, like_params, key)
                if failures:
                    logger.warning(
                        "serving restore fell back to step %d past %s",
                        step, "; ".join(failures))
                if with_step:
                    return params, specs, step
                return params, specs
            except (ckpt.CheckpointCorruptError, ValueError, OSError,
                    KeyError) as e:
                failures.append(f"step {step}: {e!r}")
                logger.warning(
                    "checkpoint step %d unusable for serving (%r); "
                    "falling back", step, e)
    raise FileNotFoundError(
        f"no checkpoint under {ckpt_dir!r} restorable for serving"
        + (f" (tried: {'; '.join(failures)})" if failures else ""))


def mesh_or_registered(mesh):
    if mesh is not None:
        return mesh
    from apex_tpu.parallel.mesh import get_mesh

    return get_mesh()
