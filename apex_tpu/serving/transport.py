"""Router↔replica wire — the Transport duck type and its two shapes.

The :class:`~apex_tpu.serving.fleet.FleetRouter` is deliberately
transport-agnostic: it drives anything with the replica client surface

    ``alive() -> bool``
    ``poll() -> list[event]``              (non-blocking; may raise)
    ``submit(frid, prompt, max_new_tokens, eos_id, sampling)``
    ``submit_many(items)``                 (optional batch fast path)
    ``begin_drain()``
    ``close()``

plus the startup convenience ``wait_ready() -> meta``.  Events and
commands are exactly the :mod:`~apex_tpu.serving.replica` wire protocol
(``("token", frid, tok)``, ``("state", snapshot)``, …).  Two
implementations exist:

- **in-process mp-queue** — :class:`~apex_tpu.serving.replica.
  ReplicaProcess` (PR 10): replica is a spawned child on THIS host,
  multiprocessing queues are the pipe.  Re-exported here as the
  reference transport.
- **framed TCP** (this module, ISSUE 14) — :class:`SocketTransport`
  talking to a :func:`replica_serve` daemon on ANY host.  The router
  does not change; every router contract (failover replay, typed shed,
  zero-downtime rollout) holds over the socket, proven under injected
  network faults by ``tests/test_transport.py`` and the
  ``scripts/fleet_smoke.sh`` socket leg.

Framing
-------
Every payload crosses as one frame::

    version(1B) | body_len(4B big-endian) | crc32(body)(4B) | body

``body`` is a pickled tuple.  A frame whose version byte is wrong,
whose length is implausible, whose crc does not match, or that ends at
EOF mid-frame is **never deserialized**: the decoder raises
:class:`FrameError`, the client counts it (``frames_corrupt``) and
classifies the replica as failed, and the router recovers through the
existing down-verdict → failover-replay path.  Torn and corrupted
frames are a *detected* failure class, not garbage handed to pickle.

Session layer
-------------
TCP delivers bytes, not guarantees, so a thin session protocol rides
the frames:

- ``("hello", last_evt_seq, cmd_seq, fresh)`` /
  ``("hello", cmd_applied, reset, resume_seq)`` — the (re)connect
  handshake.  The server keeps a bounded ring of sequence-numbered
  events; a reconnecting client names the last event seq it saw and
  the server replays the gap, so a **connection** loss at a frame
  boundary costs nothing (no failover, no token lost —
  ``fleet/reconnects`` counts it).  When the gap has fallen off the
  ring the server answers ``reset`` and the client fails the replica —
  correctness degrades to the ordinary replay path, never to a stream
  with a hole.  A ``fresh`` hello (a client that has never held a
  session — e.g. a *restarted router* attaching to a long-lived
  daemon) is different: the server resets its command-dedupe watermark
  to zero (the old session's watermark must not black-hole the new
  session's submits — a fresh client's outbox is entirely unacked and
  resends from seq 1; its ``cmd_seq`` hello field is informational),
  and when the ring cannot reach back to
  seq 0 it fast-forwards the client (``resume_seq``) and re-emits the
  sticky ``ready``/latest ``state`` events, so a fresh router always
  gets the handshake metadata and current state instead of a reset.
- ``("cmd", seq, command)`` / ``("ack", applied)`` — commands are
  sequence-numbered and buffered until acknowledged; a reconnect
  re-sends the unacked tail and the server dedupes by seq, so a torn
  connection can neither lose nor double-apply a submit.
- ``("ping", nonce)`` / ``("pong", nonce, server_mono)`` — link RTT,
  measured on the client's monotonic clock (cross-host wall clocks are
  never compared).  The router reads ``link_rtt_s`` off the client and
  *demotes* a degraded link in placement rather than hard-failing the
  replica.  ``server_mono`` (ISSUE 15) is the replica host's monotonic
  clock at pong time: together with the client-side send/receive stamps
  it yields a per-link **clock offset** estimate
  (``client ≈ server + offset``, uncertainty ±RTT/2 — the NTP
  construction), refreshed per ping and drained by the router into its
  timeline spill (``link_clock`` events) so cross-host trace stitching
  maps every replica's clock onto the router's.  The hello reply
  carries the same stamp, so a link has an offset sample from its very
  first exchange.
- ``("bye",)`` — intentional server exit (drain complete / stop): the
  client stops reconnecting and reports ``alive() == False``, which is
  how a rollout's drained-and-exited check works cross-host.

The client is single-threaded and non-blocking: all I/O happens inside
``poll()`` (the router's pump), reconnects use jittered exponential
backoff, deadlines run on an injectable monotonic clock, and the
command outbox is bounded — past ``max_outbox`` pending commands,
``submit`` raises (backpressure), which the router treats as a dead
pipe.  Nothing here imports jax.

Security note: frames are pickled python — run this transport inside
one trust domain (the fleet's private network), exactly like the
mp-queue transport it mirrors.
"""

from __future__ import annotations

import collections
import logging
import os
import pickle
import queue as queue_mod
import random
import select
import socket
import struct
import threading
import time
import zlib
from typing import Optional, Sequence, Tuple

__all__ = [
    "FRAME_HEADER",
    "FRAME_VERSION",
    "FrameDecoder",
    "FrameError",
    "SocketTransport",
    "TransportError",
    "TransportServer",
    "encode_frame",
    "replica_serve",
    "start_replica_server",
]

logger = logging.getLogger(__name__)

FRAME_VERSION = 1
# version, body_len, crc32(body) — public so frame-aware tooling (the
# ChaosProxy fault injector) parses boundaries from the one definition
FRAME_HEADER = struct.Struct(">BII")
_HEADER = FRAME_HEADER
MAX_FRAME_BYTES = 64 << 20               # sanity bound on body_len: a
#                                          corrupted length field must
#                                          fail fast, not allocate 4 GB
#                                          or park the reader forever


class FrameError(ValueError):
    """A frame that must not be deserialized: bad version, implausible
    length, crc mismatch, or EOF mid-frame (torn)."""


class TransportError(RuntimeError):
    """Client-side transport failure — the router's dead-pipe class
    (``poll``/``submit`` raise it; ``_mark_down`` + replay recover)."""


def encode_frame(obj) -> bytes:
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _HEADER.pack(FRAME_VERSION, len(body),
                        zlib.crc32(body) & 0xFFFFFFFF) + body


class FrameDecoder:
    """Incremental frame parser over a byte stream.

    ``feed(data)`` returns the complete, crc-verified payloads and
    keeps any trailing partial frame buffered; ``partial`` says whether
    an EOF *now* would tear a frame mid-flight (the caller's
    torn-detection signal)."""

    def __init__(self, *, max_frame_bytes: int = MAX_FRAME_BYTES):
        self._buf = bytearray()
        self._max = max_frame_bytes

    @property
    def partial(self) -> bool:
        return len(self._buf) > 0

    def reset(self) -> None:
        self._buf.clear()

    def feed(self, data: bytes) -> list:
        self._buf.extend(data)
        out = []
        while len(self._buf) >= _HEADER.size:
            version, length, crc = _HEADER.unpack_from(self._buf)
            if version != FRAME_VERSION:
                raise FrameError(
                    f"frame version {version} != {FRAME_VERSION}")
            if length > self._max:
                raise FrameError(
                    f"frame length {length} exceeds bound {self._max}")
            if len(self._buf) < _HEADER.size + length:
                break
            body = bytes(self._buf[_HEADER.size:_HEADER.size + length])
            del self._buf[:_HEADER.size + length]
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                raise FrameError("frame crc32 mismatch")
            out.append(pickle.loads(body))
        return out


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class SocketTransport:
    """Framed-TCP replica client — the cross-host half of the
    :class:`~apex_tpu.serving.fleet.FleetRouter` transport duck type.

    ``address``: ``(host, port)`` of a :func:`replica_serve` daemon (or
    a :class:`~apex_tpu.testing.faults.ChaosProxy` in front of one).
    All I/O is non-blocking and happens inside :meth:`poll`; connect
    attempts use jittered exponential backoff (``backoff_initial_s`` →
    ``backoff_max_s``); a connection that completes TCP but never
    answers the hello within ``send_timeout_s`` (the half-open shape)
    is dropped and retried; a send buffer stuck for ``send_timeout_s``
    while connected raises.  ``max_outbox`` bounds the unacked command
    queue — past it, ``submit`` raises (backpressure), which the router
    treats as a dead pipe and replays elsewhere.

    Counters the router mirrors into the registry: ``reconnects``
    (re-established sessions that lost no events), ``frames_corrupt``
    (torn/crc-failed frames, each a replica-failure verdict);
    ``link_rtt_s`` is the latest ping round trip on THIS process's
    monotonic clock (never compared to the replica's clocks).
    """

    def __init__(self, name: str, address: Tuple[str, int], *,
                 connect_timeout_s: float = 1.0,
                 send_timeout_s: float = 5.0,
                 max_outbox: int = 1024,
                 backoff_initial_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 backoff_jitter: float = 0.5,
                 ping_every_s: float = 0.25,
                 max_frame_bytes: int = MAX_FRAME_BYTES,
                 clock=time.monotonic,
                 rng: Optional[random.Random] = None):
        self.name = name
        self.address = (address[0], int(address[1]))
        self.meta: Optional[dict] = None
        self.connect_timeout_s = connect_timeout_s
        self.send_timeout_s = send_timeout_s
        self.max_outbox = max_outbox
        self.backoff_initial_s = backoff_initial_s
        self.backoff_max_s = backoff_max_s
        self.backoff_jitter = backoff_jitter
        self.ping_every_s = ping_every_s
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        self._max_frame = max_frame_bytes

        self.reconnects = 0
        self.frames_corrupt = 0
        self.link_rtt_s: Optional[float] = None
        # latest per-link clock offset (client_mono ≈ server_mono +
        # offset), ±RTT/2; None until the first stamped pong/hello
        self.clock_offset_s: Optional[float] = None
        # undrained (rtt_s, offset_s, server_mono) samples for the
        # router (take_rtt_samples) — bounded so a standalone client
        # that nobody drains cannot grow
        self._rtt_samples: collections.deque = collections.deque(
            maxlen=512)

        self._sock: Optional[socket.socket] = None
        self._pending_sock: Optional[socket.socket] = None
        self._connect_started = 0.0
        self._decoder = FrameDecoder(max_frame_bytes=max_frame_bytes)
        self._hello_done = False
        self._hello_sent_t = 0.0
        self._ever_connected = False
        self._attempts = 0
        self._next_connect_t = -float("inf")
        self._wire = bytearray()          # bytes staged for the kernel
        self._wire_since: Optional[float] = None
        self._last_evt_seq = 0
        self._cmd_seq = 0
        # unacked commands: (seq, frame_bytes); resent after reconnect,
        # dropped on ("ack", applied) — bounded by max_outbox
        self._outbox: collections.deque = collections.deque()
        self._pending: list = []          # events buffered by wait_ready
        self._pings: dict = {}            # nonce -> send time
        self._ping_nonce = 0
        self._last_ping_t = -float("inf")
        self._failed: Optional[str] = None
        self._exited = False              # server said bye (clean exit)
        self._closed = False

    # ------------------------------------------------------------ liveness

    def alive(self) -> bool:
        return self._failed is None and not self._exited

    # ------------------------------------------------------------ commands

    def _send_cmd(self, cmd: tuple) -> None:
        if self._failed is not None:
            raise TransportError(
                f"replica {self.name}: transport failed ({self._failed})")
        if self._exited:
            raise TransportError(f"replica {self.name}: exited")
        if len(self._outbox) >= self.max_outbox:
            # bounded send queue: refusing here surfaces as a dead pipe
            # at the router, which replays elsewhere — strictly better
            # than buffering without bound into a partition
            raise TransportError(
                f"replica {self.name}: send backpressure "
                f"({len(self._outbox)} commands pending ack)")
        self._cmd_seq += 1
        frame = encode_frame(("cmd", self._cmd_seq, cmd))
        self._outbox.append((self._cmd_seq, frame))
        if self._hello_done:
            self._stage(frame)

    def submit(self, frid, prompt: Sequence[int], max_new_tokens: int,
               eos_id=None, sampling=None, trace=None) -> None:
        self._send_cmd(("submit", frid, [int(t) for t in prompt],
                        int(max_new_tokens), eos_id, sampling, trace))

    def submit_many(self, items: Sequence[tuple]) -> None:
        from apex_tpu.serving.replica import wire_submit_item

        self._send_cmd(("submit_many",
                        [wire_submit_item(it) for it in items]))

    def begin_drain(self, **kw) -> None:
        """Cross-host drain: the wire command (the daemon's worker runs
        the same PreemptionGuard drain a local SIGTERM would start)."""
        self._send_cmd(("drain",))

    # ------------------------------------------------- KV migration cmds
    # (ISSUE 16) Each call is ONE frame on the wire — a kv_block
    # payload rides its own frame, so the outbox/ack machinery already
    # gives the migration per-block resumability: a reconnect resends
    # exactly the unacked tail, never restarts the stream.

    def export_kv(self, frid) -> None:
        self._send_cmd(("export_kv", frid))

    def kv_ack(self, frid, ok: bool) -> None:
        self._send_cmd(("kv_ack", frid, bool(ok)))

    def import_kv(self, frid, meta: dict) -> None:
        self._send_cmd(("import_kv", frid, meta))

    def kv_block(self, frid, idx: int, payload) -> None:
        self._send_cmd(("kv_block", frid, int(idx), payload))

    def import_commit(self, frid, item, n_blocks: int) -> None:
        from apex_tpu.serving.replica import wire_submit_item

        self._send_cmd(("import_commit", frid, wire_submit_item(item),
                        int(n_blocks)))

    def kv_abort(self, frid) -> None:
        self._send_cmd(("kv_abort", frid))

    # ------------------------------------------------- adapter cmds
    # (ISSUE 17) One frame each; the ``adapter_loaded`` /
    # ``adapter_unloaded`` ack events ride the ordinary event stream.
    # Adapter weights cross as plain pickled arrays inside the frame —
    # rank-8 pairs for the test configs are a few KB, far under
    # MAX_FRAME_BYTES.

    def load_adapter(self, adapter_id, payload: Optional[dict] = None
                     ) -> None:
        self._send_cmd(("load_adapter", adapter_id,
                        dict(payload or {})))

    def unload_adapter(self, adapter_id) -> None:
        self._send_cmd(("unload_adapter", adapter_id))

    def set_knobs(self, payload: dict) -> None:
        """(ISSUE 18) Live-retune broadcast: one frame carrying the knob
        payload (plus the router's ack token); the ``knobs_set`` verdict
        rides the ordinary event stream like the adapter acks."""
        self._send_cmd(("set_knobs", dict(payload or {})))

    # -------------------------------------------------------------- events

    def poll(self) -> list:
        """One non-blocking I/O turn: connect/backoff, flush, read,
        ping.  Returns newly surfaced replica events; raises
        :class:`TransportError` on the failure classes the router must
        treat as a dead replica (corrupt/torn frame, event-ring reset,
        send timeout, backpressure already raised at submit)."""
        if self._failed is not None:
            raise TransportError(
                f"replica {self.name}: transport failed ({self._failed})")
        out, self._pending = self._pending, []
        if self._exited:
            return out
        now = self._clock()
        if self._sock is None:
            if self._pending_sock is not None:
                self._check_connecting(now)
            elif now >= self._next_connect_t:
                self._try_connect(now)
            return out
        if not self._hello_done and \
                now - self._hello_sent_t > self.send_timeout_s:
            # accept-then-silence (half-open): TCP completed but the
            # session never did — drop and retry with backoff; the
            # router's heartbeat ladder owns the eventual down verdict
            self._disconnect(now, "hello timeout (half-open link)")
            return out
        self._flush(now)
        self._read(now, out)
        if self._sock is not None and self._hello_done:
            self._maybe_ping(now)
            if self._wire and self._wire_since is not None and \
                    now - self._wire_since > self.send_timeout_s:
                self._fail(f"send timeout: {len(self._wire)} bytes "
                           f"stuck for {self.send_timeout_s:.1f}s")
        return out

    def wait_ready(self, timeout: float = 300.0) -> dict:
        """Block (pumping :meth:`poll`) until the replica's ready
        handshake arrives over the wire; other events are buffered for
        later ``poll`` calls in order."""
        if self.meta is not None:
            return self.meta
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            events = self.poll()
            keep = []
            for ev in events:
                if ev[0] == "ready" and self.meta is None:
                    self.meta = ev[1]
                keep.append(ev)
            # re-buffer everything (ready included) so the router's
            # view sees the same stream ReplicaProcess would deliver
            self._pending = keep + self._pending
            if self.meta is not None:
                return self.meta
            time.sleep(0.002)
        raise RuntimeError(
            f"replica {self.name}: no ready handshake over "
            f"{self.address} in {timeout:.0f}s")

    # ----------------------------------------------------------- internals

    def _try_connect(self, now: float) -> None:
        """Start a NON-blocking connect: the router's pump must never
        stall on a black-holed SYN (the real-partition shape, where no
        RST ever comes back) — progress is checked in later polls."""
        import errno

        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        err = sock.connect_ex(self.address)
        if err == 0:
            self._finish_connect(sock, now)
            return
        if err in (errno.EINPROGRESS, errno.EWOULDBLOCK, errno.EALREADY):
            self._pending_sock = sock
            self._connect_started = now
            return
        try:
            sock.close()
        except OSError:
            pass
        self._attempts += 1
        self._schedule_reconnect(now)
        logger.debug("transport %s: connect %s failed (errno %d), "
                     "retry in %.3fs", self.name, self.address, err,
                     self._next_connect_t - now)

    def _check_connecting(self, now: float) -> None:
        sock = self._pending_sock
        try:
            _, writable, errored = select.select([], [sock], [sock], 0)
        except (OSError, ValueError):
            writable, errored = [], [sock]
        if writable or errored:
            self._pending_sock = None
            err = 1
            try:
                err = sock.getsockopt(socket.SOL_SOCKET,
                                      socket.SO_ERROR)
            except OSError:
                pass
            if err == 0 and not errored:
                self._finish_connect(sock, now)
                return
            try:
                sock.close()
            except OSError:
                pass
            self._attempts += 1
            self._schedule_reconnect(now)
            return
        if now - self._connect_started > self.connect_timeout_s:
            self._pending_sock = None
            try:
                sock.close()
            except OSError:
                pass
            self._attempts += 1
            self._schedule_reconnect(now)

    def _finish_connect(self, sock: socket.socket, now: float) -> None:
        self._sock = sock
        self._decoder.reset()
        # fresh = this client has never held a session: the server
        # resets its command-dedupe watermark and fast-forwards our
        # event cursor instead of deduping/resetting us against a
        # PREVIOUS router's session (the restarted-router reattach
        # path).  The trailing 1 advertises the ISSUE 15 clock
        # exchange: the server stamps its hello reply (and pongs) with
        # its monotonic clock ONLY for clients that ask — a pre-15
        # router strict-unpacks the 4-tuple reply, so an unconditional
        # stamp would break the replicas-first rolling-upgrade order
        # (a pre-15 server just indexes our extra element away).
        self._wire = bytearray(encode_frame(
            ("hello", self._last_evt_seq, self._cmd_seq,
             not self._ever_connected, 1)))
        self._wire_since = now
        self._hello_done = False
        self._hello_sent_t = now
        self._flush(now)

    def _schedule_reconnect(self, now: float) -> None:
        delay = min(self.backoff_max_s,
                    self.backoff_initial_s * (2 ** max(
                        0, self._attempts - 1)))
        delay *= 1.0 + self.backoff_jitter * self._rng.random()
        self._next_connect_t = now + delay

    def _close_socks(self) -> None:
        for sock in (self._sock, self._pending_sock):
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        self._sock = None
        self._pending_sock = None

    def _disconnect(self, now: float, why: str) -> None:
        """Connection-level loss at a frame boundary: reconnect's
        business (session replay makes it lossless), not a failure."""
        self._close_socks()
        self._hello_done = False
        self._decoder.reset()
        self._wire = bytearray()
        self._wire_since = None
        self._pings.clear()
        self._attempts += 1
        self._schedule_reconnect(now)
        logger.debug("transport %s: disconnected (%s); reconnect in "
                     "%.3fs", self.name, why, self._next_connect_t - now)

    def _fail(self, reason: str) -> None:
        self._close_socks()
        self._failed = reason
        raise TransportError(f"replica {self.name}: {reason}")

    def _stage(self, frame: bytes) -> None:
        if not self._wire:
            self._wire_since = self._clock()
        self._wire.extend(frame)

    def _flush(self, now: float) -> None:
        while self._wire and self._sock is not None:
            try:
                n = self._sock.send(self._wire)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._disconnect(now, "send error")
                return
            if n <= 0:
                return
            del self._wire[:n]
        if not self._wire:
            self._wire_since = None

    def _read(self, now: float, out: list) -> None:
        while self._sock is not None:
            try:
                data = self._sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                if self._decoder.partial:
                    self.frames_corrupt += 1
                    self._fail("torn frame (connection reset mid-frame)")
                self._disconnect(now, "recv error")
                return
            if data == b"":
                if self._decoder.partial:
                    # EOF mid-frame: a torn frame, never deserialized —
                    # counted and classified as replica failure
                    self.frames_corrupt += 1
                    self._fail("torn frame (EOF mid-frame)")
                self._disconnect(now, "connection closed")
                return
            try:
                msgs = self._decoder.feed(data)
            except FrameError as e:
                self.frames_corrupt += 1
                self._fail(f"corrupt frame: {e}")
            for msg in msgs:
                self._handle(msg, now, out)
                if self._sock is None or self._exited:
                    return

    def _handle(self, msg: tuple, now: float, out: list) -> None:
        kind = msg[0]
        if kind == "evt":
            _, seq, event = msg
            if seq <= self._last_evt_seq:
                return                      # replay overlap: dedupe
            if seq != self._last_evt_seq + 1:
                self._fail(f"event sequence gap ({self._last_evt_seq} "
                           f"-> {seq})")
            self._last_evt_seq = seq
            if event[0] == "ready" and self.meta is None:
                self.meta = event[1]
            out.append(event)
        elif kind == "ack":
            applied = msg[1]
            while self._outbox and self._outbox[0][0] <= applied:
                self._outbox.popleft()
        elif kind == "hello":
            applied, reset, resume_seq = msg[1], msg[2], msg[3]
            if len(msg) > 4 and msg[4] is not None:
                # hello-time exchange: the first offset sample of the
                # link, before any ping has flown (send stamp = when we
                # staged our hello)
                self._note_clock_sample(self._hello_sent_t, now,
                                        float(msg[4]))
            if reset:
                # the server's event ring no longer covers our gap: a
                # lossless resume is impossible, so fail the replica
                # and let the router replay (correctness over uptime)
                self._fail("server reset: event ring overran the "
                           "reconnect gap")
            # a fresh session is fast-forwarded past history it never
            # owned (the server re-emits the sticky ready/state after)
            self._last_evt_seq = max(self._last_evt_seq, int(resume_seq))
            while self._outbox and self._outbox[0][0] <= applied:
                self._outbox.popleft()
            for _, frame in self._outbox:   # resend the unacked tail
                self._stage(frame)
            self._hello_done = True
            if self._ever_connected:
                self.reconnects += 1
            self._ever_connected = True
            self._attempts = 0
        elif kind == "pong":
            sent = self._pings.pop(msg[1], None)
            if sent is not None:
                if len(msg) > 2 and msg[2] is not None:
                    self._note_clock_sample(sent, now, float(msg[2]))
                else:                   # an unstamped (pre-15) pong
                    self.link_rtt_s = now - sent
        elif kind == "bye":
            self._exited = True
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _note_clock_sample(self, t_send: float, t_recv: float,
                           remote_mono: float) -> None:
        """One round trip's (rtt, offset) estimate — the NTP midpoint
        construction (:func:`~apex_tpu.observability.trace.
        estimate_offset`): the remote stamped its clock somewhere inside
        our [t_send, t_recv] window, so mapping it to the midpoint errs
        by at most RTT/2.  Kept as a sample queue for the router to
        drain (take_rtt_samples) into its RTT histogram + timeline."""
        from apex_tpu.observability.trace import estimate_offset

        offset, _ = estimate_offset(t_send, t_recv, remote_mono)
        rtt = t_recv - t_send
        self.link_rtt_s = rtt
        self.clock_offset_s = offset
        self._rtt_samples.append((rtt, offset, remote_mono))

    def take_rtt_samples(self) -> list:
        """Drain the accumulated ``(rtt_s, offset_s, remote_mono)``
        samples (router-side: histogram + ``link_clock`` spill)."""
        out = list(self._rtt_samples)
        self._rtt_samples.clear()
        return out

    def _maybe_ping(self, now: float) -> None:
        if now - self._last_ping_t < self.ping_every_s:
            return
        self._last_ping_t = now
        self._ping_nonce += 1
        self._pings[self._ping_nonce] = now
        if len(self._pings) > 64:           # unanswered pings don't grow
            oldest = min(self._pings)
            del self._pings[oldest]
        self._stage(encode_frame(("ping", self._ping_nonce)))

    # ------------------------------------------------------------ teardown

    def close(self, timeout: float = 1.0) -> None:
        """Best-effort cooperative stop + socket close (idempotent,
        never raises — the router closes fleets in a loop)."""
        if self._closed:
            return
        self._closed = True
        try:
            if (self._sock is not None and self._hello_done
                    and self._failed is None and not self._exited):
                self._stage(encode_frame(
                    ("cmd", self._cmd_seq + 1, ("stop",))))
                deadline = time.monotonic() + timeout
                self._sock.setblocking(True)
                self._sock.settimeout(0.1)
                while self._wire and time.monotonic() < deadline:
                    try:
                        n = self._sock.send(self._wire)
                    except OSError:
                        break
                    if n <= 0:
                        break
                    del self._wire[:n]
        except Exception:
            pass
        self._close_socks()

    def __enter__(self) -> "SocketTransport":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class _ServerConn:
    __slots__ = ("decoder", "out", "hello_done", "head_rem", "stalled")

    def __init__(self, max_frame_bytes: int):
        self.decoder = FrameDecoder(max_frame_bytes=max_frame_bytes)
        self.out = bytearray()
        self.hello_done = False
        # bytes of a partially-sent head frame still un-flushed (0 =
        # ``out`` starts at a frame boundary).  A deliberate drop of a
        # stalled connection must happen at a boundary only: severing
        # mid-frame would make the client see a torn frame — a
        # corruption verdict — when the wire was never corrupted
        self.head_rem = 0
        # over the buffer cap mid-frame: stop feeding live events (the
        # ring keeps them) and drop once the head frame completes
        self.stalled = False


class TransportServer:
    """Replica-side bridge: frames on a TCP listener ↔ the worker's
    ``cmd_q``/``evt_q`` pair (the exact queues
    :func:`~apex_tpu.serving.replica._replica_worker` already speaks).

    Owns a background I/O thread; the worker thread never touches a
    socket.  Events are sequence-numbered into a bounded ring
    (``event_ring``) so a reconnecting client can resume losslessly;
    commands are deduped by seq and acked.  One router connection is
    active at a time — a newer hello supersedes (and closes) the old
    connection, which is what makes reconnect churn safe.
    """

    def __init__(self, cmd_q, evt_q, *, host: str = "127.0.0.1",
                 port: int = 0, event_ring: int = 8192,
                 max_frame_bytes: int = MAX_FRAME_BYTES,
                 max_buffered_bytes: int = 16 << 20,
                 poll_s: float = 0.005):
        self._cmd_q = cmd_q
        self._evt_q = evt_q
        self._poll_s = poll_s
        self._max_frame = max_frame_bytes
        # cap on one connection's un-flushed outbound bytes: a live but
        # non-draining peer (stalled router link) must not grow replica
        # memory without bound — past the cap the connection is dropped
        # and the session seq-replay makes the loss recoverable
        self._max_buffered = max_buffered_bytes
        self._ring: collections.deque = collections.deque(
            maxlen=event_ring)
        self._evt_seq = 0
        self._cmd_applied = 0
        # sticky copies of the handshake-critical events, re-emitted to
        # a FRESH session whose gap the ring can no longer cover (the
        # restarted-router reattach path)
        self._sticky_ready: Optional[tuple] = None
        self._sticky_state: Optional[tuple] = None
        self._conns: dict = {}              # sock -> _ServerConn
        self._active: Optional[socket.socket] = None
        self._closing = False
        self._send_bye = False
        self._stopped = threading.Event()
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((host, port))
        lsock.listen(8)
        lsock.setblocking(False)
        self._lsock = lsock
        self.address: Tuple[str, int] = lsock.getsockname()
        self._thread = threading.Thread(
            target=self._serve, name="apex-transport-server", daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self.address[1]

    # --------------------------------------------------------------- loop

    def _serve(self) -> None:
        try:
            while True:
                self._pump_events()
                if self._closing and self._evt_q_drained():
                    self._goodbye()
                    return
                rlist = [self._lsock] + list(self._conns)
                wlist = [s for s, c in self._conns.items() if c.out]
                try:
                    r, w, _ = select.select(rlist, wlist, [],
                                            self._poll_s)
                except (OSError, ValueError):
                    if self._lsock.fileno() < 0:
                        return        # close() force-closed the listener
                    # a socket died between iterations; prune and retry
                    self._prune()
                    continue
                for s in w:
                    self._flush(s)
                for s in r:
                    if s is self._lsock:
                        self._accept()
                    else:
                        self._read(s)
        except Exception as e:  # noqa: BLE001 — a server thread must not
            #                     die silently; the client sees silence
            #                     and the router's ladder takes over
            logger.warning("transport server %s: loop error: %r",
                           self.address, e)
        finally:
            for s in list(self._conns):
                self._drop(s)
            try:
                self._lsock.close()
            except OSError:
                pass
            self._stopped.set()

    def _prune(self) -> None:
        for s in list(self._conns):
            if s.fileno() < 0:
                self._drop(s)

    def _accept(self) -> None:
        while True:
            try:
                conn, _ = self._lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            conn.setblocking(False)
            try:
                conn.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:
                pass
            self._conns[conn] = _ServerConn(self._max_frame)

    def _read(self, s: socket.socket) -> None:
        conn = self._conns.get(s)
        if conn is None:
            return
        try:
            data = s.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(s)
            return
        if data == b"":
            self._drop(s)
            return
        try:
            msgs = conn.decoder.feed(data)
        except FrameError as e:
            # garbage from the router direction: drop the connection;
            # the client reconnects and re-sends its unacked commands
            logger.warning("transport server: dropping connection on "
                           "bad inbound frame: %s", e)
            self._drop(s)
            return
        for msg in msgs:
            self._handle(s, conn, msg)

    def _handle(self, s, conn: _ServerConn, msg: tuple) -> None:
        kind = msg[0]
        if kind == "hello":
            # msg[2] (the client's cmd_seq) is informational: a fresh
            # client's outbox is entirely unacked and resends from
            # seq 1, so only the reset below matters for dedupe
            last_seen, fresh = int(msg[1]), bool(msg[3])
            if fresh:
                # a brand-new session (restarted router): its command
                # numbering starts over — the OLD session's dedupe
                # watermark must not black-hole the new submits
                self._cmd_applied = 0
            oldest = self._ring[0][0] - 1 if self._ring else self._evt_seq
            covered = oldest <= last_seen <= self._evt_seq
            reset = not covered and not fresh
            resume_seq = last_seen if covered else self._evt_seq
            # the monotonic clock stamp (ISSUE 15) goes only to clients
            # that ADVERTISED it (hello element 5): a pre-15 router
            # strict-unpacks a 4-tuple reply, and a mixed-version fleet
            # mid-rolling-upgrade (replicas first) must keep working
            if len(msg) > 4:
                reply = ("hello", self._cmd_applied, reset, resume_seq,
                         time.monotonic())
            else:
                reply = ("hello", self._cmd_applied, reset, resume_seq)
            conn.out.extend(encode_frame(reply))
            if covered:
                for seq, evt in self._ring:
                    if seq > last_seen:
                        conn.out.extend(encode_frame(("evt", seq, evt)))
            elif fresh:
                # fast-forwarded past history it never owned: re-emit
                # the handshake-critical sticky events as NEW events so
                # the fresh router still gets meta + current state
                for evt in (self._sticky_ready, self._sticky_state):
                    if evt is not None:
                        self._evt_seq += 1
                        self._ring.append((self._evt_seq, evt))
                        conn.out.extend(encode_frame(
                            ("evt", self._evt_seq, evt)))
            conn.hello_done = True
            if self._active is not None and self._active is not s:
                self._drop(self._active)
            self._active = s
        elif kind == "cmd":
            seq, cmd = int(msg[1]), msg[2]
            if seq > self._cmd_applied:
                self._cmd_applied = seq
                self._cmd_q.put(cmd)
            conn.out.extend(encode_frame(("ack", self._cmd_applied)))
        elif kind == "ping":
            # the pong's monotonic stamp is the clock-alignment anchor
            # (ISSUE 15): this host's clock at (approximately) the
            # client's round-trip midpoint
            conn.out.extend(encode_frame(
                ("pong", msg[1], time.monotonic())))

    def _pump_events(self) -> None:
        while True:
            try:
                raw = self._evt_q.get_nowait()
            except queue_mod.Empty:
                return
            # the worker's batched relay (ISSUE 15 satellite) arrives
            # as one ("batch", [...]) payload; each sub-event gets its
            # OWN sequence number so the client's dedupe/sticky logic
            # never sees the wrapper
            subs = raw[1] if raw and raw[0] == "batch" else (raw,)
            for evt in subs:
                self._pump_one(evt)

    def _pump_one(self, evt: tuple) -> None:
        if evt[0] == "ready":
            self._sticky_ready = evt
        elif evt[0] == "state":
            self._sticky_state = evt
        self._evt_seq += 1
        self._ring.append((self._evt_seq, evt))
        active = self._active
        if active is not None and active in self._conns and \
                self._conns[active].hello_done:
            conn = self._conns[active]
            if conn.stalled:
                return      # ring keeps the event; conn is awaiting
            #                 its boundary drop in _flush
            conn.out.extend(
                encode_frame(("evt", self._evt_seq, evt)))
            if len(conn.out) > self._max_buffered:
                # live-but-stalled peer: drop rather than grow
                # without bound; seq replay recovers on reconnect.
                # Only ever sever at a frame boundary — a mid-frame
                # cut would read as a TORN frame (a corruption
                # verdict) at the client, not a connection loss
                if conn.head_rem == 0:
                    logger.warning(
                        "transport server %s: dropping stalled "
                        "connection (%d bytes un-flushed)",
                        self.address, len(conn.out))
                    self._drop(active)
                else:
                    logger.warning(
                        "transport server %s: stalling connection "
                        "(%d bytes un-flushed, mid-frame); will "
                        "drop at the frame boundary",
                        self.address, len(conn.out))
                    conn.stalled = True

    @staticmethod
    def _mark_sent(conn: _ServerConn, n: int) -> None:
        """Advance ``head_rem`` across ``n`` just-sent bytes of
        ``conn.out`` (called BEFORE they are deleted).  ``out`` holds
        whole frames except for a partially-sent head, so frame lengths
        parse directly from the buffer."""
        pos = 0
        if conn.head_rem:
            take = min(n, conn.head_rem)
            conn.head_rem -= take
            pos = take
        while pos < n:
            _, body_len, _ = _HEADER.unpack_from(conn.out, pos)
            total = _HEADER.size + body_len
            if pos + total <= n:
                pos += total
            else:
                conn.head_rem = total - (n - pos)
                pos = n

    def _flush(self, s: socket.socket) -> None:
        conn = self._conns.get(s)
        if conn is None or not conn.out:
            return
        try:
            n = s.send(conn.out)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(s)
            return
        if n > 0:
            self._mark_sent(conn, n)
            del conn.out[:n]
        if conn.stalled and conn.head_rem == 0:
            # the deferred stall-drop: the head frame completed, so the
            # sever now lands on a boundary and the client reconnects
            # (lossless seq replay) instead of reporting a torn frame
            self._drop(s)

    def _drop(self, s: socket.socket) -> None:
        self._conns.pop(s, None)
        if self._active is s:
            self._active = None
        try:
            s.close()
        except OSError:
            pass

    def _evt_q_drained(self) -> bool:
        active = self._active
        flushed = (active is None or active not in self._conns
                   or not self._conns[active].out)
        try:
            empty = self._evt_q.empty()
        except Exception:
            empty = True
        return empty and flushed

    def _goodbye(self) -> None:
        active = self._active
        if self._send_bye and active is not None and \
                active in self._conns:
            conn = self._conns[active]
            conn.out.extend(encode_frame(("bye",)))
            deadline = time.monotonic() + 2.0
            try:
                active.setblocking(True)
                active.settimeout(0.2)
                while conn.out and time.monotonic() < deadline:
                    n = active.send(conn.out)
                    if n <= 0:
                        break
                    del conn.out[:n]
            except OSError:
                pass

    # ------------------------------------------------------------ teardown

    def close(self, *, bye: bool = True, timeout: float = 5.0) -> None:
        """Flush pending events (so a ``drained`` event beats the FIN),
        optionally send the intentional-exit ``bye``, and stop."""
        self._send_bye = bye
        self._closing = True
        self._stopped.wait(timeout)
        try:
            self._lsock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Host daemon
# ---------------------------------------------------------------------------


def replica_serve(spec, name: str, *, host: str = "127.0.0.1",
                  port: int = 0, ready_hook=None) -> None:
    """Process main for one cross-host replica: the existing
    :func:`~apex_tpu.serving.replica._replica_worker` lifecycle (same
    ready handshake carrying the restored ckpt step + debug port, same
    PreemptionGuard SIGTERM drain, same orphan watchdog) served over a
    :class:`TransportServer` instead of multiprocessing queues.

    Runs the worker on the *calling* thread so the PreemptionGuard owns
    the real SIGTERM handler — a preempted/rolled host drains exactly
    like the in-process transport.  ``ready_hook(address)`` fires once
    the listener is bound (how a spawner learns an ephemeral port).
    """
    from apex_tpu.serving.replica import _replica_worker

    cmd_q: queue_mod.Queue = queue_mod.Queue()
    evt_q: queue_mod.Queue = queue_mod.Queue()
    server = TransportServer(cmd_q, evt_q, host=host, port=port)
    if ready_hook is not None:
        ready_hook(server.address)
    try:
        _replica_worker(spec, name, cmd_q, evt_q, os.getppid())
    finally:
        server.close(bye=True)


def _replica_serve_entry(spec, name, host, port, addr_q) -> None:
    replica_serve(spec, name, host=host, port=port,
                  ready_hook=addr_q.put)


def start_replica_server(spec, name: str, *, host: str = "127.0.0.1",
                         port: int = 0, start_method: str = "spawn",
                         addr_timeout_s: float = 60.0):
    """Spawn a :func:`replica_serve` daemon locally (loopback testing /
    single-host fleets); returns ``(process, (host, port))``.  A real
    cross-host deployment runs ``replica_serve`` under its own process
    supervisor on each host instead — see docs/serving.md."""
    import multiprocessing as mp

    ctx = mp.get_context(start_method)
    addr_q = ctx.Queue()
    proc = ctx.Process(target=_replica_serve_entry,
                       args=(spec, name, host, port, addr_q),
                       daemon=False, name=f"apex-replica-serve-{name}")
    proc.start()
    deadline = time.monotonic() + addr_timeout_s
    while True:
        try:
            addr = addr_q.get(timeout=0.2)
            break
        except queue_mod.Empty:
            if not proc.is_alive():
                raise RuntimeError(
                    f"replica server {name} died before binding "
                    f"(exitcode {proc.exitcode})") from None
            if time.monotonic() > deadline:
                proc.terminate()
                raise RuntimeError(
                    f"replica server {name} did not bind in "
                    f"{addr_timeout_s:.0f}s") from None
    return proc, (addr[0], int(addr[1]))
