"""Batched multi-LoRA serving: paged adapter arena + gathered delta.

One replica, one base checkpoint, many tenants: each tenant's
fine-tune is a low-rank (LoRA) update ``W + B @ A * alpha/rank`` on the
four projections of every layer (fused QKV, attention dense, MLP fc1,
MLP fc2).  This module applies the paged-KV trick to *weights*:

- **Adapter arena** — the A/B pairs of every resident adapter live in
  stacked device arrays ``[L, n_slots, ...]``, one *slot* per adapter,
  managed host-side by :class:`AdapterArena` on the exact
  :class:`~apex_tpu.serving.kv_cache.BlockAllocator` refcount machinery
  the KV cache uses (one "block" = one adapter slot).  Slot 0 is the
  permanent **zero adapter**: all-zero A/B rows that every
  ``adapter_id=None`` request gathers, making the delta an exact zero
  and the stream bitwise identical to the bare engine.  Registered
  adapters are LRU-evicted like prefix blocks when cold; a pin per
  active request (``share``/``free`` under the request's rid) keeps a
  hot adapter resident for as long as any slot references it.
- **Gathered delta** — the decode/prefill step receives a per-slot
  ``[max_batch]`` adapter-slot vector as DATA (never shape) and
  computes ``delta = (x @ A[slot]) @ B_scaled[slot]`` per batch slot:
  the same scalar-prefetch index-map pattern
  :func:`~apex_tpu.serving.paged_attention.paged_attention_decode` uses
  for block tables, so adapter mix/churn never recompiles.  The base
  GEMM is untouched; the rank-r bypass adds ``O(r/H)`` relative FLOPs.

Tensor parallelism follows the base projections: for column-parallel
layers (qkv, fc1) A is replicated and B is sharded on the output dim —
the delta lands pre-split exactly like the base output.  For
row-parallel layers (dense, fc2) A is sharded on the *input* dim and B
replicated — each rank computes a partial delta from its input shard
and the engine all-reduces it alongside nothing else (one extra psum
per row-parallel projection per layer, only when tp > 1).

``B`` is stored pre-scaled by ``alpha/rank`` at registration, so the
runtime step is two plain matmuls.
"""

from __future__ import annotations

import dataclasses
import logging
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from apex_tpu.serving.kv_cache import BlockAllocator, OutOfBlocksError

__all__ = [
    "ADAPTER_REGISTRY",
    "AdapterArena",
    "LoRAConfig",
    "adapter_partition_specs",
    "adapter_shapes",
    "init_adapter_arena",
    "init_adapter_weights",
    "lora_delta",
    "pack_adapter_values",
    "restore_adapter_for_serving",
]

logger = logging.getLogger(__name__)

#: Composite owner under which the arena itself holds every resident
#: adapter's slot (the ``CACHE_OWNER`` pattern from kv_cache.py): a
#: slot is evictable exactly when the registry is its only holder.
ADAPTER_REGISTRY = "<adapter-registry>"

#: Arena array order: (A, B) per projection, projections in this order.
PROJECTIONS = ("qkv", "dense", "fc1", "fc2")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """Adapter-arena shape knobs (compile-time constants of the engine).

    ``max_adapters`` is the number of *resident* adapter slots — the
    zero adapter (slot 0) is always present on top of it.  ``rank`` is
    the shared low-rank width every registered adapter must match (the
    arena arrays are stacked, so rank is shape).  ``alpha`` is the
    conventional LoRA scale; B is stored pre-multiplied by
    ``alpha/rank``.  ``fused=True`` gathers A/B rows with the Pallas
    scalar-prefetch kernel; ``False`` uses the jnp.take reference twin
    (same values, used by the parity test and as the interpret
    fallback's sanity check).
    """

    rank: int = 8
    max_adapters: int = 8
    alpha: float = 16.0
    fused: bool = True

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"lora rank must be >= 1 (got {self.rank})")
        if self.max_adapters < 1:
            raise ValueError(
                f"max_adapters must be >= 1 (got {self.max_adapters})")

    @property
    def n_slots(self) -> int:
        """Resident slots + the permanent zero adapter at slot 0."""
        return self.max_adapters + 1


# ---------------------------------------------------------------------------
# Shapes, device arrays, partition specs
# ---------------------------------------------------------------------------


def adapter_shapes(config, lora: LoRAConfig
                   ) -> Dict[str, Tuple[Tuple[int, int], Tuple[int, int]]]:
    """Per-projection ``(A, B)`` shapes (without the ``[L, n_slots]``
    stack dims), matching the serving model's fused projections."""
    d = config.head_dim
    n, g = config.num_attention_heads, config.query_groups
    h, f, r = config.hidden_size, config.ffn_size, lora.rank
    return {
        "qkv": ((h, r), (r, (n + 2 * g) * d)),
        "dense": ((n * d, r), (r, h)),
        "fc1": ((h, r), (r, f)),
        "fc2": ((f, r), (r, h)),
    }


def adapter_partition_specs(tp_axis: Optional[str]):
    """shard_map partition specs for the 8 arena arrays, in arena order
    ``(qkv_a, qkv_b, dense_a, dense_b, fc1_a, fc1_b, fc2_a, fc2_b)``.

    Column-parallel projections (qkv, fc1) shard B on the output dim
    (array dim 3); row-parallel ones (dense, fc2) shard A on the input
    dim (array dim 2); everything else is replicated.
    """
    from jax.sharding import PartitionSpec as P

    rep = P(None, None, None, None)
    col_b = P(None, None, None, tp_axis)
    row_a = P(None, None, tp_axis, None)
    return (rep, col_b, row_a, rep, rep, col_b, row_a, rep)


def init_adapter_arena(config, lora: LoRAConfig, mesh=None,
                       tp_axis: str = "tp"):
    """Zero-initialized adapter arrays ``[L, n_slots, *shape]`` in arena
    order, placed on ``mesh`` when given.

    All slots start as the zero adapter, so a fresh arena is inert: a
    request gathering any slot gets an exact-zero delta.  Like the int8
    scale arenas, placement uses replicated specs when the tp axis has
    size 1 — that is what jit emits for the step outputs there, so the
    engine's adapter round trip stays jit-cache-stable.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    shapes = adapter_shapes(config, lora)
    L, s = config.num_layers, lora.n_slots
    dtype = config.param_dtype
    arrays = []
    for proj in PROJECTIONS:
        for shape in shapes[proj]:
            arrays.append(jnp.zeros((L, s) + shape, dtype))
    if mesh is None:
        return tuple(arrays)
    specs = adapter_partition_specs(tp_axis)
    if mesh.shape.get(tp_axis, 1) == 1:
        specs = tuple(P() for _ in specs)
    return tuple(
        jax.device_put(a, NamedSharding(mesh, spec))
        for a, spec in zip(arrays, specs))


# ---------------------------------------------------------------------------
# Host weights: deterministic fixtures, packing, checkpoint restore
# ---------------------------------------------------------------------------


def init_adapter_weights(config, lora: LoRAConfig, *, seed: int = 0
                         ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    """Deterministic random host weights ``{proj: (A [L, in, r],
    B [L, r, out])}`` for one adapter.

    Both A and B are nonzero (unlike training-time LoRA init, which
    zeroes B) and deliberately LOUD (0.25-std entries) so two adapters
    seeded differently produce visibly different token streams even on
    tiny test models — this is the test/bench fixture; production
    registers trained pairs via :func:`restore_adapter_for_serving`.
    """
    rng = np.random.default_rng(int(seed))
    shapes = adapter_shapes(config, lora)
    L = config.num_layers
    out = {}
    for proj in PROJECTIONS:
        (ai, ar), (br, bo) = shapes[proj]
        a = rng.standard_normal((L, ai, ar)).astype(np.float32) * 0.25
        b = rng.standard_normal((L, br, bo)).astype(np.float32) * 0.25
        out[proj] = (a, b)
    return out


def pack_adapter_values(config, lora: LoRAConfig, weights, dtype
                        ) -> Tuple[np.ndarray, ...]:
    """Validate one adapter's host weights and pack them into the 8
    arena-ordered per-slot values ``[L, *shape]``, B pre-scaled by
    ``alpha/rank`` (the arena stores the runtime form)."""
    shapes = adapter_shapes(config, lora)
    L = config.num_layers
    scale = lora.alpha / lora.rank
    vals = []
    for proj in PROJECTIONS:
        try:
            a, b = weights[proj]
        except (KeyError, TypeError, ValueError):
            raise ValueError(
                f"adapter weights missing projection {proj!r} "
                f"(need {{proj: (A, B)}} for {PROJECTIONS})") from None
        a = np.asarray(a)
        b = np.asarray(b)
        want_a, want_b = ((L,) + shapes[proj][0], (L,) + shapes[proj][1])
        if a.shape != want_a or b.shape != want_b:
            raise ValueError(
                f"adapter {proj!r} shapes {a.shape}/{b.shape} do not "
                f"match arena {want_a}/{want_b} (rank={lora.rank})")
        vals.append(np.asarray(a, dtype))
        vals.append(np.asarray(b * scale, dtype))
    return tuple(vals)


def restore_adapter_for_serving(ckpt_dir: str, config, lora: LoRAConfig, *,
                                key: str = "lora", sharded: bool = True,
                                verify: bool = True, with_step: bool = False):
    """Restore the newest intact adapter checkpoint as host weights.

    The spec-layer restore path from ``loader.restore_gpt_for_serving``,
    pointed at an adapter checkpoint: a
    :class:`~apex_tpu.resilience.CheckpointManager` directory whose
    checkpoints carry ``{key: {proj: {"a": ..., "b": ...}}}`` (any
    layer-stack factoring — placement is reshape-only via the
    mesh-independent ``load_logical`` view).  Checksum-verified, corrupt
    newest falls back to the previous committed step.  Returns the
    ``{proj: (A, B)}`` dict :meth:`ServingEngine.register_adapter`
    takes (plus the step with ``with_step=True``).
    """
    from apex_tpu import checkpoint as ckpt
    from apex_tpu.resilience import CheckpointManager, reshard

    shapes = adapter_shapes(config, lora)
    L = config.num_layers
    mgr = CheckpointManager(ckpt_dir, sharded=sharded)
    failures = []
    for step in reversed(mgr.all_steps()):
        try:
            if verify:
                mgr.verify(step)
            logical, _ = reshard.load_logical(mgr.step_path(step))
            weights = {}
            for proj in PROJECTIONS:
                pair = []
                for part, shape in zip(("a", "b"), shapes[proj]):
                    path = f"{key}/{proj}/{part}"
                    if path not in logical:
                        raise ckpt.CheckpointCorruptError(
                            f"adapter checkpoint has no leaf {path!r}")
                    host = logical[path]
                    tgt = (L,) + shape
                    if int(np.prod(host.shape)) != int(np.prod(tgt)):
                        raise ckpt.CheckpointCorruptError(
                            f"{path}: logical shape {list(host.shape)} "
                            f"cannot reshape to adapter shape {list(tgt)}")
                    pair.append(np.ascontiguousarray(host).reshape(tgt))
                weights[proj] = tuple(pair)
            if failures:
                logger.warning(
                    "adapter restore fell back to step %d past %s",
                    step, "; ".join(failures))
            if with_step:
                return weights, step
            return weights
        except (ckpt.CheckpointCorruptError, ValueError, OSError,
                KeyError) as e:
            failures.append(f"step {step}: {e!r}")
            logger.warning(
                "adapter checkpoint step %d unusable (%r); falling back",
                step, e)
    raise FileNotFoundError(
        f"no adapter checkpoint under {ckpt_dir!r} restorable"
        + (f" (tried: {'; '.join(failures)})" if failures else ""))


# ---------------------------------------------------------------------------
# The refcounted slot registry
# ---------------------------------------------------------------------------


class OutOfAdapterSlotsError(OutOfBlocksError):
    """Raised when registration needs a slot and every resident adapter
    is pinned by an active request (nothing is LRU-evictable)."""


class AdapterArena:
    """Host-side slot registry for the device adapter arrays.

    ``BlockAllocator(n_slots)`` does the refcounting: the registry
    itself holds every resident adapter's slot under
    :data:`ADAPTER_REGISTRY` (the ``CACHE_OWNER`` pattern), and every
    active request that names the adapter ``share``s the slot under its
    rid.  A slot is LRU-evictable exactly when its refcount is 1 —
    registry-only, no live pins.  Slot 0 (the zero adapter every
    ``adapter_id=None`` request gathers) is allocated once at
    construction and never enters the LRU.
    """

    def __init__(self, n_slots: int):
        if n_slots < 2:
            raise ValueError(
                f"adapter arena needs >= 2 slots (zero adapter + one "
                f"resident), got {n_slots}")
        self.n_slots = n_slots
        self.allocator = BlockAllocator(n_slots)
        (self.zero_slot,) = self.allocator.alloc(1, ADAPTER_REGISTRY)
        assert self.zero_slot == 0, "zero adapter must land in slot 0"
        # adapter_id -> slot, LRU order (oldest first; register/pin
        # move-to-end, eviction walks from the front)
        self._slots: "OrderedDict[str, int]" = OrderedDict()
        self._pins: Dict[Any, int] = {}      # rid -> pinned slot
        self.loads = 0                       # lifetime registrations
        self.evictions = 0                   # lifetime LRU evictions

    def __len__(self) -> int:
        return len(self._slots)

    def resident(self, adapter_id) -> bool:
        return adapter_id in self._slots

    def slot_of(self, adapter_id) -> Optional[int]:
        return self._slots.get(adapter_id)

    def residents(self):
        """Resident adapter ids, LRU-oldest first (heartbeat payload
        for the fleet's adapter-affinity placement)."""
        return list(self._slots)

    @property
    def active(self) -> int:
        """Live request pins across all adapters."""
        return len(self._pins)

    def register(self, adapter_id) -> Tuple[int, Optional[str]]:
        """Claim a slot for ``adapter_id``; returns ``(slot, evicted)``.

        A resident id re-registers **in place** (same slot, moved to
        LRU front) — that is the hot-swap path: the caller overwrites
        the slot's rows and in-flight requests pinning the old version
        keep their already-gathered semantics tick-to-tick.  A new id
        takes a free slot, LRU-evicting the coldest unpinned adapter if
        the arena is full; if every resident adapter is pinned,
        :class:`OutOfAdapterSlotsError`.
        """
        self.loads += 1
        if adapter_id in self._slots:
            self._slots.move_to_end(adapter_id)
            return self._slots[adapter_id], None
        evicted = None
        if not self.allocator.can_alloc(1):
            evicted = self._evict_one()
            if evicted is None:
                self.loads -= 1
                raise OutOfAdapterSlotsError(
                    f"no adapter slot free: all {len(self._slots)} "
                    f"resident adapters are pinned by active requests")
        (slot,) = self.allocator.alloc(1, ADAPTER_REGISTRY)
        self._slots[adapter_id] = slot
        return slot, evicted

    def _evict_one(self) -> Optional[str]:
        for aid, slot in self._slots.items():
            if self.allocator.refcount(slot) == 1:   # registry-only
                del self._slots[aid]
                self.allocator.free([slot], ADAPTER_REGISTRY)
                self.evictions += 1
                return aid
        return None

    def unregister(self, adapter_id) -> int:
        """Drop the registry's hold on ``adapter_id``.  The slot stays
        allocated (and its rows live) until the last pinning request
        finishes; new requests can no longer name the adapter."""
        slot = self._slots.pop(adapter_id, None)
        if slot is None:
            raise KeyError(f"adapter {adapter_id!r} is not resident")
        self.allocator.free([slot], ADAPTER_REGISTRY)
        return slot

    def pin(self, adapter_id, rid) -> int:
        """Pin ``adapter_id`` for request ``rid``; returns the slot the
        request's batch entry should gather."""
        slot = self._slots.get(adapter_id)
        if slot is None:
            raise KeyError(f"adapter {adapter_id!r} is not resident")
        if rid in self._pins:
            raise ValueError(f"request {rid!r} already pins a slot")
        self.allocator.share(slot, rid)
        self._slots.move_to_end(adapter_id)
        self._pins[rid] = slot
        return slot

    def unpin(self, rid) -> None:
        """Release ``rid``'s pin.  Idempotent no-op for a request that
        never pinned (the ``adapter_id=None`` common case), so every
        terminal path can call it unconditionally."""
        slot = self._pins.pop(rid, None)
        if slot is not None:
            self.allocator.free([slot], rid)

    def pinned_slot(self, rid) -> int:
        """The arena slot ``rid`` gathers (zero slot when unpinned)."""
        return self._pins.get(rid, self.zero_slot)

    def check(self) -> None:
        """Arena invariants (test hook, mirrors ``BlockAllocator.check``):
        allocator free-XOR-held; every resident slot held by the
        registry; every pin a share on a known slot."""
        self.allocator.check()
        seen = set()
        for aid, slot in self._slots.items():
            assert slot not in seen, f"slot {slot} mapped twice"
            seen.add(slot)
            assert self.allocator.refcount(slot) >= 1, \
                f"resident adapter {aid!r} slot {slot} has no holders"
        for rid, slot in self._pins.items():
            assert self.allocator.refcount(slot) >= 1, \
                f"pin {rid!r} on slot {slot} with no holders"
        assert self.allocator.refcount(self.zero_slot) >= 1, \
            "zero adapter slot was freed"


# ---------------------------------------------------------------------------
# The gathered delta: Pallas scalar-prefetch kernel + reference twin
# ---------------------------------------------------------------------------


def _delta_kernel(slots_ref, x_ref, a_ref, b_ref, o_ref):
    """One batch slot's rank-r bypass: ``(x @ A[slot]) @ B[slot]`` in
    fp32 on the MXU.  ``slots_ref`` is the scalar-prefetch vector the
    index maps consumed; the body never reads it."""
    import jax.numpy as jnp

    del slots_ref
    x = x_ref[...][:, 0, :].astype(jnp.float32)        # [S, in]
    a = a_ref[0].astype(jnp.float32)                   # [in, r]
    b = b_ref[0].astype(jnp.float32)                   # [r, out]
    t = jnp.dot(x, a, preferred_element_type=jnp.float32)
    o_ref[:, 0, :] = jnp.dot(
        t, b, preferred_element_type=jnp.float32).astype(o_ref.dtype)


def lora_delta_fused(x, a, b, slots):
    """Gathered LoRA delta via scalar-prefetch (the block-table trick
    on weights): grid over batch slots, A/B block index maps read
    ``slots[i]`` — which adapter a slot runs is data the prefetched
    vector carries, never a shape.

    ``x [S, B, in]`` seq-major activations; ``a [n_slots, in, r]``;
    ``b [n_slots, r, out]`` (pre-scaled); ``slots [B]`` int.  Returns
    ``[S, B, out]`` in ``x.dtype``.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from apex_tpu.serving.paged_attention import _interpret, pltpu

    S, B, IN = x.shape
    r, out = b.shape[1], b.shape[2]

    def x_idx(i, slots_ref):
        return (0, i, 0)

    def ab_idx(i, slots_ref):
        return (slots_ref[i], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((S, 1, IN), x_idx),
            pl.BlockSpec((1, IN, r), ab_idx),
            pl.BlockSpec((1, r, out), ab_idx),
        ],
        out_specs=pl.BlockSpec((S, 1, out), x_idx),
    )
    params_cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return pl.pallas_call(
        _delta_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, B, out), x.dtype),
        # batch slots are independent (parallel, megacore-splittable)
        compiler_params=params_cls(dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(slots.astype(jnp.int32), x, a, b)


def lora_delta_unfused(x, a, b, slots):
    """Reference twin of :func:`lora_delta_fused`: materialize the
    per-slot A/B gather with ``jnp.take`` and contract in fp32."""
    import jax.numpy as jnp

    ag = jnp.take(a, slots, axis=0).astype(jnp.float32)    # [B, in, r]
    bg = jnp.take(b, slots, axis=0).astype(jnp.float32)    # [B, r, out]
    t = jnp.einsum("sbi,bir->sbr", x.astype(jnp.float32), ag)
    return jnp.einsum("sbr,bro->sbo", t, bg).astype(x.dtype)


def lora_delta(x, a, b, slots, *, fused: bool = True):
    """``delta[s, i] = (x[s, i] @ A[slots[i]]) @ B_scaled[slots[i]]``."""
    if fused:
        return lora_delta_fused(x, a, b, slots)
    return lora_delta_unfused(x, a, b, slots)
