"""SLO autopilot (ISSUE 18): the control loop that operates the fleet.

PR 15 built the sensors (per-tenant/per-priority windowed TTFT/TPOT,
link RTT histograms, trace critical paths naming the slowest hop of
every tail request) and PRs 13/16/17 built every actuator
(``replica_serve`` host daemons, ready-handshake + SIGTERM-drain
lifecycle, rollout/swap, live knob broadcasts) — but closing the loop
was still a human reading ``/fleet/statusz``.  :class:`FleetAutopilot`
closes three loops beside ``FleetRouter.pump()``:

**Scale** — grow/drain replicas off queue depth and the windowed
p99-trend slope, through an injected ``spawn(name) -> client`` factory.
New replicas join via the ordinary ready handshake (never dispatched
before ready); drained replicas leave via the ordinary SIGTERM-drain
path (never a stranded request).  Never below ``min_replicas``, at most
one scale action per cool-down window, and a flapping replica (up/down
churn, or a spawn that keeps dying before ready) is QUARANTINED under
capped exponential back-off (``fleet/autopilot/quarantines``) instead
of re-spawned in a hot loop.  A partition during scale-up reaps the
half-born replica (``fleet/autopilot/reaps``) — it is removed from the
routing table, not leaked.  A tail driven by a degraded link is demoted
in placement by the router already; the autopilot recognizes that
signature (trend up, queues shallow, a link flagged degraded) and
explicitly decides *not* to scale.

**Retune** — when trace attribution (an injected ``attribution()``
callable; see :func:`trace_attribution`) blames a hop, actuate the
matching knob: shrink the chunked-prefill ``prefill_chunk`` when
``prefill`` dominates tail traces, lower speculative ``spec_k`` when
acceptance sags below the floor, tighten/relax the router's
``max_queue_depth`` shed bound when ``router_queue`` grows.  Engine
knobs travel as a broadcast command with acks (the PR 17
``swap_adapter`` discipline, over :meth:`FleetRouter.set_knobs`).

**Canary** — every engine-knob change lands on ONE replica first and is
judged over a bounded observation window by the paired
median-of-ratios A/B machinery the bench uses: at each round boundary
the canary's windowed p99 TPOT is paired with the control replicas'
median p99; the median of the per-round ratios is the verdict.  A
regressing canary is rolled back automatically
(``fleet/autopilot/rollbacks``); a healthy one is committed fleet-wide.
A canary host that dies mid-observation yields verdict
``inconclusive`` — no rollback storm, the knob died with the host.
Router-local knobs (the shed bound) have no per-replica split, so they
are judged before/after against the fleet p99 over the same window.

Every decision is four typed timeline events — ``autopilot_observe``
(the signal snapshot) → ``autopilot_decide`` (action + reason) →
``autopilot_act`` (what was actuated) → ``autopilot_verdict`` (how it
resolved) — sharing a ``decision_id`` and riding the trace plane's
spill files, so ``scripts/trace_report.py`` can reconstruct *why* the
fleet changed shape next to the request traces that made it.  The
whole loop runs on an injectable clock (default: the router's), reads
only router/registry state, and draws ids from deterministic counters:
the same signals produce the same action sequence, run after run.

Disarmed is free: an unconstructed autopilot touches nothing — no
event, no counter, no per-replica histogram, no placement change (the
router's ``per_replica_slo`` flag exists so even the canary windows
cost nothing until an autopilot flips it on).

jax-free by design, like the router it drives.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
from typing import Any, Callable, Dict, List, Optional

from apex_tpu.observability import timeline

__all__ = ["AutopilotConfig", "FleetAutopilot", "trace_attribution"]

logger = logging.getLogger(__name__)


def trace_attribution(timeline_dir: str, *, tail_pct: float = 99.0,
                      strict: bool = False) -> Optional[dict]:
    """Tail attribution off a trace spill dir — the default glue
    between the trace plane and the retune loop: returns
    ``{"slowest_hop": <bucket>, "share": <0..1>, "tail": n}`` for the
    hop that dominates the most tail traces (ties break toward the
    alphabetically-first bucket, deterministically), or ``None`` when
    there is no closed tail yet.  Wrap in a lambda to inject:
    ``FleetAutopilot(router, attribution=lambda:
    trace_attribution(spill_dir))``."""
    from apex_tpu.observability.trace import merge_dir

    try:
        tail = merge_dir(timeline_dir, strict=strict,
                         tail_pct=tail_pct)["summary"]["tail"]
    except FileNotFoundError:
        return None
    if not tail:
        return None
    votes: Dict[str, int] = {}
    for row in tail:
        votes[row["slowest_hop"]] = votes.get(row["slowest_hop"], 0) + 1
    hop = min(votes, key=lambda h: (-votes[h], h))
    return {"slowest_hop": hop,
            "share": round(votes[hop] / len(tail), 4),
            "tail": len(tail)}


@dataclasses.dataclass(frozen=True)
class AutopilotConfig:
    """Autopilot policy — every threshold the three loops read.

    Scale: grow when fleet queue depth reaches
    ``scale_up_queue_depth`` OR the windowed p99-TPOT slope reaches
    ``scale_up_trend_ms_per_s`` (unless the trend is explained by a
    degraded link); drain back when depth falls to
    ``scale_down_queue_depth`` with a non-positive trend.  One scale
    action per ``scale_cooldown_s``; pool clamped to
    [``min_replicas``, ``max_replicas``].  A replica with
    ``flap_threshold`` down-edges inside ``flap_window_s`` is
    quarantined ``quarantine_base_s`` (doubling per quarantine, capped
    at ``quarantine_cap_s``).

    Retune: one knob change per ``retune_cooldown_s``, canaried over
    ``canary_observe_s`` split into ``canary_rounds`` paired samples;
    fewer than ``canary_min_rounds`` valid pairs is inconclusive;
    a median ratio above ``canary_regress_ratio`` rolls back.
    """

    # -- scale loop
    min_replicas: int = 1
    max_replicas: int = 8
    scale_up_queue_depth: int = 16
    scale_up_trend_ms_per_s: float = 5.0
    scale_down_queue_depth: int = 2
    scale_cooldown_s: float = 30.0
    join_timeout_s: float = 300.0
    drain_timeout_s: float = 120.0
    # -- flap quarantine
    flap_window_s: float = 120.0
    flap_threshold: int = 3
    quarantine_base_s: float = 30.0
    quarantine_cap_s: float = 600.0
    # -- retune loop
    retune_cooldown_s: float = 60.0
    prefill_shrink: float = 0.5
    prefill_floor: int = 32
    spec_acceptance_floor: float = 0.3
    spec_k_floor: int = 0
    queue_bound_min: int = 16
    queue_bound_step: float = 2.0
    # -- canary judge
    canary_observe_s: float = 10.0
    canary_rounds: int = 5
    canary_min_rounds: int = 3
    canary_regress_ratio: float = 1.2
    # -- predictive scale (ISSUE 20): when the router's longitudinal
    # history is armed, project the fleet TTFT p99 forward by the
    # regression slope over ``predictive_window_s`` of real buckets; a
    # projected breach of the objective within ``predictive_horizon_s``
    # (or a slow-window SLO burn at/over ``predictive_burn``) triggers
    # scale-up BEFORE the queue-depth threshold trips.
    # ``predictive_objective_ms`` 0.0 derives the objective from the
    # router's own TTFT SLO policies (the tightest one).  A disarmed
    # router (history=None) makes the whole path a no-op: the observe
    # payload and every decision stay byte-identical to PR 19.
    predictive_horizon_s: float = 10.0
    predictive_window_s: float = 10.0
    predictive_objective_ms: float = 0.0
    predictive_burn: float = 6.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})")
        if not (0.0 < self.prefill_shrink < 1.0):
            raise ValueError(
                f"prefill_shrink must be in (0, 1), got "
                f"{self.prefill_shrink}")
        if self.canary_rounds < 1 or self.canary_min_rounds < 1:
            raise ValueError("canary rounds must be >= 1")
        if self.flap_threshold < 2:
            raise ValueError(
                f"flap_threshold must be >= 2, got {self.flap_threshold}")
        if self.queue_bound_step <= 1.0:
            raise ValueError(
                f"queue_bound_step must be > 1, got "
                f"{self.queue_bound_step}")
        if self.predictive_horizon_s < 0 or self.predictive_window_s <= 0:
            raise ValueError(
                "predictive_horizon_s must be >= 0 and "
                "predictive_window_s > 0")
        if self.predictive_burn <= 0:
            raise ValueError(
                f"predictive_burn must be positive, got "
                f"{self.predictive_burn}")


class FleetAutopilot:
    """The fleet control loop.  Construct beside a
    :class:`~apex_tpu.serving.fleet.FleetRouter` and call :meth:`tick`
    from the same loop that pumps it::

        ap = FleetAutopilot(router, spawn=lambda name:
                            ReplicaProcess(spec, name))
        while serving:
            router.pump()
            ap.tick()

    ``spawn``: the scale actuator — ``None`` disables growing (the
    retune and quarantine loops still run).  ``attribution``: a
    zero-arg callable returning ``{"slowest_hop": ...}`` or ``None``
    (see :func:`trace_attribution`).  ``clock`` defaults to the
    router's injected clock, so one fake clock drives both
    deterministically.
    """

    def __init__(self, router, *, spawn: Optional[Callable] = None,
                 config: Optional[AutopilotConfig] = None,
                 attribution: Optional[Callable[[], Optional[dict]]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 registry=None):
        self.router = router
        self.spawn = spawn
        self.config = config if config is not None else AutopilotConfig()
        self.attribution = attribution
        self._clock = clock if clock is not None else router._clock
        self.registry = registry if registry is not None else \
            router.registry
        # arm the per-replica canary windows (the ONE router-side flag
        # that separates armed from disarmed)
        router.per_replica_slo = True
        self._ids = itertools.count(1)        # decision ids
        self._spawn_seq = itertools.count(1)  # auto-replica names
        self._last_scale_t: Optional[float] = None
        self._last_none_t: Optional[float] = None
        self._last_retune_t: Optional[float] = None
        self._joining: Dict[str, dict] = {}    # name -> {deadline, id}
        self._draining: Dict[str, dict] = {}   # name -> {deadline, id}
        self._downs: Dict[str, List[float]] = {}   # down-edge times
        self._was_down: Dict[str, bool] = {}
        self._quarantine: Dict[str, dict] = {}  # {until, backoff_s}
        self._canary: Optional[dict] = None     # in-flight observation
        # committed fleet-wide knob state (None = engine default); the
        # rollback payload for the NEXT canary of the same knob
        self.knobs: Dict[str, Any] = {}
        self._base_max_queue_depth = int(router.max_queue_depth)
        # bounded decision log (the determinism tests compare these)
        self.decisions: List[dict] = []

    # ------------------------------------------------------------ events

    def _count(self, name: str) -> None:
        self.registry.counter(f"fleet/autopilot/{name}").inc()

    def _emit(self, kind: str, decision_id: str, **fields) -> None:
        """One typed decision event: appended to the bounded local log
        (what tests compare) and emitted on the timeline with the trace
        plane's ids (what ``trace_report`` reconstructs)."""
        rec = {"kind": kind, "decision_id": decision_id,
               "t": round(self._clock(), 6)}
        rec.update(fields)
        self.decisions.append(rec)
        if len(self.decisions) > 512:
            del self.decisions[:len(self.decisions) - 512]
        timeline.emit(kind, decision_id=decision_id, **fields)

    def _decide(self, loop: str, action: str, reason: str,
                observe: dict, **fields) -> str:
        """Open a decision: observe + decide share one id; act/verdict
        follow under the same id."""
        did = f"ap{next(self._ids)}"
        self._emit("autopilot_observe", did, loop=loop, **observe)
        self._emit("autopilot_decide", did, loop=loop, action=action,
                   reason=reason, **fields)
        self._count("decisions")
        return did

    # -------------------------------------------------------------- tick

    def tick(self) -> None:
        """One control iteration — non-blocking decisions on the
        injected clock (the canary *observation* spans ticks; only the
        knob-broadcast ack is pump-waited, the swap_adapter
        discipline).  Safe to call at any cadence; a tick with nothing
        to do reads a few signals and returns."""
        now = self._clock()
        self._note_downs(now)
        self._pump_joining(now)
        self._pump_draining(now)
        if self._canary is not None:
            self._judge_canary(now)
            return        # one action in flight: observe, don't stack
        self._repair(now)
        if self._maybe_scale(now):
            return
        self._maybe_retune(now)

    # ----------------------------------------------------- flap tracking

    def _note_downs(self, now: float) -> None:
        """Down-edge detection per replica name; ``flap_threshold``
        edges inside ``flap_window_s`` quarantines the name under
        doubling (capped) back-off."""
        for name, view in list(self.router._views.items()):
            cur = bool(view.down) or not view.client.alive()
            if name in self._joining or name in self._draining \
                    or view.drained:
                # orchestrated exits are not flaps: a drain completing
                # is success, and a join dying is _pump_joining's one
                # reap-and-note (never double-counted here)
                self._was_down[name] = cur
                continue
            if cur and not self._was_down.get(name, False):
                self._note_flap(name, now,
                                reason=view.down_reason or "dead")
            self._was_down[name] = cur

    def _note_flap(self, name: str, now: float, *,
                   reason: str = "down") -> None:
        edges = self._downs.setdefault(name, [])
        edges.append(now)
        cutoff = now - self.config.flap_window_s
        while edges and edges[0] < cutoff:
            edges.pop(0)
        if len(edges) < self.config.flap_threshold:
            return
        prev = self._quarantine.get(name)
        backoff = min(self.config.quarantine_cap_s,
                      prev["backoff_s"] * 2.0 if prev is not None
                      else self.config.quarantine_base_s)
        self._quarantine[name] = {"until": now + backoff,
                                  "backoff_s": backoff}
        edges.clear()
        self._count("quarantines")
        did = self._decide(
            "scale", "quarantine",
            f"{self.config.flap_threshold} down-edges in "
            f"{self.config.flap_window_s:g}s (last: {reason})",
            {"replica": name,
             "flap_threshold": self.config.flap_threshold},
            replica=name)
        self._emit("autopilot_act", did, action="quarantine",
                   replica=name, backoff_s=backoff)
        self._emit("autopilot_verdict", did, verdict="quarantined",
                   replica=name, until=round(now + backoff, 6))

    def _quarantined(self, name: str, now: float) -> bool:
        q = self._quarantine.get(name)
        return q is not None and now < q["until"]

    # -------------------------------------------------- join/drain pumps

    def _pump_joining(self, now: float) -> None:
        """Confirm ready joins; reap half-born replicas (join timeout,
        or death before ready — the partition-during-scale-up row)."""
        for name, rec in list(self._joining.items()):
            view = self.router._views.get(name)
            if view is not None and view.ready and not view.down:
                del self._joining[name]
                self._emit("autopilot_verdict", rec["id"],
                           verdict="joined", replica=name)
                continue
            dead = (view is None or view.down
                    or not view.client.alive())
            if dead or now > rec["deadline"]:
                del self._joining[name]
                self.router.remove_replica(name)
                self._count("reaps")
                self._emit("autopilot_verdict", rec["id"],
                           verdict="reaped", replica=name,
                           reason=("died before ready" if dead
                                   else "join timeout"))
                # a join that keeps dying counts toward the flap
                # quarantine — the anti-hot-loop backstop
                self._note_flap(name, now, reason="died before ready")

    def _pump_draining(self, now: float) -> None:
        """Complete scale-downs: once the drain finishes (or times
        out), retire the replica from the routing table."""
        for name, rec in list(self._draining.items()):
            view = self.router._views.get(name)
            done = (view is None or view.down or view.drained
                    or not view.client.alive())
            if not done and now <= rec["deadline"]:
                continue
            del self._draining[name]
            self.router.remove_replica(name)
            self._emit("autopilot_verdict", rec["id"],
                       verdict=("drained" if done else "drain timeout"),
                       replica=name)

    # ------------------------------------------------------------- scale

    def _live_views(self) -> List:
        return [v for v in self.router._views.values()
                if not v.down and v.client.alive()]

    def _repair(self, now: float) -> None:
        """Min-pool repair: respawn dead replicas (same name — the
        routing table replaces the down holder) up to ``min_replicas``.
        Repair bypasses the scale cool-down (it restores promised
        capacity, it does not chase load) — the quarantine back-off is
        what bounds a flapping replica's respawn rate."""
        if self.spawn is None:
            return
        capacity = len(self._live_views()) + len(self._joining)
        if capacity >= self.config.min_replicas:
            return
        for name in sorted(self.router._views):
            if capacity >= self.config.min_replicas:
                break
            view = self.router._views[name]
            if not view.down or name in self._joining:
                continue
            if self._quarantined(name, now):
                continue
            did = self._decide(
                "scale", "respawn",
                f"live capacity {capacity} below min_replicas "
                f"{self.config.min_replicas}",
                {"live": capacity, "min_replicas":
                 self.config.min_replicas, "replica": name},
                replica=name)
            if self._spawn_into(name, did, now):
                capacity += 1
                self._count("respawns")

    def _spawn_into(self, name: str, decision_id: str,
                    now: float) -> bool:
        try:
            client = self.spawn(name)
        except Exception as e:  # noqa: BLE001 — verdict, not crash
            logger.warning("autopilot: spawn(%s) failed: %r", name, e)
            self._emit("autopilot_verdict", decision_id,
                       verdict="spawn failed", replica=name,
                       reason=repr(e))
            self._note_flap(name, now, reason=f"spawn failed: {e!r}")
            return False
        self.router.add_replica(client)
        self._was_down[name] = False
        self._joining[name] = {
            "deadline": now + self.config.join_timeout_s,
            "id": decision_id}
        self._emit("autopilot_act", decision_id, action="spawn",
                   replica=name)
        self._count("actions")
        return True

    def _predict(self, now: float):
        """Predictive scale signal off the router's longitudinal
        history (ISSUE 20): project the fleet TTFT p99 forward by its
        regression slope; a projected objective breach within the
        horizon — or a slow-window SLO burn over ``predictive_burn`` —
        is a scale-up trigger that fires BEFORE queue depth does.
        Returns ``(predictive, extra_observe)``; ``(False, None)`` when
        the history plane is disarmed, so the PR 19 observe payload and
        decision stream stay byte-identical."""
        cfg = self.config
        history = getattr(self.router, "history", None)
        if history is None or cfg.predictive_horizon_s <= 0:
            return False, None
        series = "fleet/ttft_ms:p99"
        slope = history.slope(series, cfg.predictive_window_s, now=now)
        last = history.latest(series)
        objective = cfg.predictive_objective_ms
        slo = getattr(self.router, "slo", None)
        if objective <= 0 and slo is not None:
            objs = [p.objective for p in slo.policies
                    if p.metric.startswith("fleet/ttft_ms")]
            if objs:
                objective = min(objs)
        burn = 0.0
        if slo is not None and slo.last_rows:
            burn = max(r["burn_slow"] for r in slo.last_rows)
        extra = {"history_slope_ms_per_s": round(slope, 4),
                 "history_p99_ms": (None if last is None
                                    else round(last, 3)),
                 "burn_slow": round(burn, 4)}
        breach = bool(
            last is not None and slope > 0 and objective > 0
            and last + slope * cfg.predictive_horizon_s >= objective)
        return breach or burn >= cfg.predictive_burn, extra

    def _maybe_scale(self, now: float) -> bool:
        """One load-driven scale action per cool-down window."""
        cfg = self.config
        if self._joining or self._draining:
            return False     # a membership change is already in flight
        if self._last_scale_t is not None and \
                now - self._last_scale_t < cfg.scale_cooldown_s:
            return False
        live = self._live_views()
        depth = self.router.total_queue_depth()
        trend = self.router.p99_trend("tpot_ms")
        observe = {"queue_depth": depth,
                   "p99_trend_ms_per_s": round(trend, 4),
                   "live": len(live)}
        predictive, pred_obs = self._predict(now)
        if pred_obs is not None:
            observe.update(pred_obs)
        deep = depth >= cfg.scale_up_queue_depth
        trending = trend >= cfg.scale_up_trend_ms_per_s
        if (deep or trending or predictive) and self.spawn is not None \
                and len(live) < cfg.max_replicas:
            if (trending or predictive) and not deep \
                    and any(v.link_degraded for v in live):
                # the slow-link row of the fault matrix: the tail
                # slope is the wire's, and placement already demotes
                # the degraded replica — more capacity would not move
                # the p99, so the explicit decision is "none"
                if self._last_none_t is None or \
                        now - self._last_none_t >= cfg.scale_cooldown_s:
                    self._last_none_t = now
                    did = self._decide(
                        "scale", "none",
                        "p99 trend explained by a degraded link "
                        "(demoted in placement, not scaled)",
                        dict(observe, link_degraded=[
                            v.name for v in live if v.link_degraded]))
                    self._emit("autopilot_verdict", did,
                               verdict="no action")
                return False
            name = f"auto{next(self._spawn_seq)}"
            while name in self.router._views:
                name = f"auto{next(self._spawn_seq)}"
            did = self._decide(
                "scale", "scale_up",
                ("queue depth over threshold" if deep
                 else "predicted p99 TTFT breach within horizon"
                 if predictive and not trending
                 else "p99 TPOT trending up"),
                observe, replica=name)
            if self._spawn_into(name, did, now):
                self._count("scale_up")
                self._last_scale_t = now
            return True
        if depth <= cfg.scale_down_queue_depth and trend <= 0.0 \
                and not predictive and len(live) > cfg.min_replicas:
            victim = self._pick_drain_victim(live)
            if victim is None:
                return False
            did = self._decide(
                "scale", "scale_down",
                "queue drained and tail flat; above min_replicas",
                observe, replica=victim.name)
            try:
                victim.client.begin_drain()
            except Exception as e:  # noqa: BLE001 — verdict, not crash
                self._emit("autopilot_verdict", did,
                           verdict="drain failed", replica=victim.name,
                           reason=repr(e))
                return True
            self._draining[victim.name] = {
                "deadline": now + cfg.drain_timeout_s, "id": did}
            self._emit("autopilot_act", did, action="drain",
                       replica=victim.name)
            self._count("actions")
            self._count("scale_down")
            self._last_scale_t = now
            return True
        return False

    def _pick_drain_victim(self, live: List):
        """Deterministic: the newest autopilot-spawned replica first
        (drain back what the burst grew), else the lexicographically
        last name."""
        def order(v):
            auto = v.name.startswith("auto")
            return (0 if auto else 1,
                    -int(v.name[4:]) if auto and v.name[4:].isdigit()
                    else 0, v.name)
        for v in sorted(live, key=order):
            return v
        return None

    # ------------------------------------------------------------ retune

    def _knob_base(self, key: str) -> Optional[int]:
        """Current effective value of an engine knob: the committed
        override if set, else the engine default read off the state
        heartbeats (the smallest across live replicas — conservative)."""
        if self.knobs.get(key) is not None:
            return int(self.knobs[key])
        default_key = {"prefill_chunk": "prefill_len",
                       "spec_k": "spec_k_max"}[key]
        vals = []
        for v in self._live_views():
            knobs = (v.state or {}).get("knobs") or {}
            if knobs.get(default_key) is not None:
                vals.append(int(knobs[default_key]))
        return min(vals) if vals else None

    def _min_spec_acceptance(self) -> Optional[float]:
        vals = [v.state["spec_acceptance"] for v in self._live_views()
                if v.state and v.state.get("spec_acceptance") is not None]
        return min(vals) if vals else None

    def _maybe_retune(self, now: float) -> None:
        cfg = self.config
        if self._last_retune_t is not None and \
                now - self._last_retune_t < cfg.retune_cooldown_s:
            return
        live = self._live_views()
        if not live:
            return
        attr = self.attribution() if self.attribution is not None \
            else None
        hop = (attr or {}).get("slowest_hop")
        # knob priority is fixed (deterministic): prefill attribution,
        # then acceptance sag, then the router's own queue
        if hop == "prefill":
            base = self._knob_base("prefill_chunk")
            if base is not None:
                target = max(cfg.prefill_floor,
                             int(base * cfg.prefill_shrink))
                if target < base:
                    self._start_knob_canary(
                        now, {"prefill_chunk": target},
                        {"prefill_chunk": self.knobs.get(
                            "prefill_chunk")},
                        reason=f"prefill dominates the tail "
                               f"(share {attr.get('share')})",
                        observe={"attribution": attr,
                                 "prefill_chunk": base})
                    return
        acc = self._min_spec_acceptance()
        if acc is not None and acc < cfg.spec_acceptance_floor:
            base = self._knob_base("spec_k")
            if base is not None and base > cfg.spec_k_floor:
                self._start_knob_canary(
                    now, {"spec_k": base - 1},
                    {"spec_k": self.knobs.get("spec_k")},
                    reason=f"spec acceptance {acc:.3f} below floor "
                           f"{cfg.spec_acceptance_floor:g}",
                    observe={"spec_acceptance": acc, "spec_k": base})
                return
        if hop == "router_queue":
            self._retune_queue_bound(now, attr)

    def _start_knob_canary(self, now: float, payload: dict,
                           rollback: dict, *, reason: str,
                           observe: dict) -> None:
        """Apply an engine-knob change to ONE replica and open the
        paired observation window."""
        cfg = self.config
        names = sorted(v.name for v in self._live_views())
        canary, controls = names[0], names[1:]
        did = self._decide("retune", "set_knobs", reason, observe,
                           payload=dict(payload), canary=canary)
        self._last_retune_t = now
        res = self.router.set_knobs(payload, names=[canary])
        ok, info = res.get(canary, (False, "replica down"))
        self._count("actions")
        self._count("retunes")
        self._emit("autopilot_act", did, action="set_knobs",
                   canary=canary, payload=dict(payload),
                   ok=bool(ok), info=repr(info) if not ok else None)
        if not ok:
            self._emit("autopilot_verdict", did, verdict="act failed",
                       canary=canary, reason=repr(info))
            return
        step = cfg.canary_observe_s / cfg.canary_rounds
        self._canary = {
            "id": did, "mode": "knob", "payload": dict(payload),
            "rollback": dict(rollback), "canary": canary,
            "controls": controls, "pairs": [], "next_round": 0,
            "round_ends": [now + step * (i + 1)
                           for i in range(cfg.canary_rounds)],
        }

    def _retune_queue_bound(self, now: float,
                            attr: Optional[dict]) -> None:
        """Tighten the router's shed bound when its own queue is the
        tail's slowest hop (shed earlier, protect admitted tails);
        judged before/after over the same canary window since the knob
        is router-local (no per-replica split exists)."""
        cfg = self.config
        cur = int(self.router.max_queue_depth)
        target = max(cfg.queue_bound_min, int(cur / cfg.queue_bound_step))
        if target >= cur:
            return
        did = self._decide(
            "retune", "queue_bound",
            "router_queue dominates the tail: tighten the shed bound",
            {"attribution": attr, "max_queue_depth": cur},
            payload={"max_queue_depth": target})
        self._last_retune_t = now
        self.router.max_queue_depth = target
        self._count("actions")
        self._count("retunes")
        self._emit("autopilot_act", did, action="queue_bound",
                   payload={"max_queue_depth": target})
        step = cfg.canary_observe_s / cfg.canary_rounds
        self._canary = {
            "id": did, "mode": "router",
            "payload": {"max_queue_depth": target},
            "rollback": {"max_queue_depth": cur},
            "baseline": self._fleet_p99(), "pairs": [],
            "next_round": 0,
            "round_ends": [now + step * (i + 1)
                           for i in range(cfg.canary_rounds)],
        }

    # ------------------------------------------------------------ canary

    def _replica_p99(self, name: str) -> Optional[float]:
        return self.router._slo_hist(
            f"fleet/replica/{name}/tpot_ms").percentile(99)

    def _fleet_p99(self) -> Optional[float]:
        hist = self.registry._histograms.get("fleet/tpot_ms")
        return hist.percentile(99) if hist is not None else None

    def _sample_pair(self, c: dict) -> Optional[tuple]:
        """One paired (treated, control) p99 sample, or None when
        either side has no window yet."""
        if c["mode"] == "knob":
            treated = self._replica_p99(c["canary"])
            ctrl = sorted(p for p in (self._replica_p99(n)
                                      for n in c["controls"])
                          if p is not None)
            control = ctrl[len(ctrl) // 2] if ctrl else None
        else:
            treated, control = self._fleet_p99(), c["baseline"]
        if treated is None or control is None:
            return None
        return (float(treated), float(control))

    def _rollback(self, c: dict) -> None:
        if c["mode"] == "knob":
            self.router.set_knobs(c["rollback"], names=[c["canary"]])
        else:
            self.router.max_queue_depth = \
                int(c["rollback"]["max_queue_depth"])

    def _judge_canary(self, now: float) -> None:
        """Advance the paired observation; at the window's end, the
        median of per-round (treated / control) p99 ratios is the
        verdict — the bench's paired median-of-ratios machinery run
        live."""
        c, cfg = self._canary, self.config
        if c["mode"] == "knob":
            view = self.router._views.get(c["canary"])
            if view is None or view.down or not view.client.alive():
                # canary host died mid-observation: the knob died with
                # it — verdict inconclusive, no rollback storm (failure
                # detection + repair own the host; the knob change was
                # never committed fleet-wide)
                self._canary = None
                self._count("inconclusive")
                self._emit("autopilot_verdict", c["id"],
                           verdict="inconclusive",
                           reason="canary host died mid-observation",
                           canary=c["canary"])
                return
        while c["next_round"] < len(c["round_ends"]) and \
                now >= c["round_ends"][c["next_round"]]:
            pair = self._sample_pair(c)
            if pair is not None:
                c["pairs"].append(pair)
            c["next_round"] += 1
        if now < c["round_ends"][-1]:
            return
        self._canary = None
        pairs = c["pairs"]
        if len(pairs) < cfg.canary_min_rounds:
            # not enough paired signal to judge: restore the canary
            # (it is alive — this is caution, not a regression verdict)
            self._rollback(c)
            self._count("inconclusive")
            self._emit("autopilot_verdict", c["id"],
                       verdict="inconclusive",
                       reason=f"only {len(pairs)} paired samples "
                              f"(need {cfg.canary_min_rounds})",
                       restored=True)
            return
        ratios = sorted(t / max(ctrl, 1e-9) for t, ctrl in pairs)
        ratio = ratios[len(ratios) // 2]
        if ratio > cfg.canary_regress_ratio:
            self._rollback(c)
            self._count("rollbacks")
            self._emit("autopilot_verdict", c["id"],
                       verdict="rollback",
                       ratio=round(ratio, 4), rounds=len(pairs),
                       payload=c["payload"], rolled_back=c["rollback"])
            return
        # healthy: commit fleet-wide
        if c["mode"] == "knob":
            rest = [n for n in sorted(
                v.name for v in self._live_views())
                if n != c["canary"]]
            if rest:
                self.router.set_knobs(c["payload"], names=rest)
        self.knobs.update(c["payload"])
        self._count("commits")
        self._emit("autopilot_verdict", c["id"], verdict="commit",
                   ratio=round(ratio, 4), rounds=len(pairs),
                   payload=c["payload"])

    # ----------------------------------------------------- introspection

    def introspect(self) -> dict:
        """Controller state for operators and tests — what is joining,
        draining, quarantined, committed, and under observation."""
        now = self._clock()
        return {
            "armed": True,
            "joining": sorted(self._joining),
            "draining": sorted(self._draining),
            "quarantined": {
                name: round(q["until"] - now, 3)
                for name, q in sorted(self._quarantine.items())
                if now < q["until"]},
            "knobs": dict(self.knobs),
            "canary": (None if self._canary is None else {
                "decision_id": self._canary["id"],
                "mode": self._canary["mode"],
                "payload": dict(self._canary["payload"]),
                "canary": self._canary.get("canary"),
                "pairs": len(self._canary["pairs"]),
            }),
            "decisions": len(self.decisions),
        }
