"""Fleet router: N engine replicas behind one SLO-aware front door.

"Millions of users" is not one engine (ROADMAP): a single
:class:`~apex_tpu.serving.engine.ServingEngine` is a single point of
failure with no overload policy beyond its FIFO, and it cannot take a
new checkpoint without going dark.  This module is the host-side router
over a fleet of :mod:`~apex_tpu.serving.replica` processes — the
serving half of the TorchTitan "production one-stop" bar (PAPERS.md
2410.06511), composed entirely from machinery this repo already
proved: PR 6's restore-anywhere, PR 8's SIGTERM drain, PR 9's
``introspect()``/debug-server state.

Three promises, all fault-injected (``scripts/fleet_smoke.sh``), never
asserted:

**Failover replay.**  A replica SIGKILLed mid-decode is detected by
dead pipe / missed heartbeat (with retry+backoff before the verdict),
marked down, and its in-flight requests are *replayed*: each one is
re-submitted to a surviving replica with ``prompt + tokens emitted so
far`` as the new prompt (through the ordinary packed-prefill path) and
the remaining token budget.  Greedy decode is a deterministic function
of the prefix, so the stitched stream is **bitwise identical** to an
uninterrupted reference — pinned at kill-at-token-k ∈ {0, 1, mid,
last} in ``tests/test_fleet.py`` and end-to-end in the smoke.

**Shed on overload.**  Once fleet-wide queue depth (router backlog +
every live replica's reported queue) crosses ``max_queue_depth``,
``submit`` returns a request in the typed ``REJECTED`` terminal state
and increments ``serving/requests_rejected`` — an observable refusal,
never a silent hang.  Below the bound, admission is SLO-aware: strict
priority classes, and weighted per-tenant fairness (stride scheduling)
within a class.

**Zero-downtime rollout.**  :meth:`FleetRouter.rollout` walks the
fleet one replica at a time: SIGTERM (the existing ``PreemptionGuard``
drain — in-flight requests deliver, queued ones come back to the
router and are rescheduled), clean exit, replacement spawned restoring
the newest VERIFIED checkpoint (corrupt-newest falls back — PR 6/8
machinery), rejoin on handshake — under continuous load, with every
request reaching a terminal state and p99 TPOT bounded (the smoke's
staggered-roll matrix).

ISSUE 13 satellites: **per-request sampling over the wire** (the PR 11
``SamplingParams`` engine API fleet-routed; failover replay rebases the
seeded draw counter by the emitted prefix, so a sampled stream is
stitched bitwise like a greedy one), **prefix-cache affinity** (a
tenant's requests prefer the replica whose ``PrefixCache`` plausibly
holds their template blocks — a placement tie-break read off the
``prefix_cache_hits``/``kv_occupancy`` state heartbeats, never
overriding free-blocks/queue-depth pressure), and the **streaming
client API** (:meth:`FleetRouter.stream` — an iterator over a
request's tokens as events arrive, closed by the terminal state).

The router is deliberately **jax-free and transport-agnostic**: it
drives anything with the replica client surface (``alive``/``poll``/
``submit``/``begin_drain``/``close`` — the duck type
:mod:`~apex_tpu.serving.transport` documents), which is how
``tests/test_fleet.py`` exercises every policy branch hermetically with
in-memory fakes, and how ISSUE 14 made the fleet cross-host: the framed
TCP :class:`~apex_tpu.serving.transport.SocketTransport` slots in where
``ReplicaProcess`` did and the router does not change.  Two
network-shaped policies ride on top:

**Graceful link degradation.**  A transport that reports a link RTT
(``link_rtt_s`` off the client, measured by ping/pong on the router
host's monotonic clock) past ``link_degraded_rtt_s`` is **demoted** in
placement — every healthy-link replica with capacity wins first — but
never hard-failed: its streams keep flowing, and it keeps serving if it
is all that's left (``fleet/link_degraded`` counts the transitions,
per-replica RTT rides ``introspect()``).

**Bounded-deadline shed when unreachable.**  When *no* replica is
dispatchable (all down/draining/rolling — the full-partition shape),
pending requests wait at most ``dispatch_deadline_s`` and are then shed
in the typed REJECTED terminal state: a fleet cut off from its replicas
degrades to observable refusals, never to an unbounded queue of silent
hangs.

``FleetRouter.introspect()`` duck-types the debug server's engine slot,
so ``DebugServer(engine=router)`` serves live fleet state at
``/statusz`` unchanged.

Metric catalog additions (host-local, ``docs/observability.md``):
``fleet/requests_submitted`` / ``fleet/requests_finished`` /
``serving/requests_rejected`` counters, ``fleet/replays`` /
``fleet/failovers`` / ``fleet/reschedules`` / ``fleet/rollouts``
counters, ``fleet/reconnects`` / ``fleet/frames_corrupt`` /
``fleet/link_degraded`` transport counters (ISSUE 14),
``fleet/replicas_live`` / ``fleet/queue_depth`` gauges,
``fleet/ttft_ms`` / ``fleet/tpot_ms`` histograms (router-observed).

ISSUE 15 — the observability plane over the fleet: with a flight
recorder armed (:mod:`~apex_tpu.observability.timeline`), ``submit``
mints a ``trace_id`` per request and every dispatch stamps
``{trace_id, attempt}`` onto the wire, so the router's hop events
(``fleet_submit`` / ``fleet_dispatch`` / ``fleet_replay`` /
``fleet_finish`` / ``fleet_reject``) and every replica's engine events
stitch into ONE per-request trace across processes
(:mod:`~apex_tpu.observability.trace`); the socket transport's clock
samples are spilled as ``link_clock`` events (cross-host mapping) and
fed to per-replica ``fleet/link_rtt_ms/<name>`` windowed histograms
(RTT tails next to the point value the demotion reads).  SLO
accounting rides the same registry: ``fleet/tenant/<t>/*`` and
``fleet/priority/<p>/*`` windowed ttft/tpot/queue-wait percentiles +
finished/rejected/replay counts, served merged (with replica
heartbeats and transport counters) by :meth:`FleetRouter.
fleet_statusz` → the debug server's ``/fleet/statusz``.  Unarmed,
all of it is a None check.

ISSUE 16 — disaggregated prefill/decode fleets.  A replica's
``ReplicaSpec.role`` rides its ready handshake; placement grows a role
axis beside prefix/adapter affinity: initial dispatch prefers
prefill-eligible replicas (``prefill``/``both``), and once a request
on a ``role="prefill"`` replica has its first token the router
migrates its paged KV to a decode-eligible replica — a streamed
per-block relay over the SAME session-layer frames both transports
already speak (``kv_meta`` → N×``kv_block`` → ``kv_export_done`` up
from the source; ``import_kv`` → N×``kv_block`` → ``import_commit``
down to the destination), so a reconnect resumes mid-migration at a
block boundary instead of restarting.  The handoff state machine is
failure-first: the source keeps the run PINNED until the router's
``kv_ack``, and EVERY fault — source death, destination death, torn
frame, import refusal, stream-completed-during-transfer — degrades to
the existing re-prefill/replay path (the request re-enters the pool
with its emitted prefix; bitwise identity holds by the same argument
as failover).  ``role="both"`` fleets never migrate: byte-for-byte
the PR 15 behavior.  Counters: ``fleet/kv_migrate_started`` /
``_completed`` / ``_failed`` / ``_blocks`` / ``_bytes`` +
``fleet/kv_migrate_ms`` windowed histogram; per-role SLO splits and
migration backlog ride :meth:`FleetRouter.fleet_statusz`; the
``fleet_migrate_start`` hop event opens the trace plane's
``kv_migrate`` bucket (closed by the dispatch-onto-decode).

ISSUE 17 — batched multi-LoRA over the fleet.  ``adapter_id`` rides
``SamplingParams`` (data on the existing wire — both transports,
failover replay and preemption readmit carry it for free; replays of an
adapter-tagged request redraw the identical stream by the same
step-offset rebase argument).  :meth:`load_adapter` broadcasts an
adapter's weights to every live replica and pump-waits the
``adapter_loaded`` acks; :meth:`swap_adapter` is the zero-downtime
hot-swap — the rollout's one-replica-at-a-time discipline (``rolling``
dispatch gate, quiesce in-flight pinners, in-place slot overwrite, no
process replacement, no recompile).  Placement grows an
adapter-affinity tie-break beside prefix affinity (a replica whose
heartbeat reports the adapter resident wins ties, standing down past
the same occupancy cap), and the SLO plane grows a per-adapter axis:
``fleet/adapter/<id>/ttft_ms|tpot_ms`` windowed percentiles +
finished/rejected counts in :meth:`fleet_statusz`, plus
``fleet/adapter_loads`` / ``fleet/adapter_swaps`` counters.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.observability import timeline
from apex_tpu.serving.sampling import SamplingParams
from apex_tpu.serving.scheduler import RequestState

__all__ = ["FleetRequest", "FleetRouter"]

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class FleetRequest:
    """One request's fleet-level state (the router's source of truth;
    replica-side Request objects are per-attempt and disposable)."""

    rid: int
    prompt: np.ndarray            # int32 [prompt_len] — the ORIGINAL
    max_new_tokens: int
    eos_id: Optional[int] = None
    tenant: str = "default"
    priority: int = 0             # lower = more urgent (class 0 first)
    sampling: Optional[SamplingParams] = None   # None = greedy

    state: RequestState = RequestState.WAITING
    output_tokens: List[int] = dataclasses.field(default_factory=list)
    replica: Optional[str] = None   # current / last assignment
    replays: int = 0                # failover re-submissions
    reschedules: int = 0            # drain-cancel / reject re-routes
    t_submit: float = 0.0
    t_first_token: Optional[float] = None
    t_last_token: Optional[float] = None
    # distributed tracing (ISSUE 15): the fleet-wide trace id minted at
    # submit when a flight recorder is armed (None otherwise — tracing
    # unarmed is a None check end to end), and how many times dispatch
    # has seated this request (the hop stamp's attempt number: attempt
    # k > 1 means a failover replay / drain reschedule re-dispatch)
    trace_id: Optional[str] = None
    dispatches: int = 0
    # set at migration commit (ISSUE 16): the next inter-token gap
    # spans the handoff (already accounted in fleet/kv_migrate_ms), so
    # the per-ROLE pool-health TPOT skips it once — tenant-facing TPOT
    # keeps the gap (the stall is real user-visible latency)
    migrated_gap: bool = False
    # bounded SLO accounting keys, resolved ONCE at submit (the token
    # path is the router's hottest loop — it must not re-derive them
    # per token): (tenant_key, priority_key, adapter_key-or-None),
    # "(other)" past the cap.  The adapter key (ISSUE 17) comes off
    # ``sampling.adapter_id``; None — the bare-engine majority — costs
    # nothing on the token path (every adapter site is a None check).
    slo_keys: tuple = ("default", "0", None)

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.REJECTED)

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.output_tokens)


class _ReplicaView:
    """Router-side bookkeeping for one replica client."""

    def __init__(self, client, now: float):
        self.client = client
        # a client whose handshake was already consumed out-of-band
        # (ReplicaProcess.wait_ready before router construction) is
        # ready on arrival; otherwise the ("ready", meta) event flips it
        self.meta: Optional[dict] = getattr(client, "meta", None)
        self.ready = self.meta is not None
        self.state: Optional[dict] = None   # last introspect snapshot
        self.last_event_t = now             # any event counts as a beat
        self.down = False
        self.down_reason: Optional[str] = None
        self.draining = False
        self.drained = False
        self.rolling = False                # excluded from dispatch
        self.probes = 0                     # missed-heartbeat probes so far
        self.next_probe_t: Optional[float] = None
        self.assigned: Dict[int, FleetRequest] = {}
        # transport link state (ISSUE 14): last-synced client counters
        # and the degradation verdict placement demotes on
        self.tx_reconnects = 0
        self.tx_frames_corrupt = 0
        self.link_rtt_s: Optional[float] = None
        self.link_degraded = False
        # mp-relay batching mirror (ISSUE 15 satellite)
        self.tx_relay_batches = 0
        self.tx_relay_events = 0

    @property
    def name(self) -> str:
        return self.client.name

    @property
    def role(self) -> str:
        """Fleet role from the ready handshake (ISSUE 16); a transport
        that does not say (pre-16 daemons, hermetic fakes) reads as
        ``"both"`` — the never-migrates default."""
        return (self.meta or {}).get("role") or "both"

    def dispatchable(self) -> bool:
        return (self.ready and not self.down and not self.draining
                and not self.rolling and self.client.alive())

    def in_flight(self) -> int:
        """Replica-side load: everything queued or decoding there.  The
        replica's own snapshot (queue + active slots) and the router's
        ``assigned`` map are two views of the same population offset by
        transport lag — take their max, never their sum (summing
        double-counts every request between dispatch and the next state
        heartbeat, which halves the effective limits and over-sheds)."""
        reported = 0
        if self.state is not None:
            reported = (int(self.state.get("queue_depth", 0))
                        + int(self.state.get("active_slots", 0)))
        return max(reported, len(self.assigned))

    def backlog(self) -> int:
        """Replica-side *waiting* load only — what the shed bound sums.
        A full-but-flowing fleet (every slot decoding, nothing queued)
        has zero backlog and must not shed; :meth:`in_flight` is the
        placement ceiling, this is the overload signal.  Same max-not-
        sum rule: dispatched-but-unreported requests (no first token
        yet) are the router's view of the same queue the replica
        reports."""
        reported = 0
        if self.state is not None:
            reported = int(self.state.get("queue_depth", 0))
        local = sum(1 for r in self.assigned.values()
                    if r.t_first_token is None)
        return max(reported, local)


class FleetRouter:
    """Admit, place, replay, and roll requests across engine replicas.

    ``replicas``: clients with the replica surface (see module
    docstring).  ``max_queue_depth``: the fleet-wide shed bound.
    ``replica_queue_limit``: per-replica dispatch ceiling (backlog past
    it stays in the router, where it can still be re-routed).
    ``heartbeat_timeout_s`` / ``probe_retries`` / ``probe_backoff_s``:
    failure detection — a silent replica is probed ``probe_retries``
    times, ``probe_backoff_s`` apart, before the down verdict (a dead
    pipe / dead process short-circuits the probes).  ``clock`` is
    injectable so the detection ladder is deterministic under test.

    Drive with :meth:`pump` (one poll+detect+dispatch iteration) from
    whatever loop owns the host thread; nothing here blocks.
    """

    def __init__(self, replicas: Sequence, *, max_queue_depth: int = 64,
                 replica_queue_limit: int = 4,
                 heartbeat_timeout_s: float = 10.0,
                 probe_retries: int = 3, probe_backoff_s: float = 0.2,
                 max_attempts: int = 8, keep_done: int = 4096,
                 affinity_occupancy_cap: float = 0.95,
                 link_degraded_rtt_s: float = 1.0,
                 dispatch_deadline_s: float = 120.0,
                 slo_key_cap: int = 64,
                 migrate_min_remaining: int = 2,
                 migrate_max_inflight: int = 16,
                 trend_window_s: float = 1.0, trend_windows: int = 8,
                 history_every_s: float = 0.0,
                 history_max_series: int = 512,
                 slo_policies=None,
                 registry=None, clock: Callable[[], float] = time.monotonic):
        from apex_tpu.observability.metrics import default_registry

        self._clock = clock
        self.max_queue_depth = max_queue_depth
        self.replica_queue_limit = replica_queue_limit
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.probe_retries = probe_retries
        self.probe_backoff_s = probe_backoff_s
        # link RTT past this demotes the replica in placement (never a
        # hard failure); transports that report no RTT are exempt
        self.link_degraded_rtt_s = link_degraded_rtt_s
        # the all-replicas-unreachable bound: pending requests wait at
        # most this long with zero dispatchable replicas before the
        # typed REJECTED shed (a partitioned fleet refuses observably)
        self.dispatch_deadline_s = dispatch_deadline_s
        self._no_dispatch_since: Optional[float] = None
        # a request the fleet keeps bouncing (replica-level rejects,
        # drain cancels, failover replays) is parked REJECTED after
        # this many re-routes — a poison request (e.g. one no replica's
        # pool shape can serve) must reach a terminal state, not
        # ping-pong forever
        self.max_attempts = max_attempts
        self.keep_done = keep_done
        self.registry = registry if registry is not None else \
            default_registry()
        now = clock()
        self._views: Dict[str, _ReplicaView] = {}
        for client in replicas:
            if client.name in self._views:
                # a silent overwrite would leak the first client's
                # process (never polled, never closed) — the PR 7
                # duplicate-dp_ranks precedent: validate, don't collapse
                raise ValueError(
                    f"duplicate replica name {client.name!r}")
            self._views[client.name] = _ReplicaView(client, now)
        self._ids = itertools.count()
        self.requests: Dict[int, FleetRequest] = {}
        # terminal rids in completion order; beyond keep_done the oldest
        # are evicted from `requests` (callers keep their FleetRequest
        # handles — eviction only bounds the router's own maps, so a
        # weeks-long router does not grow per-request state forever)
        self._done_ring: collections.deque = collections.deque()
        # pending[(priority, tenant)] -> deque of WAITING requests;
        # replays go to the LEFT (they already waited their turn)
        self._pending: Dict[tuple, collections.deque] = {}
        self._tenant_pass: Dict[str, float] = {}
        self._tenant_weight: Dict[str, float] = {}
        # prefix-cache affinity: tenant -> the replica that last served
        # it (whose PrefixCache plausibly holds the tenant's template
        # blocks); a placement tie-break, gated on the replica's
        # heartbeat-reported kv_occupancy staying under the cap.
        # LRU-bounded (insertion order + refresh-on-write): tenants are
        # caller-supplied strings, and an unbounded map would grow
        # forever under unique-tenant-per-request traffic — losing an
        # affinity hint costs one cold prefill, never correctness
        self.affinity_occupancy_cap = affinity_occupancy_cap
        self._tenant_affinity: Dict[str, str] = {}
        self._tenant_affinity_cap = 4096
        # SLO accounting (ISSUE 15): the tenant / priority-class keys
        # ever seen, so /fleet/statusz can enumerate its per-key
        # windowed histograms and counters without walking the
        # registry.  Tenants are caller-supplied strings, so the key
        # space is CAPPED: past slo_key_cap distinct keys, new arrivals
        # account under the "(other)" overflow bucket — a client
        # stamping a unique tenant per request must not grow router
        # memory (3 windowed histograms + counters per key) or scrape
        # size without bound.
        self.slo_key_cap = slo_key_cap
        self._slo_tenants: set = set()
        self._slo_priorities: set = set()
        # per-adapter SLO keys (ISSUE 17): same bounded-cap discipline —
        # adapter ids are caller-supplied strings too
        self._slo_adapters: set = set()
        # adapter broadcast acks: (replica_name, adapter_id) ->
        # (ok, info), filled by the adapter_loaded/_unloaded events the
        # load_adapter/swap_adapter pump-waits consume
        self._adapter_acks: Dict[tuple, tuple] = {}
        # KV migration (ISSUE 16): rid -> handoff record.  A request on
        # a role="prefill" replica becomes a migration candidate once
        # it has a first token AND at least migrate_min_remaining
        # budget left (a stream about to finish is cheaper to let
        # finish in place than to ship).  migrate_max_inflight bounds
        # concurrent handoffs so a prefill flood cannot turn the
        # router into an unbounded block relay.
        # record: {"src", "dst", "phase": "export"|"transfer"|"commit"
        #          |"aborted", "meta", "n_sent", "t_start"}
        self.migrate_min_remaining = int(migrate_min_remaining)
        self.migrate_max_inflight = int(migrate_max_inflight)
        self._migrations: Dict[int, dict] = {}
        # controller-readable p99-trend (ISSUE 18 satellite): every
        # trend_window_s (on the injected clock) the pump snapshots the
        # fleet TTFT/TPOT p99 into a bounded window; the least-squares
        # slope over the last trend_windows snapshots is the "is the
        # tail getting worse" signal — first-class on introspect() /
        # fleet_statusz so the autopilot and external probes read the
        # SAME number instead of each diffing histogram scrapes.
        self.trend_window_s = float(trend_window_s)
        self.trend_windows = int(trend_windows)
        self._trend: Dict[str, collections.deque] = {
            "ttft_ms": collections.deque(maxlen=self.trend_windows),
            "tpot_ms": collections.deque(maxlen=self.trend_windows)}
        self._trend_last_t = now
        # per-replica SLO windows exist only while a FleetAutopilot is
        # attached (it flips this on) — the canary judge needs paired
        # per-replica p99s, but a disarmed fleet must observe NOTHING
        # extra (the acceptance criterion: disarmed == the PR 17 fleet)
        self.per_replica_slo = False
        # live-retune broadcast acks (ISSUE 18): the adapter-ack
        # discipline applied to set_knobs — (replica_name, token) ->
        # (ok, info), filled by knobs_set events, consumed by the
        # set_knobs pump-wait; tokens come from a deterministic counter
        # so knob rounds are reproducible under injected clocks
        self._knob_acks: Dict[tuple, tuple] = {}
        self._knob_tokens = itertools.count(1)
        # longitudinal history + SLO burn-rate plane (ISSUE 20): armed
        # by history_every_s > 0, the pump snapshots the registry into
        # a fixed-memory MetricHistory on that cadence, merges the
        # compacted deltas replicas ship on their state heartbeats, and
        # (when policies are given) evaluates multi-window burn rates
        # into slo_burn_alert/slo_burn_clear timeline events.  DISARMED
        # (the default) every touch point below is a single None check:
        # the PR 19 fleet, byte for byte.
        self.history_every_s = float(history_every_s)
        if self.history_every_s > 0:
            from apex_tpu.observability.slo import SLOEvaluator
            from apex_tpu.observability.timeseries import MetricHistory

            self.history = MetricHistory(
                self.registry, clock=clock,
                max_series=history_max_series,
                on_overflow=lambda: self.registry.counter(
                    "fleet/series_overflow").inc())
            self.slo = SLOEvaluator(self.history, slo_policies or (),
                                    clock=clock) \
                if slo_policies else None
            self._history_last_t: Optional[float] = None
        else:
            if slo_policies:
                raise ValueError(
                    "slo_policies need the history armed: pass "
                    "history_every_s > 0")
            self.history = None
            self.slo = None

    # ----------------------------------------------------------- tenants

    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        """Weighted fairness within a priority class: a tenant with
        weight w gets ~w shares per round of dispatch (stride
        scheduling — the pass/stride virtual clock)."""
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        self._tenant_weight[tenant] = float(weight)

    def _see_tenant(self, tenant: str) -> None:
        """Pin a tenant's virtual clock at first sight (the current
        minimum): a late arrival starts level with the pack, neither
        owed the whole history nor forever trailing it."""
        if tenant not in self._tenant_pass:
            self._tenant_pass[tenant] = min(
                self._tenant_pass.values(), default=0.0)

    def _charge(self, tenant: str) -> None:
        self._see_tenant(tenant)
        self._tenant_pass[tenant] += \
            1.0 / self._tenant_weight.get(tenant, 1.0)

    # ------------------------------------------------------------ submit

    def total_queue_depth(self) -> int:
        """Fleet-wide backlog: router pending + every non-down
        replica's *waiting* queue.  Deliberately excludes requests
        already decoding — a fully-utilized fleet with empty queues is
        healthy, not overloaded, and must not shed."""
        depth = sum(len(q) for q in self._pending.values())
        for view in self._views.values():
            if not view.down:
                depth += view.backlog()
        return depth

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None, *, tenant: str = "default",
               priority: int = 0,
               sampling: Optional[SamplingParams] = None) -> FleetRequest:
        """Admit or shed.  Above ``max_queue_depth`` the request comes
        back REJECTED — a typed terminal state the caller can observe
        and retry against, never a silent hang — and
        ``serving/requests_rejected`` counts it.

        ``sampling`` rides the replica wire per request (the PR 11
        engine API, fleet-routed).  Failover replay stays stream-exact:
        the engine keys draw i at ``seed, step_offset + i``, and every
        dispatch rebases ``step_offset`` by the emitted prefix it
        re-prefills — a survivor continues the SAME stochastic stream
        the dead replica was emitting."""
        req = FleetRequest(
            rid=next(self._ids),
            prompt=np.asarray(prompt, np.int32),
            max_new_tokens=int(max_new_tokens), eos_id=eos_id,
            tenant=tenant, priority=int(priority), sampling=sampling,
            t_submit=time.monotonic())
        self.requests[req.rid] = req
        self._slo_keys(req)
        self.registry.counter("fleet/requests_submitted").inc()
        if timeline.active() is not None:
            # trace context minted HERE (the request's first hop is the
            # router); unarmed routers mint nothing — the free-telemetry
            # None-check discipline applied to tracing
            req.trace_id = uuid.uuid4().hex[:16]
            timeline.emit("fleet_submit", rid=req.rid,
                          trace_id=req.trace_id, tenant=req.tenant,
                          priority=req.priority,
                          prompt_tokens=int(req.prompt.size),
                          max_new_tokens=req.max_new_tokens)
        if self.total_queue_depth() >= self.max_queue_depth:
            self._reject(req)
            return req
        self._enqueue(req)
        return req

    def _slo_hist(self, name: str):
        return self.registry.histogram(name, keep_samples=4096)

    def _slo_key(self, keys: set, key) -> str:
        """Bounded SLO accounting key: a known key passes through, a
        new one registers while the cap holds, and everything past the
        cap lands in the "(other)" overflow bucket (metrics stay
        bounded however many distinct tenants callers invent)."""
        key = str(key)
        if key in keys:
            return key
        if len(keys) >= self.slo_key_cap:
            # the overflow is itself observable (ISSUE 20): a fleet
            # whose tenant cardinality blew the cap should say so
            self.registry.counter("fleet/series_overflow").inc()
            keys.add("(other)")
            return "(other)"
        keys.add(key)
        return key

    def _slo_keys(self, req: FleetRequest) -> tuple:
        """Resolve (and cache on the request) its bounded accounting
        keys — called once at submit; every later site reads the
        cached triple."""
        aid = getattr(req.sampling, "adapter_id", None) \
            if req.sampling is not None else None
        req.slo_keys = (
            self._slo_key(self._slo_tenants, req.tenant),
            self._slo_key(self._slo_priorities, req.priority),
            (self._slo_key(self._slo_adapters, aid)
             if aid is not None else None))
        return req.slo_keys

    def _reject(self, req: FleetRequest) -> None:
        req.state = RequestState.REJECTED
        self.registry.counter("serving/requests_rejected").inc()
        tkey, pkey, akey = req.slo_keys
        self.registry.counter(f"fleet/tenant/{tkey}/rejected").inc()
        self.registry.counter(f"fleet/priority/{pkey}/rejected").inc()
        if akey is not None:
            self.registry.counter(f"fleet/adapter/{akey}/rejected").inc()
        if req.trace_id is not None:
            timeline.emit("fleet_reject", rid=req.rid,
                          trace_id=req.trace_id)
        self._note_done(req)

    def _note_done(self, req: FleetRequest) -> None:
        """Bound the per-request maps: remember terminal rids in order
        and evict the oldest past ``keep_done`` (the caller's own
        FleetRequest handle stays valid — only the router forgets)."""
        self._done_ring.append(req.rid)
        while len(self._done_ring) > self.keep_done:
            self.requests.pop(self._done_ring.popleft(), None)

    def _enqueue(self, req: FleetRequest, *, front: bool = False) -> None:
        req.state = RequestState.WAITING
        req.replica = None
        self._see_tenant(req.tenant)
        q = self._pending.setdefault((req.priority, req.tenant),
                                     collections.deque())
        if front:
            q.appendleft(req)
        else:
            q.append(req)

    # -------------------------------------------------------------- pump

    def pump(self) -> None:
        """One router iteration: poll events, run failure detection,
        dispatch what fits.  Non-blocking; call it from the serving
        host's loop (the smoke pumps at ~1 kHz)."""
        for view in list(self._views.values()):
            if not view.down:
                self._poll_view(view)
        for view in list(self._views.values()):
            if not view.down:
                self._detect_failure(view)
        self._dispatch()
        self._pump_migrations()
        live = sum(1 for v in self._views.values()
                   if not v.down and v.client.alive())
        self.registry.gauge("fleet/replicas_live").set(live)
        self.registry.gauge("fleet/queue_depth").set(
            self.total_queue_depth())
        self._update_trend()
        if self.history is not None:
            self._pump_history()

    def _pump_history(self) -> None:
        """One history snapshot + SLO evaluation per elapsed cadence
        window (injected clock) — armed fleets only."""
        now = self._clock()
        if self._history_last_t is not None \
                and now - self._history_last_t < self.history_every_s:
            return
        self._history_last_t = now
        self.history.sample(now)
        if self.slo is not None:
            self.slo.evaluate(now)

    def _update_trend(self) -> None:
        """One p99 snapshot per elapsed trend window (injected clock)."""
        now = self._clock()
        if now - self._trend_last_t < self.trend_window_s:
            return
        self._trend_last_t = now
        for metric in ("ttft_ms", "tpot_ms"):
            # read-only peek: never CREATE the histogram (an idle
            # fleet's registry must stay byte-identical to a router
            # without trend windows)
            hist = self.registry._histograms.get(f"fleet/{metric}")
            p99 = hist.percentile(99) if hist is not None else None
            if p99 is not None:
                self._trend[metric].append((now, float(p99)))

    def p99_trend(self, metric: str = "tpot_ms") -> float:
        """Slope of the windowed p99 in ms per second — least-squares
        over the last ``trend_windows`` (t, p99) snapshots; 0.0 until
        two windows exist.  Positive = the tail is getting worse."""
        pts = self._trend.get(metric)
        if pts is None or len(pts) < 2:
            return 0.0
        n = len(pts)
        mt = sum(t for t, _ in pts) / n
        mv = sum(v for _, v in pts) / n
        denom = sum((t - mt) ** 2 for t, _ in pts)
        if denom <= 0.0:
            return 0.0
        return sum((t - mt) * (v - mv) for t, v in pts) / denom

    # ------------------------------------------------------------- events

    def _sync_link(self, view: _ReplicaView) -> None:
        """Mirror the transport's link counters into the registry and
        refresh the degradation verdict.  Duck-typed: transports
        without the attributes (mp queues, hermetic fakes) read as
        healthy links.  Runs even when poll raised — a poll that died
        ON a corrupt frame already counted it client-side."""
        client = view.client
        rec = int(getattr(client, "reconnects", 0) or 0)
        if rec > view.tx_reconnects:
            self.registry.counter("fleet/reconnects").inc(
                rec - view.tx_reconnects)
            view.tx_reconnects = rec
        corrupt = int(getattr(client, "frames_corrupt", 0) or 0)
        if corrupt > view.tx_frames_corrupt:
            self.registry.counter("fleet/frames_corrupt").inc(
                corrupt - view.tx_frames_corrupt)
            view.tx_frames_corrupt = corrupt
        # batched mp-relay mirror (ISSUE 15 satellite): how many events
        # crossed in batches vs one-per-put — the in-proc leg of the
        # wire_vs_inproc story, now visible
        batches = int(getattr(client, "relay_batches", 0) or 0)
        if batches > view.tx_relay_batches:
            self.registry.counter("fleet/relay_batch").inc(
                batches - view.tx_relay_batches)
            view.tx_relay_batches = batches
        revents = int(getattr(client, "relay_batched_events", 0) or 0)
        if revents > view.tx_relay_events:
            self.registry.counter("fleet/relay_batch_events").inc(
                revents - view.tx_relay_events)
            view.tx_relay_events = revents
        # link RTT: every round trip becomes a sample in the per-replica
        # windowed histogram (ISSUE 15 satellite — the point gauge kept
        # no tails, so link_degraded_rtt_s was judged against one
        # number), and each sample's clock-offset estimate is spilled as
        # a link_clock event for cross-host trace stitching
        take = getattr(client, "take_rtt_samples", None)
        if take is not None:
            for rtt_s, offset_s, remote_mono in take():
                self._slo_hist(
                    f"fleet/link_rtt_ms/{view.name}").observe(
                        rtt_s * 1e3)
                timeline.emit("link_clock", replica=view.name,
                              rtt_s=round(rtt_s, 6),
                              offset_s=round(offset_s, 6),
                              remote_mono=round(remote_mono, 6))
        rtt = getattr(client, "link_rtt_s", None)
        view.link_rtt_s = rtt
        degraded = rtt is not None and rtt > self.link_degraded_rtt_s
        if degraded and not view.link_degraded:
            self.registry.counter("fleet/link_degraded").inc()
            logger.warning(
                "fleet: replica %s link degraded (rtt %.3fs > %.3fs); "
                "demoting in placement", view.name, rtt,
                self.link_degraded_rtt_s)
        view.link_degraded = degraded

    def _poll_view(self, view: _ReplicaView) -> None:
        try:
            events = view.client.poll()
        except Exception as e:  # dead pipe mid-read
            self._sync_link(view)
            logger.warning("fleet: replica %s poll failed: %r",
                           view.name, e)
            self._mark_down(view, f"dead pipe: {e!r}")
            return
        self._sync_link(view)
        if events:
            view.last_event_t = self._clock()
            view.probes = 0
            view.next_probe_t = None
        for ev in events:
            self._handle_event(view, ev)

    def _handle_event(self, view: _ReplicaView, ev: tuple) -> None:
        kind = ev[0]
        if kind == "ready":
            view.ready = True
            view.meta = ev[1]
        elif kind == "state":
            # a history-armed replica (ISSUE 20) attaches its compacted
            # delta to the ordinary heartbeat — popped here so the raw
            # buckets never sit in view.state, merged only when this
            # router keeps a history of its own (prefixed per replica,
            # bucket stamps rebased onto the router clock at ingest)
            delta = ev[1].pop("history", None)
            view.state = ev[1]
            view.draining = bool(ev[1].get("draining"))
            if delta and self.history is not None:
                self.history.ingest_delta(
                    delta, prefix=f"replica/{view.name}/")
        elif kind == "token":
            _, frid, token = ev
            req = self.requests.get(frid)
            if req is None or req.done:
                return
            now = time.monotonic()
            tkey, pkey, akey = req.slo_keys
            if req.t_first_token is None:
                req.t_first_token = now
                ttft_ms = (now - req.t_submit) * 1e3
                self.registry.histogram(
                    "fleet/ttft_ms", keep_samples=4096).observe(ttft_ms)
                # per-tenant / per-priority SLO windows (ISSUE 15): the
                # same router-observed latency, keyed so /fleet/statusz
                # can answer "whose p99 blew up" instead of "the fleet's"
                self._slo_hist(
                    f"fleet/tenant/{tkey}/ttft_ms").observe(ttft_ms)
                self._slo_hist(
                    f"fleet/priority/{pkey}/ttft_ms").observe(ttft_ms)
                # per-role SLO split (ISSUE 16): the same latency keyed
                # by the EMITTING replica's role, so /fleet/statusz can
                # answer "is the decode pool's p99 clean" directly
                self._slo_hist(
                    f"fleet/role/{view.role}/ttft_ms").observe(ttft_ms)
                if akey is not None:
                    # per-adapter SLO window (ISSUE 17): whose tenant-
                    # model's p99 blew up, not just whose tenant's
                    self._slo_hist(
                        f"fleet/adapter/{akey}/ttft_ms").observe(ttft_ms)
            else:
                tpot_ms = (now - req.t_last_token) * 1e3
                self.registry.histogram(
                    "fleet/tpot_ms", keep_samples=65536).observe(tpot_ms)
                self._slo_hist(
                    f"fleet/tenant/{tkey}/tpot_ms").observe(tpot_ms)
                self._slo_hist(
                    f"fleet/priority/{pkey}/tpot_ms").observe(tpot_ms)
                if akey is not None:
                    self._slo_hist(
                        f"fleet/adapter/{akey}/tpot_ms").observe(tpot_ms)
                if req.migrated_gap:
                    # the gap spanning the handoff is kv_migrate cost,
                    # not the decode pool's steady-state TPOT
                    req.migrated_gap = False
                else:
                    self._slo_hist(
                        f"fleet/role/{view.role}/tpot_ms").observe(
                        tpot_ms)
                if self.per_replica_slo:
                    # canary judging (ISSUE 18): per-replica TPOT
                    # windows exist only while an autopilot is attached
                    self._slo_hist(
                        f"fleet/replica/{view.name}/tpot_ms").observe(
                        tpot_ms)
            req.t_last_token = now
            req.output_tokens.append(int(token))
        elif kind == "finished":
            req = self.requests.get(ev[1])
            if req is not None and not req.done:
                self._finish(req, view)
        elif kind in ("cancelled", "rejected"):
            # cancelled: drained out of the replica's queue (rollout /
            # preemption); rejected: refused at the replica door (drain
            # window race, or a pool-shape mismatch).  Either way a
            # fleet-level request is NOT lost — it goes back in the
            # pool for another replica, until the attempt cap parks it
            req = self.requests.get(ev[1])
            view.assigned.pop(ev[1], None)
            if req is not None and not req.done:
                req.reschedules += 1
                self.registry.counter("fleet/reschedules").inc()
                self._requeue_or_park(
                    req, f"replica {view.name} {kind}",
                    replica=view.name)
        elif kind == "drained":
            view.drained = True
            view.draining = True
        elif kind in ("adapter_loaded", "adapter_unloaded"):
            # (ISSUE 17) broadcast/hot-swap verdict: recorded for the
            # load_adapter/swap_adapter pump-waits; failures are loud
            # (a replica that cannot host the adapter would REJECT every
            # request routed there naming it)
            _, aid, ok, info = ev
            self._adapter_acks[(view.name, aid)] = (bool(ok), info)
            if kind == "adapter_loaded":
                self.registry.counter("fleet/adapter_loads").inc()
            if not ok:
                logger.warning("fleet: replica %s %s %r failed: %r",
                               view.name, kind, aid, info)
        elif kind == "knobs_set":
            # (ISSUE 18) live-retune verdict: recorded for the
            # set_knobs pump-wait (the adapter-ack discipline); a
            # refused payload is loud — the autopilot's canary treats
            # a failed ack as a failed action, never a silent no-op
            _, token, ok, info = ev
            self._knob_acks[(view.name, token)] = (bool(ok), info)
            if not ok:
                logger.warning("fleet: replica %s set_knobs failed: %r",
                               view.name, info)
        elif kind in ("kv_meta", "kv_block", "kv_export_done",
                      "kv_export_failed", "kv_imported"):
            self._handle_migration_event(view, ev)
        elif kind == "error":
            logger.warning("fleet: replica %s relayed error: %r",
                           view.name, ev[1])
            self._mark_down(view, f"relayed error: {ev[1]!r}")

    def _finish(self, req: FleetRequest, view: Optional[_ReplicaView],
                ) -> None:
        req.state = RequestState.FINISHED
        if view is not None:
            view.assigned.pop(req.rid, None)
        self.registry.counter("fleet/requests_finished").inc()
        tkey, pkey, akey = req.slo_keys
        self.registry.counter(f"fleet/tenant/{tkey}/finished").inc()
        self.registry.counter(f"fleet/priority/{pkey}/finished").inc()
        if akey is not None:
            self.registry.counter(f"fleet/adapter/{akey}/finished").inc()
        if req.trace_id is not None:
            timeline.emit("fleet_finish", rid=req.rid,
                          trace_id=req.trace_id,
                          tokens=len(req.output_tokens))
        self._note_done(req)

    def _requeue_or_park(self, req: FleetRequest, why: str, *,
                         replica: Optional[str] = None) -> None:
        """Put a bounced request back in the pool — unless it has burnt
        ``max_attempts`` re-routes, in which case it is parked in the
        typed REJECTED terminal state (a poison request every replica
        refuses must converge, not livelock the dispatch loop)."""
        if req.replays + req.reschedules >= self.max_attempts:
            logger.warning(
                "fleet: request %d exhausted %d attempts (%s); parking "
                "it REJECTED", req.rid, self.max_attempts, why)
            self._reject(req)
            return
        if req.trace_id is not None:
            # the trace walk's failover_replay boundary: from the dead
            # replica's last flushed event up to the NEXT fleet_dispatch
            # is replay cost, not decode (observability/trace.py)
            timeline.emit("fleet_replay", rid=req.rid,
                          trace_id=req.trace_id, replica=replica,
                          reason=why)
        self._enqueue(req, front=True)

    # ------------------------------------------------- failure detection

    def _detect_failure(self, view: _ReplicaView) -> None:
        if not view.client.alive():
            # dead process: consume any events that flushed before
            # death (tokens generated pre-kill are real), then verdict
            self._poll_view(view)
            if view.down:
                return
            if view.drained and not view.assigned:
                # clean rollout exit, not a failure: retire quietly
                self._mark_down(view, "drained and exited", clean=True)
            else:
                self._mark_down(view, "process died")
            return
        if not view.ready:
            return  # startup (engine compile) is wait_ready's business
        silent_for = self._clock() - view.last_event_t
        if silent_for <= self.heartbeat_timeout_s:
            return
        # missed heartbeat: probe with backoff before the down verdict
        # (a GC pause or one slow decode step must not trigger a replay
        # storm — the retry ladder is the difference between failover
        # and flapping)
        now = self._clock()
        if view.next_probe_t is None:
            view.next_probe_t = now + self.probe_backoff_s
            return
        if now < view.next_probe_t:
            return
        view.probes += 1
        view.next_probe_t = now + self.probe_backoff_s
        logger.warning(
            "fleet: replica %s silent for %.2fs (probe %d/%d)",
            view.name, silent_for, view.probes, self.probe_retries)
        if view.probes >= self.probe_retries:
            self._mark_down(
                view, f"missed heartbeat for {silent_for:.2f}s "
                f"after {view.probes} probes")

    def _mark_down(self, view: _ReplicaView, reason: str,
                   *, clean: bool = False) -> None:
        view.down = True
        view.down_reason = reason
        if not clean:
            logger.warning("fleet: replica %s DOWN (%s); replaying %d "
                           "in-flight request(s)", view.name, reason,
                           len(view.assigned))
            self.registry.counter("fleet/failovers").inc()
        self._abort_migrations_for(view)
        self._replay(view)

    def _replay(self, view: _ReplicaView) -> None:
        """Failover replay: every request the dead replica held goes
        back in the pool with its emitted prefix intact; dispatch
        re-submits ``prompt + prefix`` with the remaining budget."""
        # reverse rid order + appendleft == oldest request ends up at
        # the very front: replays keep their original relative order
        for frid, req in sorted(view.assigned.items(), reverse=True):
            if req.done:
                continue
            if self._stream_complete(req):
                self._finish(req, None)
                continue
            req.replays += 1
            self.registry.counter("fleet/replays").inc()
            self.registry.counter(
                f"fleet/tenant/{req.slo_keys[0]}/replays").inc()
            self._requeue_or_park(req, f"replica {view.name} down",
                                  replica=view.name)
        view.assigned.clear()

    def _context_limits(self) -> tuple:
        """Smallest ``(max_seq, prefill_len)`` any known replica
        advertised in its ready handshake — ``(None, None)`` when the
        transport does not say (hermetic fakes need not)."""
        max_seq = prefill = None
        for v in self._views.values():
            m = v.meta or {}
            if m.get("max_seq") is not None:
                max_seq = (m["max_seq"] if max_seq is None
                           else min(max_seq, m["max_seq"]))
            if m.get("prefill_len") is not None:
                prefill = (m["prefill_len"] if prefill is None
                           else min(prefill, m["prefill_len"]))
        return max_seq, prefill

    def _stream_complete(self, req: FleetRequest) -> bool:
        """True when the stream needs no more decoding and only the
        ``finished`` event was lost to the kill: budget exhausted, eos
        emitted, or the engine's third finish condition — the context
        cap.  A stream at ``max_seq`` was FINISHED by the engine
        ("truncation is a response"); and a replay prefix that no
        longer fits a packed prefill row on any replica cannot be
        continued anywhere — deliver the truncated stream instead of
        bouncing the request into REJECTED."""
        if req.remaining <= 0:
            return True
        if (req.eos_id is not None and req.output_tokens
                and req.output_tokens[-1] == req.eos_id):
            return True
        max_seq, prefill = self._context_limits()
        wire = len(req.prompt) + len(req.output_tokens)
        if max_seq is not None and wire >= max_seq:
            return True
        if prefill is not None and wire > prefill:
            return True
        return False

    # ----------------------------------------------------------- dispatch

    def _pick_tenant(self, priority: int) -> Optional[tuple]:
        keys = [k for k, q in self._pending.items()
                if k[0] == priority and q]
        if not keys:
            return None
        return min(keys, key=lambda k: (
            self._tenant_pass.get(k[1], 0.0), k[1]))

    def _pick_replica(self, tenant: Optional[str] = None,
                      adapter_id: Optional[str] = None
                      ) -> Optional[_ReplicaView]:
        candidates = [v for v in self._views.values()
                      if v.dispatchable()
                      and v.in_flight() < self.replica_queue_limit]
        if not candidates:
            return None
        # Role axis (ISSUE 16): initial dispatch is the admission +
        # chunked-prefill phase, so prefill-eligible replicas
        # ("prefill"/"both") win it; decode specialists take requests
        # through KV migration instead.  Graceful degradation over
        # starvation: when every candidate is a decode specialist, use
        # them anyway — a "decode" replica is a full engine and
        # prefills correctly, just not at its best placement.
        prefill_ok = [v for v in candidates if v.role != "decode"]
        if prefill_ok:
            candidates = prefill_ok
        # Prefix-cache affinity (ISSUE 13 satellite): the replica that
        # last served this tenant plausibly still holds the tenant's
        # template blocks in its PrefixCache, so landing there turns
        # the prefill into block shares (`serving/prefix_cache_hits`
        # climbing on the state heartbeats is the visible effect).
        # Strictly a TIE-BREAK: free blocks and queue depth dominate,
        # and a replica whose reported kv_occupancy is past the cap is
        # under pool pressure — affinity yields rather than force
        # evictions of hotter blocks.
        warm = self._tenant_affinity.get(tenant)

        def score(v: _ReplicaView):
            state = v.state or {}
            free = int(state.get("free_blocks", 0))
            occ = float(state.get("kv_occupancy") or 0.0)
            affine = (v.name == warm
                      and occ < self.affinity_occupancy_cap)
            # Adapter affinity (ISSUE 17): a replica whose heartbeat
            # says the request's adapter is already RESIDENT wins ties
            # — landing there costs zero adapter loads/evictions, while
            # a cold replica would churn its arena.  Same discipline as
            # prefix affinity: a tie-break only (free blocks and queue
            # depth dominate), standing down past the same occupancy
            # cap so affinity never forces an overloaded pool.
            resident = (state.get("adapters_resident") or ())
            adapter_affine = (adapter_id is not None
                              and adapter_id in resident
                              and occ < self.affinity_occupancy_cap)
            # link degradation leads the key (ISSUE 14): a slow link is
            # DEMOTED — any healthy-link candidate wins regardless of
            # pool shape — but never excluded, so a fleet whose every
            # link degraded still serves instead of starving
            return (1 if v.link_degraded else 0, -free,
                    len(v.assigned), 0 if affine else 1,
                    0 if adapter_affine else 1, v.name)

        return min(candidates, key=score)

    def _dispatch(self) -> None:
        # Selection stays per-request (priority, stride fairness and
        # placement all update as each request is seated), but the
        # transport writes are BATCHED: everything routed to one
        # replica this pump goes out as a single ``submit_many``
        # command when the client supports it — at fleet arrival rates
        # the per-command queue/pickle overhead was the router's
        # dominant cost (ROADMAP fleet follow-on).
        batches: Dict[str, tuple] = {}   # name -> (view, [items])
        while True:
            priorities = sorted({k[0] for k, q in self._pending.items()
                                 if q})
            if not priorities:
                break
            key = self._pick_tenant(priorities[0])
            if key is None:
                break
            # peek the queue head's adapter (ISSUE 17) so placement can
            # prefer a replica already holding it resident — the head
            # is exactly the request popped below
            head = self._pending[key][0]
            head_aid = getattr(head.sampling, "adapter_id", None) \
                if head.sampling is not None else None
            view = self._pick_replica(key[1], head_aid)
            if view is None:
                break  # no capacity anywhere: stays in the router pool
            req = self._pending[key].popleft()
            if req.done:
                continue
            self._charge(req.tenant)
            # replay prefix: the engine prefills prompt+emitted tokens
            # through the ordinary chunked-prefill admission path —
            # recovery needs no special-case decode state
            wire_prompt = list(map(int, req.prompt)) + req.output_tokens
            # the replayed prefix consumed draw counters 0..len(emitted)
            # on the dead replica; rebase the survivor's counter so the
            # sampled stream CONTINUES instead of restarting
            sampling = req.sampling
            if sampling is not None and req.output_tokens:
                sampling = dataclasses.replace(
                    sampling, step_offset=sampling.step_offset
                    + len(req.output_tokens))
            req.state = RequestState.RUNNING
            req.replica = view.name
            req.dispatches += 1
            view.assigned[req.rid] = req
            self._tenant_affinity.pop(req.tenant, None)   # refresh LRU
            self._tenant_affinity[req.tenant] = view.name
            if len(self._tenant_affinity) > self._tenant_affinity_cap:
                self._tenant_affinity.pop(
                    next(iter(self._tenant_affinity)))
            if req.dispatches == 1 and req.t_first_token is None:
                # router-side queue wait, observed once per request
                wait_ms = (time.monotonic() - req.t_submit) * 1e3
                tkey, pkey = req.slo_keys[:2]
                self._slo_hist(
                    f"fleet/tenant/{tkey}/queue_wait_ms").observe(
                        wait_ms)
                self._slo_hist(
                    f"fleet/priority/{pkey}/queue_wait_ms").observe(
                        wait_ms)
            trace = None
            if req.trace_id is not None:
                # the hop stamp: replica + attempt ride the wire so the
                # replica-side events of a re-dispatched request are
                # distinguishable from its first incarnation's
                trace = {"trace_id": req.trace_id,
                         "attempt": req.dispatches}
                timeline.emit("fleet_dispatch", rid=req.rid,
                              trace_id=req.trace_id,
                              attempt=req.dispatches,
                              replica=view.name,
                              prior_tokens=len(req.output_tokens))
            batches.setdefault(view.name, (view, []))[1].append(
                (req.rid, wire_prompt, req.remaining, req.eos_id,
                 sampling, trace))
        for view, items in batches.values():
            try:
                if len(items) > 1 and hasattr(view.client, "submit_many"):
                    view.client.submit_many(items)
                    self.registry.counter("fleet/batched_submits").inc()
                else:
                    for item in items:
                        view.client.submit(*item)
            except Exception as e:  # dead pipe on write
                logger.warning("fleet: submit to %s failed: %r",
                               view.name, e)
                self._mark_down(view, f"dead pipe on submit: {e!r}")
        self._shed_if_unreachable()

    def _shed_if_unreachable(self) -> None:
        """Graceful degradation when the whole fleet is out of reach
        (every replica down/draining/rolling — the full-partition
        shape): pending requests wait a bounded ``dispatch_deadline_s``
        from the moment the last replica became undispatchable, then
        shed in the typed REJECTED terminal state.  Any replica coming
        back (probe reset, rollout rejoin) resets the window."""
        pending = sum(len(q) for q in self._pending.values())
        if pending == 0 or any(v.dispatchable()
                               for v in self._views.values()):
            self._no_dispatch_since = None
            return
        now = self._clock()
        if self._no_dispatch_since is None:
            self._no_dispatch_since = now
            return
        if now - self._no_dispatch_since <= self.dispatch_deadline_s:
            return
        logger.warning(
            "fleet: no replica dispatchable for %.1fs; shedding %d "
            "pending request(s) REJECTED", now - self._no_dispatch_since,
            pending)
        for q in self._pending.values():
            while q:
                req = q.popleft()
                if not req.done:
                    self._reject(req)
        self._no_dispatch_since = None

    # ------------------------------------------------- KV migration (16)

    def _view_if_up(self, name: Optional[str]) -> Optional[_ReplicaView]:
        view = self._views.get(name) if name is not None else None
        if view is None or view.down or not view.client.alive():
            return None
        return view

    def _pick_migration_dst(self, src: _ReplicaView
                            ) -> Optional[_ReplicaView]:
        """A decode-eligible landing replica: decode specialists first
        (the whole point of the split), ``both`` as fallback, never the
        source, never past the per-replica ceiling."""
        candidates = [v for v in self._views.values()
                      if v is not src and v.dispatchable()
                      and v.role != "prefill"
                      and v.in_flight() < self.replica_queue_limit]
        if not candidates:
            return None

        def score(v: _ReplicaView):
            state = v.state or {}
            return (1 if v.link_degraded else 0,
                    0 if v.role == "decode" else 1,
                    -int(state.get("free_blocks", 0)),
                    len(v.assigned), v.name)

        return min(candidates, key=score)

    def _pump_migrations(self) -> None:
        """The handoff trigger: any first-tokened request sitting on a
        ``role="prefill"`` replica with enough budget left ships its KV
        to a decode replica.  One ``export_kv`` command starts it; the
        rest of the state machine runs on the source's event stream
        (:meth:`_handle_migration_event`)."""
        if len(self._migrations) >= self.migrate_max_inflight:
            return
        for view in list(self._views.values()):
            if view.role != "prefill" or not view.dispatchable():
                continue
            for rid, req in list(view.assigned.items()):
                if len(self._migrations) >= self.migrate_max_inflight:
                    return
                if (rid in self._migrations or req.done
                        or req.t_first_token is None
                        or not req.output_tokens
                        or req.remaining < self.migrate_min_remaining
                        or self._stream_complete(req)):
                    continue
                dst = self._pick_migration_dst(view)
                if dst is None:
                    return      # nowhere to land; keep decoding here
                try:
                    view.client.export_kv(rid)
                except Exception as e:
                    logger.warning(
                        "fleet: export_kv to %s failed: %r",
                        view.name, e)
                    self._mark_down(
                        view, f"dead pipe on export_kv: {e!r}")
                    return
                self._migrations[rid] = {
                    "src": view.name, "dst": dst.name,
                    "phase": "export", "meta": None, "n_sent": 0,
                    "t_start": time.monotonic()}
                self.registry.counter("fleet/kv_migrate_started").inc()
                if req.trace_id is not None:
                    # opens the trace plane's kv_migrate hop; the
                    # dispatch-onto-decode at commit closes it
                    timeline.emit("fleet_migrate_start", rid=rid,
                                  trace_id=req.trace_id,
                                  attempt=req.dispatches,
                                  src=view.name, dst=dst.name,
                                  prior_tokens=len(req.output_tokens))

    def _resolve_migration(self, rid: int, rec: dict, why: str, *,
                           requeue: bool = True) -> None:
        """Common failure epilogue: un-pin the source (``kv_ack`` False
        — the exported run still indexes into its prefix cache, so the
        re-prefill that follows is usually a block-share, not a
        recompute), drop any pending destination import, and put the
        request back in the pool.  The degraded path IS the proven
        replay path — token identity needs no new argument."""
        self._migrations.pop(rid, None)
        self.registry.counter("fleet/kv_migrate_failed").inc()
        src = self._view_if_up(rec["src"])
        dst = self._view_if_up(rec["dst"])
        if dst is not None:
            try:
                dst.client.kv_abort(rid)
            except Exception:       # dying pipe: poll() will verdict it
                pass
        if src is not None:
            try:
                src.client.kv_ack(rid, False)
            except Exception:
                pass
        req = self.requests.get(rid)
        if req is None or req.done:
            return
        if not requeue:
            return                  # still decoding on the source
        for name in (rec["src"], rec["dst"]):
            v = self._views.get(name)
            if v is not None:       # down views too: a later _replay
                v.assigned.pop(rid, None)   # must not double-enqueue
        if self._stream_complete(req):
            self._finish(req, None)
            return
        logger.warning("fleet: KV migration of request %d failed (%s); "
                       "degrading to re-prefill", rid, why)
        self._requeue_or_park(req, f"kv migration failed: {why}",
                              replica=rec["src"])

    def _abort_migrations_for(self, view: _ReplicaView) -> None:
        """A replica going down mid-handoff (either side).  Source
        down: the request is still in its ``assigned`` map, so the
        ordinary :meth:`_replay` that follows covers it — only the
        destination's pending import needs dropping.  Destination
        down: the source's export may still be streaming, so the
        record flips to "aborted" and the source's own
        ``kv_export_done`` resolves it (its events are swallowed in
        between); a handoff already past export resolves immediately."""
        for rid, rec in list(self._migrations.items()):
            if rec["src"] == view.name:
                if rec["phase"] == "commit":
                    # the commit already raced toward the decode
                    # replica — it may be admitted there any moment, so
                    # it must NOT also replay (double execution).  Move
                    # it optimistically; the kv_imported verdict (or
                    # the destination's own death) resolves the handoff
                    req = self.requests.get(rid)
                    dst = self._view_if_up(rec["dst"])
                    view.assigned.pop(rid, None)
                    if req is not None and not req.done \
                            and dst is not None:
                        req.replica = dst.name
                        dst.assigned[rid] = req
                    else:
                        self._resolve_migration(
                            rid, rec, "source died at commit")
                    continue
                self._migrations.pop(rid, None)
                self.registry.counter("fleet/kv_migrate_failed").inc()
                dst = self._view_if_up(rec["dst"])
                if dst is not None:
                    try:
                        dst.client.kv_abort(rid)
                    except Exception:
                        pass
            elif rec["dst"] == view.name:
                if rec["phase"] in ("export", "transfer"):
                    rec["phase"] = "aborted"
                else:
                    self._resolve_migration(
                        rid, rec, f"decode replica {view.name} died")

    def _handle_migration_event(self, view: _ReplicaView,
                                ev: tuple) -> None:
        kind, rid = ev[0], ev[1]
        rec = self._migrations.get(rid)
        if rec is None:
            return      # stale event of an already-resolved handoff
        req = self.requests.get(rid)
        if kind == "kv_export_failed" and view.name == rec["src"]:
            # nothing left the source engine — the request just keeps
            # decoding there; only the destination's pending import
            # (if the meta ever went out) needs dropping
            self._resolve_migration(rid, rec, str(ev[2]), requeue=False)
        elif kind == "kv_meta" and view.name == rec["src"]:
            if rec["phase"] == "aborted":
                return
            rec["meta"] = ev[2]
            rec["phase"] = "transfer"
            if req is None or req.done or \
                    int(ev[2].get("n_out", -1)) != len(req.output_tokens):
                # token stream and export are out of phase — never
                # commit a cache that disagrees with the stream
                self._resolve_migration(
                    rid, rec, "token/export phase mismatch")
                return
            dst = self._view_if_up(rec["dst"])
            if dst is None:
                self._resolve_migration(rid, rec, "destination gone")
                return
            try:
                dst.client.import_kv(rid, ev[2])
            except Exception as e:
                self._resolve_migration(rid, rec, f"import_kv: {e!r}")
        elif kind == "kv_block" and view.name == rec["src"]:
            if rec["phase"] != "transfer":
                return      # aborted mid-stream: swallow the tail
            payload = ev[3]
            rec["n_sent"] += 1
            self.registry.counter("fleet/kv_migrate_blocks").inc()
            self.registry.counter("fleet/kv_migrate_bytes").inc(
                int(sum(getattr(s, "nbytes", 0) for s in payload)))
            dst = self._view_if_up(rec["dst"])
            if dst is None:
                self._resolve_migration(rid, rec, "destination gone")
                return
            try:
                dst.client.kv_block(rid, int(ev[2]), payload)
            except Exception as e:
                self._resolve_migration(rid, rec, f"kv_block: {e!r}")
        elif kind == "kv_export_done" and view.name == rec["src"]:
            if rec["phase"] == "aborted":
                # the destination died while the source streamed; the
                # export is complete source-side, so resolve NOW (the
                # late un-pin path of the refcount story)
                self._resolve_migration(
                    rid, rec, "destination died mid-transfer")
                return
            n = int(ev[2])
            meta = rec["meta"] or {}
            if rec["phase"] != "transfer" or rec["n_sent"] != n or \
                    int(meta.get("n_blocks", -1)) != n:
                self._resolve_migration(rid, rec, "block count mismatch")
                return
            if req is None or req.done or self._stream_complete(req):
                # the stream finished while its KV was in flight —
                # deliver it, don't bounce it through an import that
                # would refuse a zero budget
                self._resolve_migration(rid, rec, "stream complete")
                return
            dst = self._view_if_up(rec["dst"])
            if dst is None:
                self._resolve_migration(rid, rec, "destination gone")
                return
            # the commit is a dispatch onto the decode replica: same
            # wire item as failover replay (full stream as the prompt,
            # remaining budget, step_offset rebased by the emitted
            # prefix) — the imported KV just makes the re-prefill a
            # one-token recompute instead of a full one
            sampling = req.sampling
            if sampling is not None and req.output_tokens:
                sampling = dataclasses.replace(
                    sampling, step_offset=sampling.step_offset
                    + len(req.output_tokens))
            wire_prompt = list(map(int, req.prompt)) + req.output_tokens
            req.dispatches += 1
            trace = None
            if req.trace_id is not None:
                trace = {"trace_id": req.trace_id,
                         "attempt": req.dispatches}
                timeline.emit("fleet_dispatch", rid=rid,
                              trace_id=req.trace_id,
                              attempt=req.dispatches,
                              replica=dst.name, migrated=True,
                              prior_tokens=len(req.output_tokens))
            item = (rid, wire_prompt, req.remaining, req.eos_id,
                    sampling, trace)
            try:
                dst.client.import_commit(rid, item, n)
            except Exception as e:
                self._resolve_migration(rid, rec, f"import_commit: {e!r}")
                return
            rec["phase"] = "commit"
        elif kind == "kv_imported" and view.name == rec["dst"]:
            ok, why = bool(ev[2]), ev[3]
            if not ok or req is None or req.done:
                self._resolve_migration(
                    rid, rec, f"import refused: {why}")
                return
            # handoff complete: the request now lives on the decode
            # replica; the source un-pins into its prefix cache
            self._migrations.pop(rid, None)
            raw_src = self._views.get(rec["src"])
            if raw_src is not None:
                # pop from the raw view even when it is down — a source
                # that died AFTER flushing its export completes the
                # handoff, and a stale assigned entry here would make
                # the death-time _replay double-execute the request
                raw_src.assigned.pop(rid, None)
            src = self._view_if_up(rec["src"])
            if src is not None:
                try:
                    src.client.kv_ack(rid, True)
                except Exception:
                    pass
            req.replica = view.name
            req.migrated_gap = True
            view.assigned[rid] = req
            self.registry.counter("fleet/kv_migrate_completed").inc()
            self._slo_hist("fleet/kv_migrate_ms").observe(
                (time.monotonic() - rec["t_start"]) * 1e3)

    # ------------------------------------------------------------ rollout

    def rollout(self, factory: Callable[[str], object], *,
                names: Optional[Sequence[str]] = None,
                drain_timeout_s: float = 120.0,
                ready_timeout_s: float = 300.0,
                poll_s: float = 0.002,
                on_tick: Optional[Callable[[], None]] = None) -> List[str]:
        """Zero-downtime weight rollout, one replica at a time.

        For each name: SIGTERM-drain (in-flight requests deliver on the
        old weights, queued ones reschedule onto the rest of the
        fleet), wait for the clean exit, spawn ``factory(name)`` (which
        restores the newest VERIFIED checkpoint), wait for its ready
        handshake, rejoin.  The router keeps pumping throughout —
        ``on_tick`` (called every iteration) is where a load generator
        keeps traffic flowing so the smoke can prove the fleet never
        went dark.  Returns the rolled replica names.

        A replica that dies mid-drain is handled by the ordinary
        failover path (its remaining requests replay) and is still
        replaced — a rollout must converge even through a crash.
        """
        rolled = []
        for name in list(names if names is not None else self._views):
            view = self._views[name]
            self.registry.counter("fleet/rollouts").inc()
            view.rolling = True
            view.client.begin_drain()
            # deadlines run on the injected clock (one control-flow
            # clock domain with failure detection — the timeout paths
            # are drivable in deterministic tests)
            deadline = self._clock() + drain_timeout_s
            while not view.down:
                self.pump()
                if on_tick is not None:
                    on_tick()
                if view.drained and not view.client.alive():
                    break
                if self._clock() > deadline:
                    logger.warning(
                        "fleet: %s did not drain in %.0fs; escalating",
                        name, drain_timeout_s)
                    self._mark_down(view, "drain timeout")
                    break
                time.sleep(poll_s)
            # retire the old client (reap the exited process) and seat
            # the replacement under the same name
            try:
                view.client.close()
            except Exception as e:
                logger.warning("fleet: closing old %s failed: %r",
                               name, e)
            if not view.down:
                self._mark_down(view, "rolled out", clean=True)
            new_client = factory(name)
            if new_client.name != name:
                raise ValueError(
                    f"rollout factory returned client named "
                    f"{new_client.name!r} for slot {name!r}")
            new_view = _ReplicaView(new_client, self._clock())
            self._views[name] = new_view
            deadline = self._clock() + ready_timeout_s
            while not new_view.ready:
                self.pump()
                if on_tick is not None:
                    on_tick()
                if not new_client.alive() and not new_view.ready:
                    raise RuntimeError(
                        f"fleet: replacement replica {name} died before "
                        f"ready (exitcode "
                        f"{getattr(new_client, 'exitcode', None)})")
                if self._clock() > deadline:
                    raise RuntimeError(
                        f"fleet: replacement replica {name} not ready "
                        f"in {ready_timeout_s:.0f}s")
                time.sleep(poll_s)
            rolled.append(name)
        return rolled

    # ------------------------------------------------- adapters (ISSUE 17)

    def _await_acks(self, acks: Dict[tuple, tuple],
                    pairs: Sequence[tuple], *,
                    timeout_s: float, poll_s: float = 0.002,
                    on_tick: Optional[Callable[[], None]] = None
                    ) -> Dict[str, tuple]:
        """Pump until every ``(replica_name, key)`` pair has an ack in
        ``acks`` (or the deadline passes); a replica that dies mid-wait
        reads as a failed ack, never a hang.  Shared by the adapter
        broadcasts (ISSUE 17) and the live-retune broadcast (ISSUE 18)."""
        deadline = self._clock() + timeout_s
        while any(p not in acks for p in pairs):
            self.pump()
            if on_tick is not None:
                on_tick()
            if all(self._view_if_up(p[0]) is None or
                   p in acks for p in pairs):
                break
            if self._clock() > deadline:
                break
            time.sleep(poll_s)
        out = {}
        for name, key in pairs:
            out[name] = acks.pop(
                (name, key), (False, "no ack (replica down or timeout)"))
        return out

    def _await_adapter_acks(self, pairs: Sequence[tuple], *,
                            timeout_s: float, poll_s: float = 0.002,
                            on_tick: Optional[Callable[[], None]] = None
                            ) -> Dict[str, tuple]:
        return self._await_acks(self._adapter_acks, pairs,
                                timeout_s=timeout_s, poll_s=poll_s,
                                on_tick=on_tick)

    def load_adapter(self, adapter_id, *, weights=None, seed=None,
                     names: Optional[Sequence[str]] = None,
                     timeout_s: float = 60.0,
                     on_tick: Optional[Callable[[], None]] = None
                     ) -> Dict[str, tuple]:
        """Register (or hot-swap) a LoRA adapter across the fleet: the
        ``load_adapter`` wire command broadcast to every live replica
        (or ``names``), then a pump-wait on the ``adapter_loaded``
        acks.  Returns ``{replica_name: (ok, info)}`` — ``info`` is
        ``{"slot", "evicted"}`` on success, the repr'd refusal
        otherwise.  Failover replay depends on this being a broadcast:
        an adapter-tagged request can only replay onto a survivor that
        has the adapter resident."""
        payload: dict = {}
        if weights is not None:
            payload["weights"] = weights
        if seed is not None:
            payload["seed"] = seed
        results: Dict[str, tuple] = {}
        pairs = []
        for name in list(names if names is not None else self._views):
            view = self._view_if_up(name)
            if view is None:
                results[name] = (False, "replica down")
                continue
            send = getattr(view.client, "load_adapter", None)
            if send is None:
                results[name] = (False, "transport has no load_adapter")
                continue
            try:
                send(adapter_id, payload)
            except Exception as e:    # dead pipe on write
                logger.warning("fleet: load_adapter to %s failed: %r",
                               name, e)
                self._mark_down(view, f"dead pipe on load_adapter: {e!r}")
                results[name] = (False, repr(e))
                continue
            pairs.append((name, adapter_id))
        results.update(self._await_adapter_acks(
            pairs, timeout_s=timeout_s, on_tick=on_tick))
        return results

    def unload_adapter(self, adapter_id, *,
                       names: Optional[Sequence[str]] = None,
                       timeout_s: float = 60.0,
                       on_tick: Optional[Callable[[], None]] = None
                       ) -> Dict[str, tuple]:
        """Drop an adapter's registry reference fleet-wide: new submits
        naming it are REJECTED at every replica door; in-flight pinners
        finish on the weights they started with (slot frees on last
        unpin — the engine's refcount contract)."""
        results: Dict[str, tuple] = {}
        pairs = []
        for name in list(names if names is not None else self._views):
            view = self._view_if_up(name)
            if view is None:
                results[name] = (False, "replica down")
                continue
            send = getattr(view.client, "unload_adapter", None)
            if send is None:
                results[name] = (False,
                                 "transport has no unload_adapter")
                continue
            try:
                send(adapter_id)
            except Exception as e:
                logger.warning("fleet: unload_adapter to %s failed: %r",
                               name, e)
                self._mark_down(view,
                                f"dead pipe on unload_adapter: {e!r}")
                results[name] = (False, repr(e))
                continue
            pairs.append((name, adapter_id))
        results.update(self._await_adapter_acks(
            pairs, timeout_s=timeout_s, on_tick=on_tick))
        return results

    def swap_adapter(self, adapter_id, *, weights=None, seed=None,
                     names: Optional[Sequence[str]] = None,
                     quiesce_timeout_s: float = 120.0,
                     ack_timeout_s: float = 60.0, poll_s: float = 0.002,
                     on_tick: Optional[Callable[[], None]] = None
                     ) -> Dict[str, tuple]:
        """Zero-downtime adapter hot-swap — the rollout discipline
        without the process replacement.  One replica at a time: take
        it out of dispatch (``rolling``, exactly the rollout gate),
        pump until its in-flight requests naming this adapter have
        delivered (a stream must never change weights mid-decode —
        that is the whole difference between a swap and a corruption),
        push the new weights through :meth:`load_adapter` (an in-place
        slot overwrite on the replica: the arena's hot-swap path, no
        recompile), await the ack, rejoin.  The rest of the fleet keeps
        serving throughout — under a live request drip the swap
        completes with ZERO failed requests (pinned in
        ``tests/test_fleet.py``).  ``on_tick`` is the load generator's
        hook, same as :meth:`rollout`."""
        results: Dict[str, tuple] = {}
        for name in list(names if names is not None else self._views):
            view = self._view_if_up(name)
            if view is None:
                results[name] = (False, "replica down")
                continue
            self.registry.counter("fleet/adapter_swaps").inc()
            view.rolling = True
            try:
                deadline = self._clock() + quiesce_timeout_s
                while any(
                        not r.done and r.sampling is not None
                        and getattr(r.sampling, "adapter_id", None)
                        == adapter_id
                        for r in list(view.assigned.values())):
                    self.pump()
                    if on_tick is not None:
                        on_tick()
                    if view.down or self._clock() > deadline:
                        break
                    time.sleep(poll_s)
                results[name] = self.load_adapter(
                    adapter_id, weights=weights, seed=seed,
                    names=[name], timeout_s=ack_timeout_s,
                    on_tick=on_tick).get(name, (False, "replica down"))
            finally:
                view.rolling = False
        return results

    # --------------------------------------------- live knobs (ISSUE 18)

    def set_knobs(self, payload: dict, *,
                  names: Optional[Sequence[str]] = None,
                  timeout_s: float = 60.0,
                  on_tick: Optional[Callable[[], None]] = None
                  ) -> Dict[str, tuple]:
        """Live-retune broadcast — the adapter-ack discipline applied
        to serving knobs.  ``payload`` is what
        :meth:`~apex_tpu.serving.engine.ServingEngine.set_knobs`
        accepts (``prefill_chunk`` / ``spec_k``; ``None`` values reset
        to engine defaults).  Each named replica (default: all) gets
        one ``set_knobs`` wire command stamped with a per-call token;
        the router pump-waits the ``knobs_set`` acks.  Returns
        ``{replica_name: (ok, info)}`` — ``info`` is the replica's
        applied knob state on success (the engine echo), the repr'd
        refusal otherwise.  This is the autopilot's retune actuator:
        canary first (``names=[one]``), fleet-wide only after the
        canary verdict."""
        token = next(self._knob_tokens)
        wire = dict(payload)
        wire["token"] = token
        results: Dict[str, tuple] = {}
        pairs = []
        for name in list(names if names is not None else self._views):
            view = self._view_if_up(name)
            if view is None:
                results[name] = (False, "replica down")
                continue
            send = getattr(view.client, "set_knobs", None)
            if send is None:
                results[name] = (False, "transport has no set_knobs")
                continue
            try:
                send(wire)
            except Exception as e:    # dead pipe on write
                logger.warning("fleet: set_knobs to %s failed: %r",
                               name, e)
                self._mark_down(view, f"dead pipe on set_knobs: {e!r}")
                results[name] = (False, repr(e))
                continue
            pairs.append((name, token))
        results.update(self._await_acks(
            self._knob_acks, pairs, timeout_s=timeout_s,
            on_tick=on_tick))
        return results

    # ----------------------------------------- fleet membership (ISSUE 18)

    def add_replica(self, client) -> None:
        """Seat a new replica — the autopilot's scale-up actuator.  The
        client joins through the ordinary ready handshake (``pump``
        flips the view ready on its first event); until then it is not
        dispatchable, so a half-born replica never receives work.  A
        live name collision raises (the rollout path retires the old
        holder first); a DOWN holder is retired in place — respawning
        under the same name is how a dead replica is replaced."""
        old = self._views.get(client.name)
        if old is not None:
            if not old.down:
                raise ValueError(
                    f"replica {client.name!r} is already live")
            try:
                old.client.close()
            except Exception as e:  # noqa: BLE001 — already dead
                logger.warning("fleet: closing retired %s failed: %r",
                               client.name, e)
        self._views[client.name] = _ReplicaView(client, self._clock())

    def remove_replica(self, name: str) -> None:
        """Retire a replica from the routing table (scale-down
        completion, or reaping a half-born join).  A still-live holder
        is marked down first so its in-flight requests replay through
        the ordinary failover path — removal never strands a request.
        Unknown names are a no-op (reap paths race with failure
        detection)."""
        view = self._views.pop(name, None)
        if view is None:
            return
        if not view.down:
            self._mark_down(view, "removed by controller",
                            clean=not view.assigned)
        try:
            view.client.close()
        except Exception as e:  # noqa: BLE001 — already dead
            logger.warning("fleet: closing removed %s failed: %r",
                           name, e)

    # ------------------------------------------------------- introspection

    def introspect(self) -> dict:
        """Live fleet state — duck-types the engine slot of
        :class:`~apex_tpu.observability.debug_server.DebugServer`, so
        ``DebugServer(engine=router)`` serves the fleet at /statusz."""
        replicas = {}
        for name, v in self._views.items():
            rtt_hist = self._slo_hist(f"fleet/link_rtt_ms/{name}")
            replicas[name] = {
                "ready": v.ready, "down": v.down,
                "role": v.role,
                "down_reason": v.down_reason,
                "draining": v.draining, "rolling": v.rolling,
                "assigned": len(v.assigned),
                "in_flight": v.in_flight(),
                # link state (ISSUE 14): RTT on the router host's
                # monotonic clock — never a cross-host wall compare.
                # p50/p99 answer over the windowed histogram (ISSUE 15
                # satellite): link *jitter* tails next to the latest
                # point value the degradation verdict reads
                "link_rtt_ms": (round(v.link_rtt_s * 1e3, 3)
                                if v.link_rtt_s is not None else None),
                "link_rtt_p50_ms": rtt_hist.percentile(50),
                "link_rtt_p99_ms": rtt_hist.percentile(99),
                "link_degraded": v.link_degraded,
                "reconnects": v.tx_reconnects,
                "frames_corrupt": v.tx_frames_corrupt,
                "relay_batches": v.tx_relay_batches,
                "relay_batched_events": v.tx_relay_events,
                "free_blocks": (v.state or {}).get("free_blocks"),
                "kv_occupancy": (v.state or {}).get("kv_occupancy"),
                "prefix_cache_hits": (v.state or {}).get(
                    "prefix_cache_hits"),
                # migration backlog, replica side (ISSUE 16): imports
                # pending commit + exports pinned awaiting ack
                "kv_pending_imports": (v.state or {}).get(
                    "kv_pending_imports"),
                "kv_exports_pinned": (v.state or {}).get(
                    "kv_exports_pinned"),
                # adapter residency (ISSUE 17), read off the state
                # heartbeat — the same signal placement's adapter
                # affinity keys on
                "adapters_resident": (v.state or {}).get(
                    "adapters_resident"),
                "adapter_active": (v.state or {}).get("adapter_active"),
                "ckpt_step": (v.meta or {}).get("ckpt_step"),
            }
        states = collections.Counter(
            r.state.value for r in self.requests.values())
        return {
            "replicas": replicas,
            "tenant_affinity": dict(self._tenant_affinity),
            "queue_depth": self.total_queue_depth(),
            "pending": sum(len(q) for q in self._pending.values()),
            # controller-readable signals (ISSUE 18 satellite):
            # dispatched-but-not-yet-decoding backlog and the windowed
            # p99 slope, first-class — the autopilot and external
            # probes read the same numbers the scrape shows
            "backlog": sum(v.backlog() for v in self._views.values()
                           if not v.down),
            "p99_trend": {
                "ttft_ms_per_s": round(self.p99_trend("ttft_ms"), 4),
                "tpot_ms_per_s": round(self.p99_trend("tpot_ms"), 4),
                "windows": {m: len(d) for m, d in self._trend.items()},
                "window_s": self.trend_window_s,
            },
            "requests": dict(states),
            # the fleet is "draining" only when every replica is —
            # /healthz on the router stays ok through a staggered roll
            "draining": bool(self._views) and all(
                v.draining or v.down for v in self._views.values()),
        }

    def fleet_statusz(self) -> dict:
        """The fleet aggregation plane (ISSUE 15): merged replica
        heartbeats + transport counters + per-tenant / per-priority SLO
        accounting, served by the debug server at ``/fleet/statusz``
        (the engine-slot duck type grew one optional method).

        Per tenant and per priority class: windowed p50/p99 TTFT, TPOT
        and router queue-wait (the existing :class:`~apex_tpu.
        observability.metrics.Histogram` bounded-ring semantics — the
        percentiles describe the recent window, the counts are
        lifetime), plus finished / rejected (shed) / replay (failover)
        counts.  Everything is a read-only locked snapshot — the
        free-telemetry discipline applied to the scrape path."""
        def hist_row(name: str, keep: int = 4096) -> dict:
            # keep matches the observe sites' windows — keep_samples
            # binds at first creation, and a scrape racing the first
            # observation must not shrink a window
            h = self.registry.histogram(name, keep_samples=keep)
            return {"count": h.count,
                    "p50": h.percentile(50), "p99": h.percentile(99)}

        def counter(name: str) -> int:
            return int(self.registry.counter(name).value)

        def slo_rows(kind: str, keys) -> dict:
            rows = {}
            for key in sorted(keys, key=str):
                rows[str(key)] = {
                    "ttft_ms": hist_row(f"fleet/{kind}/{key}/ttft_ms"),
                    "tpot_ms": hist_row(f"fleet/{kind}/{key}/tpot_ms"),
                    "queue_wait_ms": hist_row(
                        f"fleet/{kind}/{key}/queue_wait_ms"),
                    "finished": counter(f"fleet/{kind}/{key}/finished"),
                    "rejected": counter(f"fleet/{kind}/{key}/rejected"),
                }
                if kind == "tenant":
                    rows[str(key)]["replays"] = counter(
                        f"fleet/{kind}/{key}/replays")
            return rows

        base = self.introspect()
        # per-role SLO split + migration backlog (ISSUE 16): a
        # saturated migration link shows up HERE (backlog climbing,
        # decode-role tpot widening) before it becomes tail latency
        roles: Dict[str, dict] = {}
        for name, v in self._views.items():
            row = roles.setdefault(v.role, {
                "replicas": [], "assigned": 0, "backlog": 0,
                "ttft_ms": hist_row(f"fleet/role/{v.role}/ttft_ms"),
                "tpot_ms": hist_row(f"fleet/role/{v.role}/tpot_ms"),
            })
            row["replicas"].append(name)
            if not v.down:
                row["assigned"] += len(v.assigned)
                row["backlog"] += v.backlog()
        # per-adapter speculative acceptance (ISSUE 18 satellite):
        # summed across the live replicas' state heartbeats so the
        # template-poor tenant is visible fleet-wide, not hidden in
        # one replica's introspect
        spec_acc: Dict[str, List[int]] = {}
        for v in self._views.values():
            if v.down:
                continue
            rows = (v.state or {}).get("spec_by_adapter") or {}
            for aid, row in rows.items():
                agg = spec_acc.setdefault(str(aid), [0, 0])
                agg[0] += int(row.get("proposed") or 0)
                agg[1] += int(row.get("accepted") or 0)
        out = {
            "replicas": base["replicas"],
            "queue_depth": base["queue_depth"],
            "pending": base["pending"],
            "backlog": base["backlog"],
            "p99_trend": base["p99_trend"],
            "requests": base["requests"],
            "draining": base["draining"],
            "spec_acceptance": {
                aid: {"proposed": p, "accepted": a,
                      "acceptance": round(a / p, 4) if p else None}
                for aid, (p, a) in sorted(spec_acc.items())},
            "roles": roles,
            "migrations": {
                "inflight": len(self._migrations),
                "backlog": len(self._migrations) + sum(
                    int((v.state or {}).get("kv_pending_imports") or 0)
                    + int((v.state or {}).get("kv_exports_pinned") or 0)
                    for v in self._views.values() if not v.down),
                "started": counter("fleet/kv_migrate_started"),
                "completed": counter("fleet/kv_migrate_completed"),
                "failed": counter("fleet/kv_migrate_failed"),
                "blocks": counter("fleet/kv_migrate_blocks"),
                "bytes": counter("fleet/kv_migrate_bytes"),
                "migrate_ms": hist_row("fleet/kv_migrate_ms"),
            },
            "slo": {
                "tenants": slo_rows("tenant", self._slo_tenants),
                "priorities": slo_rows("priority",
                                       self._slo_priorities),
                # per-adapter SLO windows (ISSUE 17): same row shape as
                # tenants/priorities so scrapers need no new parser
                "adapters": slo_rows("adapter", self._slo_adapters),
            },
            "totals": {
                "submitted": counter("fleet/requests_submitted"),
                "finished": counter("fleet/requests_finished"),
                "rejected": counter("serving/requests_rejected"),
                "failovers": counter("fleet/failovers"),
                "replays": counter("fleet/replays"),
                "reschedules": counter("fleet/reschedules"),
                "reconnects": counter("fleet/reconnects"),
                "frames_corrupt": counter("fleet/frames_corrupt"),
                "relay_batch": counter("fleet/relay_batch"),
                "relay_batch_events": counter(
                    "fleet/relay_batch_events"),
                "adapter_loads": counter("fleet/adapter_loads"),
                "adapter_swaps": counter("fleet/adapter_swaps"),
            },
            "fleet_ttft_ms": hist_row("fleet/ttft_ms"),
            "fleet_tpot_ms": hist_row("fleet/tpot_ms", keep=65536),
        }
        # longitudinal history + burn-rate blocks (ISSUE 20) appear ONLY
        # when the history plane is armed — a disarmed fleet's statusz
        # stays byte-for-byte the PR 19 shape
        if self.history is not None:
            out["history"] = self.history.introspect()
            if self.slo is not None:
                out["slo"]["burn"] = {
                    "rows": self.slo.last_rows,
                    "worst": self.slo.worst(),
                    **self.slo.introspect(),
                }
        return out

    # ---------------------------------------------------------- lifecycle

    def stream(self, req, *, poll_s: float = 0.002,
               timeout_s: float = 300.0):
        """Iterate a request's tokens as router events surface them —
        the streaming client API (ROADMAP fleet follow-on): callers
        stop polling result buffers and consume the stream.

        ``req``: a :class:`FleetRequest` or its rid.  Each iteration
        **pumps the router** (the single-threaded driving model —
        consuming a stream keeps the whole fleet moving, exactly like
        :meth:`run_until_idle`), yields any newly-surfaced tokens, and
        closes when the request reaches a terminal state.  Tokens
        survive failover transparently: a replay appends to the same
        ``output_tokens``, so the iterator just keeps yielding the
        stitched (bitwise-identical) stream.  A shed/parked REJECTED
        request yields nothing and closes immediately — the terminal
        state is the caller's signal, same as the non-streaming path.
        ``timeout_s`` is an **inactivity** bound — it resets on every
        surfaced token, so a long healthy stream never trips it; only a
        stream that goes silent (and that failover/attempt-parking has
        not already driven to a terminal state) raises.
        """
        if not hasattr(req, "output_tokens"):
            found = self.requests.get(req)
            if found is None:
                raise KeyError(f"unknown or evicted fleet request {req!r}")
            req = found
        sent = 0
        deadline = self._clock() + timeout_s
        while True:
            progressed = sent < len(req.output_tokens)
            while sent < len(req.output_tokens):
                yield req.output_tokens[sent]
                sent += 1
            if req.done:
                return
            self.pump()
            if progressed:
                deadline = self._clock() + timeout_s
            elif self._clock() > deadline:
                raise RuntimeError(
                    f"stream of request {req.rid} surfaced no token and "
                    f"no terminal state for {timeout_s:.0f}s")
            if poll_s and not progressed:
                time.sleep(poll_s)

    def idle(self) -> bool:
        """True when every submitted request reached a terminal state."""
        return all(r.done for r in self.requests.values())

    def run_until_idle(self, *, timeout_s: float = 300.0,
                       poll_s: float = 0.002) -> None:
        deadline = self._clock() + timeout_s
        while not self.idle():
            self.pump()
            if self._clock() > deadline:
                open_reqs = [r.rid for r in self.requests.values()
                             if not r.done]
                raise RuntimeError(
                    f"fleet not idle after {timeout_s:.0f}s; open "
                    f"requests: {open_reqs[:16]}")
            time.sleep(poll_s)

    def close(self) -> None:
        """Tear the fleet down (idempotent per client)."""
        for view in self._views.values():
            try:
                view.client.close()
            except Exception as e:
                logger.warning("fleet: closing %s failed: %r",
                               view.name, e)
