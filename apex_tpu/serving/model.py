"""Prefill/decode forward over the *training* transformer layers.

The serving twin of ``transformer.testing.gpt_parallel_train``: the same
parameter pytree (:class:`~apex_tpu.transformer.testing.gpt_parallel_train.
GPT3DParams`, layer stack flattened to ``[L, ...]``), the same
tensor-parallel modules (``ColumnParallelLinear``/``RowParallelLinear``
/``VocabParallelEmbedding``-backed :class:`Embedding`, ``ParallelMLP``,
``FusedLayerNorm``) and the same RoPE tables — but driven through two
inference-shaped entry points instead of a loss:

- :meth:`DecodeModel.prefill` — one **packed row** ``[1, L]`` holding
  one or more requests' prompts back to back (host-built segment ids,
  position ids, and per-token cache destinations).  Attention is the
  PR 2 flash kernel with ``segment_ids`` — packed multi-request prefill
  falls out of the varlen mechanism for free — and each layer's K/V
  are scattered into the paged arena at host-precomputed
  ``(block, offset)`` destinations.
- :meth:`DecodeModel.decode_step` — the jit-stable continuous-batching
  step: fixed ``[max_batch, 1]`` tokens, per-slot positions/tables and
  an active mask; inactive slots are pure data (their cache writes are
  routed out of range and dropped; their attention length is 0), so
  requests joining/leaving never change a shape and the step **never
  recompiles**.  Attention over the cache is the fused Pallas
  paged-attention kernel (:mod:`.paged_attention`), and the
  residual/norm tail of each block can run as the fused epilogue
  kernel (:mod:`.fused_ops`) — both A/B-able against their unfused XLA
  lowerings via the constructor flags.

Both entry points are **shard_map bodies**: run them under
``collectives.shard_over`` with the tensor axis bound (the engine does
this) — the parallel linears then shard exactly as in training, and
the K/V arena rows a rank touches are the heads it owns.  Greedy
next-token ids are computed inside (vocab-sharded logits are gathered
over tp before the argmax), so the host round-trips one int per slot
per step, not a logits tensor.
"""

from __future__ import annotations

import dataclasses
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel import collectives as cc
from apex_tpu.serving.fused_ops import (
    fused_residual_norm,
    residual_norm_unfused,
)
from apex_tpu.serving.kv_cache import KVCacheConfig
from apex_tpu.serving.paged_attention import (
    paged_attention_decode,
    paged_attention_decode_unfused,
)
from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
from apex_tpu.transformer.rope import (
    apply_rotary,
    apply_rotary_decode,
    rotary_cos_sin,
)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
)
from apex_tpu.transformer.tensor_parallel.utils import divide
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    Embedding,
    ParallelMLP,
    TransformerConfig,
    parallel_lm_logits,
)

__all__ = ["DecodeModel", "serving_config"]


def serving_config(config: TransformerConfig) -> TransformerConfig:
    """The inference view of a training config.

    Dropout off (inference), sequence parallelism off (a decode step
    has one token per slot — there is no sequence dim to shard; param
    shapes are identical so training checkpoints load unchanged),
    ring overlap off (no SP collective to decompose), fp8 off (the
    delayed-scaling state lives in a training-side collection).
    """
    if config.apply_residual_connection_post_layernorm:
        raise NotImplementedError(
            "serving decode assumes the standard pre-LN residual; "
            "apply_residual_connection_post_layernorm is not wired")
    if config.num_experts is not None:
        raise NotImplementedError(
            "MoE serving is not wired yet (the EP roadmap item)")
    return dataclasses.replace(
        config, hidden_dropout=0.0, attention_dropout=0.0,
        sequence_parallel=False, overlap_comm=False, context_axis=None,
        fp8=False)


class DecodeModel:
    """Functional prefill/decode forward bound to a config + cache shape.

    Stateless: parameters and cache arenas are arguments, so the same
    instance serves any checkpoint of the architecture and the engine
    can donate the arenas through jit.
    """

    def __init__(self, config: TransformerConfig, cache: KVCacheConfig, *,
                 fused_attention: bool = True, fuse_epilogue: bool = True):
        cfg = serving_config(config)
        self.cfg = cfg
        self.cache = cache
        self.fused_attention = fused_attention
        self.fuse_epilogue = fuse_epilogue

        d = cfg.head_dim
        n, g = cfg.num_attention_heads, cfg.query_groups
        self.hpg = divide(n, g)
        if cache.kv_heads != g:
            raise ValueError(
                f"cache kv_heads ({cache.kv_heads}) != model query_groups "
                f"({g})")
        if cache.head_dim != d:
            raise ValueError(
                f"cache head_dim ({cache.head_dim}) != model head_dim ({d})")
        self.embed = Embedding(cfg)
        self.qkv = ColumnParallelLinear(
            cfg.hidden_size, (n + 2 * g) * d, axis=cfg.tensor_axis,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        self.dense = RowParallelLinear(
            n * d, cfg.hidden_size, input_is_parallel=True,
            skip_bias_add=True, axis=cfg.tensor_axis,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        self.mlp = ParallelMLP(cfg)
        self.ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon)

    # ----------------------------------------------------------------- util

    def _split_qkv(self, qkv):
        """Group-major fused-QKV split (``ParallelAttention`` layout):
        per K/V group its query heads, then its one K and one V head."""
        cfg = self.cfg
        d = cfg.head_dim
        world = cc.bound_axis_size(cfg.tensor_axis)
        g_local = divide(cfg.query_groups, world)
        n_local = divide(cfg.num_attention_heads, world)
        s, b = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(s, b, g_local, (self.hpg + 2) * d)
        q = qkv[..., :self.hpg * d].reshape(s, b, n_local, d)
        k = qkv[..., self.hpg * d:(self.hpg + 1) * d]
        v = qkv[..., (self.hpg + 1) * d:]
        return q, k, v

    def _layer_stack(self, params, x, k_arena, v_arena, attn_core, rope_fn):
        """Scan the ``[L, ...]`` layer stack; each step consumes its own
        arena slice and emits the updated one (the scan re-stacks them,
        which XLA aliases into the donated input arena)."""

        def body(carry, xs):
            x = carry
            lp, k_layer, v_layer = xs
            ln1 = self.ln.apply({"params": lp["input_layernorm"]}, x)
            qkv = self.qkv.apply(
                {"params": lp["self_attention"]["query_key_value"]}, ln1)
            q, k, v = self._split_qkv(qkv)
            q, k = rope_fn(q, k)
            ctx, k_layer, v_layer = attn_core(q, k, v, k_layer, v_layer)
            y, y_bias = self.dense.apply(
                {"params": lp["self_attention"]["dense"]}, ctx)
            ln2 = lp["post_attention_layernorm"]
            if self.fuse_epilogue:
                ln2_out, h = fused_residual_norm(
                    y, x, ln2["scale"], ln2["bias"], bias=y_bias,
                    eps=self.cfg.layernorm_epsilon)
            else:
                ln2_out, h = residual_norm_unfused(
                    y, x, ln2["scale"], ln2["bias"], bias=y_bias,
                    eps=self.cfg.layernorm_epsilon)
            m, m_bias = self.mlp.apply({"params": lp["mlp"]}, ln2_out)
            return h + m + m_bias, (k_layer, v_layer)

        x, (k_arena, v_arena) = lax.scan(
            body, x, (params.layers, k_arena, v_arena))
        return x, k_arena, v_arena

    def _head(self, params, x):
        """Final LN + tied LM head + tp-gathered greedy argmax.

        Returns ``(next_tokens [s, b], logits [s, b, vocab])`` with the
        FULL vocab (gathered over tp so the argmax — and the host —
        see one consistent id space)."""
        cfg = self.cfg
        hidden = self.ln.apply({"params": params.final_ln}, x)
        logits = parallel_lm_logits(
            hidden, params.embedding["word_embeddings"]["embedding"], cfg)
        if cfg.tensor_axis is not None \
                and cc.bound_axis_size(cfg.tensor_axis) > 1:
            logits = cc.all_gather(logits, cfg.tensor_axis, concat_axis=-1)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits

    def _rope_tables(self, positions, dtype):
        cfg = self.cfg
        if cfg.position_embedding_type != "rope":
            return None
        return rotary_cos_sin(positions, cfg.rotary_dim, cfg.rotary_base,
                              dtype)

    # ---------------------------------------------------------------- entry

    def decode_step(self, k_arena, v_arena, params, tokens, positions,
                    block_tables, active):
        """One continuously-batched greedy decode step (shard_map body).

        ``tokens [max_batch, 1]`` (each slot's last sampled/prompt
        token), ``positions [max_batch]`` (the cache index this token
        is written at — the slot's current length), ``block_tables
        [max_batch, max_blocks]``, ``active [max_batch]`` bool.  Every
        shape is fixed by the engine config; request churn only changes
        values.  Returns ``(k_arena, v_arena, next_tokens [max_batch],
        logits [max_batch, vocab])``.
        """
        cfg = self.cfg
        cache = self.cache
        bs = cache.block_size
        b = tokens.shape[0]
        positions = positions.astype(jnp.int32)
        lengths = jnp.where(active, positions + 1, 0).astype(jnp.int32)
        # this step's cache write destination; inactive slots write out
        # of range and the scatter drops them
        logical = positions // bs
        phys = jnp.take_along_axis(
            block_tables, logical[:, None], axis=1)[:, 0]
        phys = jnp.where(active, phys, cache.n_blocks).astype(jnp.int32)
        offs = (positions % bs).astype(jnp.int32)

        if cfg.position_embedding_type == "learned":
            x = self.embed.apply({"params": params.embedding}, tokens,
                                 positions[:, None])
        else:
            x = self.embed.apply({"params": params.embedding}, tokens)
        # x: [1, max_batch, hidden]
        rope = self._rope_tables(positions, x.dtype)

        def rope_fn(q, k):
            if rope is None:
                return q, k
            cos, sin = rope
            return (apply_rotary_decode(q, cos, sin),
                    apply_rotary_decode(k, cos, sin))

        attend = (paged_attention_decode if self.fused_attention
                  else paged_attention_decode_unfused)

        def attn_core(q, k, v, k_layer, v_layer):
            # append this token's K/V, then attend over the paged cache
            k_layer = k_layer.at[phys, offs].set(
                k[0].astype(k_layer.dtype), mode="drop")
            v_layer = v_layer.at[phys, offs].set(
                v[0].astype(v_layer.dtype), mode="drop")
            ctx = attend(q[0], k_layer, v_layer, block_tables, lengths)
            return ctx.reshape(1, b, -1).astype(q.dtype), k_layer, v_layer

        x, k_arena, v_arena = self._layer_stack(
            params, x, k_arena, v_arena, attn_core, rope_fn)
        next_tokens, logits = self._head(params, x)
        return k_arena, v_arena, next_tokens[0], logits[0]

    def prefill(self, k_arena, v_arena, params, tokens, position_ids,
                segment_ids, dest_blocks, dest_offsets):
        """Packed multi-request prefill of one ``[1, L]`` row
        (shard_map body).

        ``position_ids [1, L]`` — each token's position *within its
        request* (restarting per segment; also the RoPE angle source,
        so packing composes with rope); ``segment_ids [1, L]`` — 1-based
        request ids, 0 = padding (the flash-attention varlen mechanism:
        causal ∧ same-segment = per-request causal attention);
        ``dest_blocks/dest_offsets [L]`` — each token's physical cache
        destination (out-of-range = dropped, used for padding).
        Returns ``(k_arena, v_arena, next_tokens [L], logits [L,
        vocab])`` — the greedy next token *at every position*; the host
        reads each request's last-prompt-position entry as its first
        generated token.
        """
        from apex_tpu.ops.flash_attention import flash_attention

        cfg = self.cfg
        L = tokens.shape[1]
        dest_blocks = dest_blocks.astype(jnp.int32)
        dest_offsets = dest_offsets.astype(jnp.int32)

        if cfg.position_embedding_type == "learned":
            x = self.embed.apply({"params": params.embedding}, tokens,
                                 position_ids)
        else:
            x = self.embed.apply({"params": params.embedding}, tokens)
        # x: [L, 1, hidden]
        rope = self._rope_tables(position_ids[0], x.dtype)

        def rope_fn(q, k):
            if rope is None:
                return q, k
            cos, sin = rope
            return apply_rotary(q, cos, sin), apply_rotary(k, cos, sin)

        def attn_core(q, k, v, k_layer, v_layer):
            # q [L, 1, n_local, d]; k/v [L, 1, g_local, d] (compact GQA)
            k_layer = k_layer.at[dest_blocks, dest_offsets].set(
                k[:, 0].astype(k_layer.dtype), mode="drop")
            v_layer = v_layer.at[dest_blocks, dest_offsets].set(
                v[:, 0].astype(v_layer.dtype), mode="drop")
            ke, ve = k, v
            if self.hpg > 1:
                ke = jnp.repeat(ke, self.hpg, axis=2)
                ve = jnp.repeat(ve, self.hpg, axis=2)
            ctx = flash_attention(
                q.transpose(1, 2, 0, 3), ke.transpose(1, 2, 0, 3),
                ve.transpose(1, 2, 0, 3), causal=True,
                segment_ids_q=segment_ids, segment_ids_kv=segment_ids,
            )  # [1, n_local, L, d]
            return (ctx.transpose(2, 0, 1, 3).reshape(L, 1, -1)
                    .astype(q.dtype), k_layer, v_layer)

        x, k_arena, v_arena = self._layer_stack(
            params, x, k_arena, v_arena, attn_core, rope_fn)
        next_tokens, logits = self._head(params, x)
        return k_arena, v_arena, next_tokens[:, 0], logits[:, 0]
