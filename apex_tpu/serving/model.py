"""Prefill/decode forward over the *training* transformer layers.

The serving twin of ``transformer.testing.gpt_parallel_train``: the same
parameter pytree (:class:`~apex_tpu.transformer.testing.gpt_parallel_train.
GPT3DParams`, layer stack flattened to ``[L, ...]``), the same
tensor-parallel modules (``ColumnParallelLinear``/``RowParallelLinear``
/``VocabParallelEmbedding``-backed :class:`Embedding`, ``ParallelMLP``,
``FusedLayerNorm``) and the same RoPE tables — but driven through two
inference-shaped entry points instead of a loss:

- :meth:`DecodeModel.prefill` — **batched chunked prefill**: a fixed
  ``[max_batch, chunk]`` slice of tokens, one chunk per slot, scattered
  into the paged arena at host-precomputed ``(block, offset)``
  destinations and attended with the chunked-prefill paged kernel
  (:func:`~apex_tpu.serving.paged_attention.paged_prefill_attention`):
  each token's per-token causal ``limit`` covers the request's whole
  cached context — prior chunks, shared prefix-cache blocks, and the
  in-chunk causal triangle — in ONE block sweep, which is what makes a
  long prompt sliceable across decode ticks (it never stalls a tick)
  and a prefix-cache hit a pure block-table entry.
- :meth:`DecodeModel.decode_step` — the jit-stable continuous-batching
  step: fixed ``[max_batch, spec_width]`` tokens (``spec_width = k + 1``
  with speculative decoding, 1 without — a compile-time constant of the
  engine config), per-slot positions/tables, an active mask and a
  per-slot ``n_draft``; inactive slots and unused draft positions are
  pure data (their cache writes are routed out of range and dropped;
  their attention limit is 0), so requests joining/leaving/preempting
  and per-tick draft counts anywhere in ``[0, k]`` never change a shape
  and the step **never recompiles**.

  With drafts the step is the **fused k+1 verify** (ISSUE 13): each
  slot's real last token plus its k drafted continuations attend in one
  multi-query block sweep with per-position causal limits
  (:func:`~apex_tpu.serving.paged_attention.paged_attention_decode`
  with 4-D q), every position samples with the request's policy at its
  own output index, and the accepted count — the longest prefix of
  drafts matching the step's own outputs — is computed in-graph.
  Accepted tokens are bitwise the tokens sequential decode would have
  produced (each verified position is teacher-forced on an accepted
  prefix), so speculation never changes a stream, only its arrival
  rate.  Rejected drafts cost nothing to undo: their K/V rows sit past
  the host-side length that was never advanced (the O(1) rollback —
  pointer/length moves, no copies), and the next tick overwrites them.

Both entry points **sample in-graph** (:mod:`.sampling`): per-slot
temperature/top-k/top-p/seed/step ride as ``[max_batch]`` data, the
vocab-sharded logits are gathered over tp before the draw, and the host
round-trips one int per slot per step, not a logits tensor.  Greedy
(``temperature == 0``) stays the exact argmax every token-identity
contract rests on.

With an **int8 cache** the K/V rows are quantized on write (one
symmetric fp32 scale per row, computed in-graph) and dequantized inside
the paged kernels — the arenas argument widens to
``(k, v, k_scales, v_scales)`` and everything else is unchanged.

Both entry points are **shard_map bodies**: run them under
``collectives.shard_over`` with the tensor axis bound (the engine does
this) — the parallel linears then shard exactly as in training, and
the K/V arena rows a rank touches are the heads it owns.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel import collectives as cc
from apex_tpu.serving.fused_ops import (
    fused_residual_norm,
    residual_norm_unfused,
)
from apex_tpu.serving.kv_cache import KVCacheConfig
from apex_tpu.serving.lora import LoRAConfig, lora_delta
from apex_tpu.serving.paged_attention import (
    paged_attention_decode,
    paged_attention_decode_unfused,
    paged_prefill_attention,
    paged_prefill_attention_unfused,
)
from apex_tpu.serving.sampling import sample_tokens
from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
from apex_tpu.transformer.rope import (
    apply_rotary_decode,
    apply_rotary_packed,
    rotary_cos_sin,
)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
)
from apex_tpu.transformer.tensor_parallel.utils import divide
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    Embedding,
    ParallelMLP,
    TransformerConfig,
    parallel_lm_logits,
)

__all__ = ["DecodeModel", "serving_config"]


def serving_config(config: TransformerConfig) -> TransformerConfig:
    """The inference view of a training config.

    Dropout off (inference), sequence parallelism off (a decode step
    has one token per slot — there is no sequence dim to shard; param
    shapes are identical so training checkpoints load unchanged),
    ring overlap off (no SP collective to decompose), fp8 off (the
    delayed-scaling state lives in a training-side collection).
    """
    if config.apply_residual_connection_post_layernorm:
        raise NotImplementedError(
            "serving decode assumes the standard pre-LN residual; "
            "apply_residual_connection_post_layernorm is not wired")
    if config.num_experts is not None:
        raise NotImplementedError(
            "MoE serving is not wired yet (the EP roadmap item)")
    return dataclasses.replace(
        config, hidden_dropout=0.0, attention_dropout=0.0,
        sequence_parallel=False, overlap_comm=False, context_axis=None,
        fp8=False)


def _quantize_rows(x):
    """Symmetric int8 row quantization: ``x [..., d]`` -> (int8 values,
    fp32 per-row scales ``[...]``).  ``amax / 127`` with an epsilon
    floor so an all-zero row round-trips to exact zeros."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scales = jnp.maximum(amax / 127.0, 1e-8).astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / scales[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8), scales


class DecodeModel:
    """Functional prefill/decode forward bound to a config + cache shape.

    Stateless: parameters and cache arenas are arguments, so the same
    instance serves any checkpoint of the architecture and the engine
    can donate the arenas through jit.
    """

    def __init__(self, config: TransformerConfig, cache: KVCacheConfig, *,
                 fused_attention: bool = True, fuse_epilogue: bool = True,
                 lora: Optional[LoRAConfig] = None):
        cfg = serving_config(config)
        self.cfg = cfg
        self.cache = cache
        self.fused_attention = fused_attention
        self.fuse_epilogue = fuse_epilogue
        self.lora = lora

        d = cfg.head_dim
        n, g = cfg.num_attention_heads, cfg.query_groups
        self.hpg = divide(n, g)
        if cache.kv_heads != g:
            raise ValueError(
                f"cache kv_heads ({cache.kv_heads}) != model query_groups "
                f"({g})")
        if cache.head_dim != d:
            raise ValueError(
                f"cache head_dim ({cache.head_dim}) != model head_dim ({d})")
        self.embed = Embedding(cfg)
        self.qkv = ColumnParallelLinear(
            cfg.hidden_size, (n + 2 * g) * d, axis=cfg.tensor_axis,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        self.dense = RowParallelLinear(
            n * d, cfg.hidden_size, input_is_parallel=True,
            skip_bias_add=True, axis=cfg.tensor_axis,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        self.mlp = ParallelMLP(cfg)
        self.ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon)
        if lora is not None:
            # the adapter path needs the MLP's two GEMMs exposed (the
            # fc1 delta lands before the activation), so bind the same
            # parallel linears ParallelMLP builds, under its param
            # names — _mlp_with_adapter replays its ops verbatim
            self.mlp_fc1 = ColumnParallelLinear(
                cfg.hidden_size, cfg.ffn_size,
                sequence_parallel=cfg.sequence_parallel,
                skip_bias_add=True, axis=cfg.tensor_axis,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                fp8=cfg.fp8, overlap_comm=cfg.overlap_comm)
            self.mlp_gate = None
            if cfg.swiglu:
                self.mlp_gate = ColumnParallelLinear(
                    cfg.hidden_size, cfg.ffn_size,
                    sequence_parallel=cfg.sequence_parallel,
                    skip_bias_add=True, axis=cfg.tensor_axis,
                    dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                    fp8=cfg.fp8, overlap_comm=cfg.overlap_comm)
            self.mlp_fc2 = RowParallelLinear(
                cfg.ffn_size, cfg.hidden_size, input_is_parallel=True,
                sequence_parallel=cfg.sequence_parallel,
                skip_bias_add=True, axis=cfg.tensor_axis,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                fp8=cfg.fp8, overlap_comm=cfg.overlap_comm)

    # ----------------------------------------------------------------- util

    def _split_qkv(self, qkv):
        """Group-major fused-QKV split (``ParallelAttention`` layout):
        per K/V group its query heads, then its one K and one V head."""
        cfg = self.cfg
        d = cfg.head_dim
        world = cc.bound_axis_size(cfg.tensor_axis)
        g_local = divide(cfg.query_groups, world)
        n_local = divide(cfg.num_attention_heads, world)
        s, b = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(s, b, g_local, (self.hpg + 2) * d)
        q = qkv[..., :self.hpg * d].reshape(s, b, n_local, d)
        k = qkv[..., self.hpg * d:(self.hpg + 1) * d]
        v = qkv[..., (self.hpg + 1) * d:]
        return q, k, v

    def _append_rows(self, layer_arenas, dest_blocks, dest_offsets, k, v):
        """Scatter K/V rows into one layer's arena slice at
        ``(block, offset)`` destinations (out-of-range = dropped —
        inactive slots and padding route there), quantizing on write
        for the int8 cache (the per-row scales land beside the rows,
        through the same dropped-scatter indices)."""
        if self.cache.quantized:
            k_layer, v_layer, ks_layer, vs_layer = layer_arenas
            qk, sk = _quantize_rows(k)
            qv, sv = _quantize_rows(v)
            k_layer = k_layer.at[dest_blocks, dest_offsets].set(
                qk, mode="drop")
            v_layer = v_layer.at[dest_blocks, dest_offsets].set(
                qv, mode="drop")
            ks_layer = ks_layer.at[dest_blocks, dest_offsets].set(
                sk, mode="drop")
            vs_layer = vs_layer.at[dest_blocks, dest_offsets].set(
                sv, mode="drop")
            return (k_layer, v_layer, ks_layer, vs_layer)
        k_layer, v_layer = layer_arenas
        k_layer = k_layer.at[dest_blocks, dest_offsets].set(
            k.astype(k_layer.dtype), mode="drop")
        v_layer = v_layer.at[dest_blocks, dest_offsets].set(
            v.astype(v_layer.dtype), mode="drop")
        return (k_layer, v_layer)

    def _attend_kwargs(self, layer_arenas):
        """(k, v[, scale kwargs]) of one layer slice for the kernels."""
        if self.cache.quantized:
            k_layer, v_layer, ks_layer, vs_layer = layer_arenas
            return (k_layer, v_layer), dict(k_scales=ks_layer,
                                            v_scales=vs_layer)
        return layer_arenas, {}

    def _lora_delta(self, x, a, b, slots):
        """The gathered rank-r bypass of one projection for every batch
        slot (``slots [max_batch]`` is DATA — see :mod:`.lora`)."""
        return lora_delta(x, a, b, slots, fused=self.lora.fused)

    def _lora_psum(self, d):
        """Sum a row-parallel projection's partial deltas over tp (A is
        sharded on the input dim there, so each rank holds a partial —
        the one collective the adapter path adds)."""
        cfg = self.cfg
        if cfg.tensor_axis is not None \
                and cc.bound_axis_size(cfg.tensor_axis) > 1:
            return cc.all_reduce(d, cfg.tensor_axis)
        return d

    def _mlp_with_adapter(self, mlp_params, x, fc1_a, fc1_b, fc2_a, fc2_b,
                          slots):
        """``ParallelMLP`` replayed op-for-op with the gathered adapter
        deltas injected: fc1's (column-parallel — lands pre-split like
        the base output, before the activation) and fc2's (row-parallel
        — per-rank partial, psum'd).  Zero-slot gathers add exact zeros,
        keeping the bare stream bitwise."""
        cfg = self.cfg
        h, bias = self.mlp_fc1.apply(
            {"params": mlp_params["dense_h_to_4h"]}, x)
        h = h + bias + self._lora_delta(x, fc1_a, fc1_b, slots)
        if cfg.swiglu:
            gate, gate_bias = self.mlp_gate.apply(
                {"params": mlp_params["dense_h_to_4h_gate"]}, x)
            h = jax.nn.silu(gate + gate_bias) * h
        else:
            h = jax.nn.gelu(h, approximate=cfg.bias_gelu_fusion)
        out, out_bias = self.mlp_fc2.apply(
            {"params": mlp_params["dense_4h_to_h"]}, h)
        out = out + self._lora_psum(
            self._lora_delta(h, fc2_a, fc2_b, slots))
        return out, out_bias

    def _layer_stack(self, params, x, arenas, attn_core, adapters=None,
                     adapter_slots=None):
        """Scan the ``[L, ...]`` layer stack; each step consumes its own
        arena slices and emits the updated ones (the scan re-stacks
        them, which XLA aliases into the donated input arenas).

        With ``adapters`` (the 8 ``[L, n_slots, ...]`` LoRA arrays,
        threaded exactly like the arenas so the engine can donate them
        too), every projection adds its slot-gathered delta; the scan
        re-emits the adapter slices unchanged."""
        n_ar = len(arenas)

        def body(carry, xs):
            x = carry
            lp, rest = xs[0], xs[1:]
            layer_arenas = rest[:n_ar]
            layer_adapters = rest[n_ar:]
            ln1 = self.ln.apply({"params": lp["input_layernorm"]}, x)
            qkv = self.qkv.apply(
                {"params": lp["self_attention"]["query_key_value"]}, ln1)
            if layer_adapters:
                (qkv_a, qkv_b, dense_a, dense_b,
                 fc1_a, fc1_b, fc2_a, fc2_b) = layer_adapters
                qkv = qkv + self._lora_delta(ln1, qkv_a, qkv_b,
                                             adapter_slots)
            q, k, v = self._split_qkv(qkv)
            ctx, layer_arenas = attn_core(q, k, v, layer_arenas)
            y, y_bias = self.dense.apply(
                {"params": lp["self_attention"]["dense"]}, ctx)
            if layer_adapters:
                y = y + self._lora_psum(self._lora_delta(
                    ctx, dense_a, dense_b, adapter_slots))
            ln2 = lp["post_attention_layernorm"]
            if self.fuse_epilogue:
                ln2_out, h = fused_residual_norm(
                    y, x, ln2["scale"], ln2["bias"], bias=y_bias,
                    eps=self.cfg.layernorm_epsilon)
            else:
                ln2_out, h = residual_norm_unfused(
                    y, x, ln2["scale"], ln2["bias"], bias=y_bias,
                    eps=self.cfg.layernorm_epsilon)
            if layer_adapters:
                m, m_bias = self._mlp_with_adapter(
                    lp["mlp"], ln2_out, fc1_a, fc1_b, fc2_a, fc2_b,
                    adapter_slots)
            else:
                m, m_bias = self.mlp.apply({"params": lp["mlp"]}, ln2_out)
            return h + m + m_bias, layer_arenas + tuple(layer_adapters)

        xs = (params.layers,) + tuple(arenas)
        if adapters is not None:
            xs = xs + tuple(adapters)
        x, out = lax.scan(body, x, xs)
        if adapters is None:
            return x, out, None
        return x, out[:n_ar], out[n_ar:]

    def _head(self, params, x):
        """Final LN + tied LM head, vocab gathered over tp.

        Returns ``logits [s, b, vocab]`` with the FULL vocab (gathered
        so the in-graph sampler — and the host — see one consistent id
        space)."""
        cfg = self.cfg
        hidden = self.ln.apply({"params": params.final_ln}, x)
        logits = parallel_lm_logits(
            hidden, params.embedding["word_embeddings"]["embedding"], cfg)
        if cfg.tensor_axis is not None \
                and cc.bound_axis_size(cfg.tensor_axis) > 1:
            logits = cc.all_gather(logits, cfg.tensor_axis, concat_axis=-1)
        return logits

    def _rope_tables(self, positions, dtype):
        cfg = self.cfg
        if cfg.position_embedding_type != "rope":
            return None
        return rotary_cos_sin(positions, cfg.rotary_dim, cfg.rotary_base,
                              dtype)

    # ---------------------------------------------------------------- entry

    def decode_step(self, arenas, params, tokens, positions, block_tables,
                    active, n_draft, temperature, top_k, top_p, seeds,
                    steps, adapters=None, adapter_slots=None):
        """One continuously-batched decode/verify step (shard_map body).

        ``arenas`` — ``(k, v)`` or ``(k, v, k_scales, v_scales)``;
        ``tokens [max_batch, S]`` where ``S = spec_width`` (column 0 is
        each slot's last sampled/prompt token, columns ``1..n_draft``
        its drafted continuations, the rest padding), ``positions
        [max_batch]`` (the cache index column 0 is written at — the
        slot's current length), ``block_tables
        [max_batch, max_blocks]``, ``active [max_batch]`` bool,
        ``n_draft [max_batch]`` (0..S-1, per-slot draft count — DATA),
        and the ``[max_batch]`` sampling-policy arrays (:mod:`.sampling`
        — ``steps`` is each slot's output-token counter, the seed
        fold-in; verify position t draws at counter ``steps + t``).
        Every shape is fixed by the engine config; request churn,
        preemption, eviction, draft counts and policy changes only move
        values.  Returns ``(arenas, out_tokens [max_batch, S],
        accepted [max_batch], logits [max_batch, S, vocab])`` —
        ``accepted`` is the longest prefix of drafts matching the
        step's own outputs, so the host emits ``out_tokens[:, :a + 1]``
        and advances lengths by ``a + 1`` (rejection is a length that
        simply never advances — nothing to copy back).

        With LoRA enabled the step also takes ``adapters`` (the 8
        donated arena arrays) and ``adapter_slots [max_batch]`` (each
        slot's arena row — DATA, like the block tables), and returns
        ``(arenas, adapters, out, accepted, logits)``.
        """
        cfg = self.cfg
        cache = self.cache
        bs = cache.block_size
        B, S = tokens.shape
        positions = positions.astype(jnp.int32)
        n_draft = n_draft.astype(jnp.int32)
        offsets = lax.broadcasted_iota(jnp.int32, (B, S), 1)
        pos_ids = positions[:, None] + offsets          # [B, S]
        live = active[:, None] & (offsets <= n_draft[:, None])
        # per-position causal horizon: verify token t sees cache
        # positions < pos + t + 1 (its own row included — scattered
        # below, before the attention, the prefill convention)
        limits = jnp.where(live, pos_ids + 1, 0).astype(jnp.int32)
        lengths = jnp.where(active, positions + n_draft + 1,
                            0).astype(jnp.int32)
        # cache write destinations; inactive slots and padding columns
        # write out of range and the scatter drops them
        logical = jnp.clip(pos_ids // bs, 0, block_tables.shape[1] - 1)
        phys = jnp.take_along_axis(block_tables, logical, axis=1)
        dest_blocks = jnp.where(live, phys,
                                cache.n_blocks).astype(jnp.int32)
        dest_offsets = (pos_ids % bs).astype(jnp.int32)

        if cfg.position_embedding_type == "learned":
            x = self.embed.apply({"params": params.embedding}, tokens,
                                 pos_ids)
        else:
            x = self.embed.apply({"params": params.embedding}, tokens)
        # x: [S, max_batch, hidden]
        rope = None
        if cfg.position_embedding_type == "rope":
            if S == 1:
                rope = self._rope_tables(positions, x.dtype)
            else:
                cos, sin = self._rope_tables(pos_ids.reshape(-1), x.dtype)
                rope = (cos.reshape(B, S, -1).transpose(1, 0, 2),
                        sin.reshape(B, S, -1).transpose(1, 0, 2))

        attend = (paged_attention_decode if self.fused_attention
                  else paged_attention_decode_unfused)

        def attn_core(q, k, v, layer_arenas):
            # q [S, B, n_local, d]; k/v [S, B, g_local, d]
            if rope is not None:
                cos, sin = rope
                rot = apply_rotary_decode if S == 1 else apply_rotary_packed
                q = rot(q, cos, sin)
                k = rot(k, cos, sin)
            # append the K/V rows, then attend over the paged cache
            layer_arenas = self._append_rows(
                layer_arenas, dest_blocks, dest_offsets,
                k.transpose(1, 0, 2, 3), v.transpose(1, 0, 2, 3))
            kv, sc = self._attend_kwargs(layer_arenas)
            if S == 1:
                # the single-token kernel: the non-speculative engine
                # keeps exactly the PR 8 decode program
                ctx = attend(q[0], *kv, block_tables, lengths, **sc)
            else:
                ctx = attend(q.transpose(1, 0, 2, 3), *kv, block_tables,
                             lengths, limits=limits, **sc)  # [B, S, n, d]
                ctx = ctx.transpose(1, 0, 2, 3)
            return (ctx.reshape(S, B, -1).astype(q.dtype), layer_arenas)

        x, arenas, adapters = self._layer_stack(
            params, x, arenas, attn_core, adapters, adapter_slots)
        logits = self._head(params, x)             # [S, B, vocab]
        logits = logits.transpose(1, 0, 2)         # [B, S, vocab]
        # every position samples with its slot's policy at its own
        # output counter — accepted draws are the draws the sequential
        # path would have made (same key, same teacher-forced logits)
        rep = lambda a: jnp.repeat(a, S, axis=0)   # noqa: E731
        sampled = sample_tokens(
            logits.reshape(B * S, -1), rep(temperature), rep(top_k),
            rep(top_p), rep(seeds),
            (steps[:, None] + offsets).reshape(-1))
        out = jnp.where(live, sampled.reshape(B, S), 0).astype(jnp.int32)
        if S > 1:
            # accepted = longest prefix with draft t == output t-1
            match = (tokens[:, 1:].astype(jnp.int32) == out[:, :-1]) \
                & (offsets[:, 1:] <= n_draft[:, None])
            accepted = jnp.cumprod(
                match.astype(jnp.int32), axis=1).sum(axis=1)
        else:
            accepted = jnp.zeros((B,), jnp.int32)
        accepted = jnp.where(active, accepted, 0).astype(jnp.int32)
        if adapters is not None:
            return arenas, adapters, out, accepted, logits
        return arenas, out, accepted, logits

    def prefill(self, arenas, params, tokens, position_ids, block_tables,
                lengths, limits, dest_blocks, dest_offsets, sample_index,
                temperature, top_k, top_p, seeds, steps, adapters=None,
                adapter_slots=None):
        """Batched chunked prefill of one ``[max_batch, chunk]`` slice
        (shard_map body).

        Per slot: ``tokens``/``position_ids [max_batch, chunk]`` — this
        tick's slice of the slot's prompt at its *absolute* positions
        (also the RoPE angle source, so chunking composes with rope);
        ``dest_blocks``/``dest_offsets [max_batch, chunk]`` — each
        token's physical cache destination (out-of-range = dropped,
        used for padding); ``block_tables [max_batch, max_blocks]`` and
        ``lengths [max_batch]`` — the slot's table and its total cache
        length INCLUDING this chunk; ``limits [max_batch, chunk]`` —
        per-token causal horizons (0 = padding).  Shared prefix-cache
        blocks and earlier chunks need no special path: they are table
        entries the per-token limits already reach.

        ``sample_index [max_batch]`` — for slots whose prompt completes
        this chunk, the in-chunk index of the last prompt token; the
        logits there are sampled with the slot's policy arrays (the
        request's FIRST generated token).  Out-of-range = no sample.
        Returns ``(arenas, next_tokens [max_batch],
        logits [max_batch, chunk, vocab])`` — with LoRA enabled,
        ``adapters``/``adapter_slots`` join exactly as in
        :meth:`decode_step` and the adapters return between the arenas
        and the tokens.
        """
        cfg = self.cfg
        B, T = tokens.shape
        dest_blocks = dest_blocks.astype(jnp.int32)
        dest_offsets = dest_offsets.astype(jnp.int32)

        if cfg.position_embedding_type == "learned":
            x = self.embed.apply({"params": params.embedding}, tokens,
                                 position_ids)
        else:
            x = self.embed.apply({"params": params.embedding}, tokens)
        # x: [chunk, max_batch, hidden]
        rope = None
        if cfg.position_embedding_type == "rope":
            cos, sin = self._rope_tables(
                position_ids.reshape(-1), x.dtype)
            rope = (cos.reshape(B, T, -1).transpose(1, 0, 2),
                    sin.reshape(B, T, -1).transpose(1, 0, 2))

        attend = (paged_prefill_attention if self.fused_attention
                  else paged_prefill_attention_unfused)

        def attn_core(q, k, v, layer_arenas):
            # q [T, B, n_local, d]; k/v [T, B, g_local, d] (compact GQA)
            if rope is not None:
                cos, sin = rope
                q = apply_rotary_packed(q, cos, sin)
                k = apply_rotary_packed(k, cos, sin)
            layer_arenas = self._append_rows(
                layer_arenas, dest_blocks, dest_offsets,
                k.transpose(1, 0, 2, 3), v.transpose(1, 0, 2, 3))
            kv, sc = self._attend_kwargs(layer_arenas)
            ctx = attend(q.transpose(1, 0, 2, 3), *kv, block_tables,
                         lengths, limits, **sc)   # [B, T, n, d]
            return (ctx.transpose(1, 0, 2, 3).reshape(T, B, -1)
                    .astype(q.dtype), layer_arenas)

        x, arenas, adapters = self._layer_stack(
            params, x, arenas, attn_core, adapters, adapter_slots)
        logits = self._head(params, x)             # [T, B, vocab]
        logits = logits.transpose(1, 0, 2)         # [B, T, vocab]
        idx = jnp.clip(sample_index.astype(jnp.int32), 0, T - 1)
        last = jnp.take_along_axis(
            logits, idx[:, None, None], axis=1)[:, 0]   # [B, vocab]
        sampled = sample_tokens(last, temperature, top_k, top_p,
                                seeds, steps)
        valid = (sample_index.astype(jnp.int32) >= 0) & \
            (sample_index.astype(jnp.int32) < T)
        next_tokens = jnp.where(valid, sampled, 0).astype(jnp.int32)
        if adapters is not None:
            return arenas, adapters, next_tokens, logits
        return arenas, next_tokens, logits
