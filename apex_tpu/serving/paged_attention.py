"""Fused paged-attention decode kernel (Pallas) + its unfused XLA twin.

The decode-step attention of the serving runtime: one query token per
active slot attends over that request's KV cache, which lives scattered
across fixed-size blocks of the pooled arena
(:mod:`apex_tpu.serving.kv_cache`).  The unfused XLA lowering needs a
big gather (materialising ``[batch, max_seq, heads, head_dim]`` K/V
copies in HBM) followed by an unfused chain of elementwise/reduction
ops — exactly the decode profile the operation-fusion paper (PAPERS.md,
arxiv 2502.17728) measures as the dominant cost.  The fused kernel does
**gather + online-softmax attention in one pass**:

- grid ``(batch, max_blocks)`` with the block index innermost; the
  K/V **index maps read the block table** (scalar prefetch —
  ``pltpu.PrefetchScalarGridSpec``), so each grid step's HBM→VMEM copy
  pulls the right physical block directly.  No gathered K/V copy ever
  exists in HBM.
- blocks past the request's length are skipped with ``pl.when`` (no
  MXU/VPU work) and their index maps **clamp to the last live block**,
  so Pallas elides the HBM copy too — the paged analog of the flash
  kernel's causal block skipping (``ops/flash_attention.py``).
- running ``(m, l, acc)`` online-softmax state lives in VMEM scratch
  across the block sweep (the flash decomposition), so VMEM holds
  O(block) state however long the context.
- K/V are read in their **storage dtype** and upcast to fp32 inside
  the kernel (the fused-dequant convention — a bf16 cache moves half
  the HBM bytes and the dequant rides the same VMEM residency).
- grouped-query attention: the arena stores the compact ``kv_heads``
  (= query groups); the kernel broadcasts each group across its query
  heads *in VMEM* — the GQA bandwidth saving is precisely the point of
  storing groups, not heads.

Layouts::

    q:            [batch, n_heads, head_dim]      (one token per slot)
    k/v arena:    [n_blocks, block_size, kv_heads, head_dim]
    block_tables: [batch, max_blocks]  int32  (entries past the live
                  range may be anything in-range; they are clamped)
    lengths:      [batch] int32  (tokens in cache; 0 = inactive slot)
    out:          [batch, n_heads, head_dim]  (zeros for length 0)

``interpret=True`` is selected automatically off-TPU so the same code
runs on the CPU test mesh (the flash-attention convention).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_attention_decode", "paged_attention_decode_unfused"]

NEG_INF = -1e30
_LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _resolve(scale: Optional[float], d: int) -> float:
    return (1.0 / (d ** 0.5)) if scale is None else scale


def _kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
            m_sc, l_sc, acc_sc, *, scale: float, block_size: int, hpg: int):
    i = pl.program_id(0)
    j = pl.program_id(1)
    num_blocks = pl.num_programs(1)
    length = len_ref[i]

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(j * block_size < length)
    def _body():
        q = q_ref[0].astype(jnp.float32)            # [n, d]
        # in-kernel dequant: storage dtype (bf16/fp32 cache) -> fp32
        k = k_ref[0].astype(jnp.float32)            # [bs, g, d]
        v = v_ref[0].astype(jnp.float32)
        if hpg > 1:                                  # GQA broadcast in VMEM
            k = jnp.repeat(k, hpg, axis=1)           # [bs, n, d]
            v = jnp.repeat(v, hpg, axis=1)
        s = jnp.einsum("nd,tnd->nt", q, k) * scale   # [n, bs]
        cols = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        s = jnp.where(cols < length, s, NEG_INF)

        m = m_sc[:, 0]
        l = l_sc[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        # all-masked-row guard (flash convention): exp against a NEG_INF
        # max must yield 0 mass, not exp(0)=1 per masked entry
        m_safe = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
        p = jnp.exp(s - m_safe[:, None])
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc_sc[...] * alpha[:, None] + jnp.einsum(
            "nt,tnd->nd", p, v)
        m_sc[...] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new[:, None], l_sc.shape)
        acc_sc[...] = acc_new

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l_fin = l_sc[:, 0]
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_sc[...] / l_safe[:, None]).astype(o_ref.dtype)


def paged_attention_decode(q, k_arena, v_arena, block_tables, lengths, *,
                           block_size: Optional[int] = None,
                           scale: Optional[float] = None):
    """One fused gather+attention pass over the paged cache.

    See the module docstring for layouts.  ``block_tables`` entries are
    clamped into the live range, so unused table columns may hold any
    value (the scheduler leaves them 0); a slot with ``lengths == 0``
    produces a zero output row.
    """
    b, n, d = q.shape
    n_blocks, bs, g, dk = k_arena.shape
    if block_size is not None and block_size != bs:
        raise ValueError(
            f"block_size ({block_size}) != arena block dim ({bs})")
    if dk != d:
        raise ValueError(f"head_dim mismatch: q {d}, arena {dk}")
    if n % g:
        raise ValueError(f"n_heads ({n}) not a multiple of kv_heads ({g})")
    hpg = n // g
    max_blocks = block_tables.shape[1]

    def kv_idx(i, j, tab_ref, len_ref):
        # clamp skipped blocks to the last live one: Pallas re-references
        # the previous block and elides the HBM copy (flash's causal
        # skip); length 0 clamps to logical block 0 -> table entry 0.
        live = jnp.maximum((len_ref[i] - 1) // bs, 0)
        return (tab_ref[i, jnp.minimum(j, live)], 0, 0, 0)

    def q_idx(i, j, tab_ref, len_ref):
        return (i, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_blocks),
        in_specs=[
            pl.BlockSpec((1, n, d), q_idx),
            pl.BlockSpec((1, bs, g, d), kv_idx),
            pl.BlockSpec((1, bs, g, d), kv_idx),
        ],
        out_specs=pl.BlockSpec((1, n, d), q_idx),
        scratch_shapes=[
            pltpu.VMEM((n, _LANES), jnp.float32),
            pltpu.VMEM((n, _LANES), jnp.float32),
            pltpu.VMEM((n, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_kernel, scale=_resolve(scale, d),
                               block_size=bs, hpg=hpg)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, d), q.dtype),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_arena, v_arena)


def _compiler_params():
    """Batch dim is independent (parallel, megacore-splittable); the
    block sweep carries the online-softmax scratch (arbitrary)."""
    params_cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return params_cls(dimension_semantics=("parallel", "arbitrary"))


def paged_attention_decode_unfused(q, k_arena, v_arena, block_tables,
                                   lengths, *, scale: Optional[float] = None):
    """The plain-XLA lowering of the same computation — the A/B baseline
    (bench ``serving.vs_unfused``) and the parity reference.

    Materialises the gathered ``[batch, max_blocks*block, heads, d]``
    K/V copies in HBM and lets XLA lower the softmax chain — the
    unfused decode profile the Pallas kernel exists to beat.
    """
    b, n, d = q.shape
    _, bs, g, _ = k_arena.shape
    hpg = n // g
    # gather the whole table per slot: [b, max_blocks, bs, g, d]
    k = jnp.take(k_arena, block_tables, axis=0).astype(jnp.float32)
    v = jnp.take(v_arena, block_tables, axis=0).astype(jnp.float32)
    t = block_tables.shape[1] * bs
    k = k.reshape(b, t, g, d)
    v = v.reshape(b, t, g, d)
    if hpg > 1:
        k = jnp.repeat(k, hpg, axis=2)
        v = jnp.repeat(v, hpg, axis=2)
    s = jnp.einsum("bnd,btnd->bnt", q.astype(jnp.float32), k)
    s = s * _resolve(scale, d)
    mask = jnp.arange(t)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(m <= NEG_INF * 0.5, 0.0, m)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bnt,btnd->bnd", p, v) / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)
