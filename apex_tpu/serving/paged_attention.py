"""Fused paged-attention kernels (Pallas) + their unfused XLA twins.

The attention of the serving runtime over the paged KV cache
(:mod:`apex_tpu.serving.kv_cache`), in two shapes:

- **decode** (:func:`paged_attention_decode`) — one query token per
  active slot attends over that request's cached blocks; with a 4-D
  ``q`` the same entry point is the **speculative k+1 verify step**
  (ISSUE 13): ``k + 1`` query positions per slot — the slot's real
  last token plus k drafted continuations — attend with per-position
  causal ``limits`` riding the same scalar-prefetch block-table index
  maps, so draft and verify never bounce through HBM between proposal
  and check (the operation-fusion finding, PAPERS.md 2502.17728);
- **chunked prefill** (:func:`paged_prefill_attention`) — a
  ``[chunk]``-token slice of each slot's prompt attends over the
  request's *whole* context so far: the already-cached history blocks
  (earlier chunks, shared prefix-cache blocks) AND the chunk's own
  tokens, which the caller scatters into the arena *before* the call —
  so one block sweep with a per-token causal ``limit`` covers history
  and in-chunk causality with no second kernel and no softmax merge.

The k+1 verify and the chunked prefill are the *same* multi-query
block sweep (``_multi_query_attention``): a verify step is a
self-proposed chunk whose per-token limits happen to be consecutive.

The unfused XLA lowering of either needs a big gather (materialising
``[batch, max_seq, heads, head_dim]`` K/V copies in HBM) followed by an
unfused chain of elementwise/reduction ops — exactly the decode profile
the operation-fusion paper (PAPERS.md, arxiv 2502.17728) measures as
the dominant cost.  The fused kernels do **gather + online-softmax
attention in one pass**:

- grid ``(batch, max_blocks)`` with the block index innermost; the
  K/V **index maps read the block table** (scalar prefetch —
  ``pltpu.PrefetchScalarGridSpec``), so each grid step's HBM→VMEM copy
  pulls the right physical block directly.  No gathered K/V copy ever
  exists in HBM.
- blocks past the request's length are skipped with ``pl.when`` (no
  MXU/VPU work) and their index maps **clamp to the last live block**,
  so Pallas elides the HBM copy too — the paged analog of the flash
  kernel's causal block skipping (``ops/flash_attention.py``).
- running ``(m, l, acc)`` online-softmax state lives in VMEM scratch
  across the block sweep (the flash decomposition), so VMEM holds
  O(block) state however long the context.
- K/V are read in their **storage dtype** and upcast to fp32 inside
  the kernel (the fused-dequant convention — a bf16 cache moves half
  the HBM bytes and the dequant rides the same VMEM residency).  An
  **int8 cache** passes the per-vector scale arenas
  (``k_scales``/``v_scales``, one fp32 scale per cached row, stored
  block-major beside the block): the scale blocks ride the same
  table-indexed index maps and the dequant is a VMEM multiply —
  quarter the HBM bytes of fp32, half of bf16, for one extra
  ``1/head_dim``-sized read.
- grouped-query attention: the arena stores the compact ``kv_heads``
  (= query groups); the kernel broadcasts each group across its query
  heads *in VMEM* — the GQA bandwidth saving is precisely the point of
  storing groups, not heads.

Layouts::

    decode   q:   [batch, n_heads, head_dim]      (one token per slot)
    verify   q:   [batch, k+1, n_heads, head_dim] (+ per-token limits)
    prefill  q:   [batch, chunk, n_heads, head_dim]
    k/v arena:    [n_blocks, block_size, kv_heads, head_dim]
    k/v scales:   [n_blocks, block_size, kv_heads]  fp32 (int8 cache)
    block_tables: [batch, max_blocks]  int32  (entries past the live
                  range may be anything in-range; they are clamped)
    lengths:      [batch] int32  (tokens in cache; 0 = inactive slot)
    limits:       [batch, chunk] int32 (prefill: each token attends
                  cache positions < limit; 0 = padding token)
    out:          same leading shape as q  (zeros for length/limit 0)

``interpret=True`` is selected automatically off-TPU so the same code
runs on the CPU test mesh (the flash-attention convention).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "paged_attention_decode",
    "paged_attention_decode_unfused",
    "paged_prefill_attention",
    "paged_prefill_attention_unfused",
]

NEG_INF = -1e30
_LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _resolve(scale: Optional[float], d: int) -> float:
    return (1.0 / (d ** 0.5)) if scale is None else scale


def _dequant(k_ref, scale_ref):
    """Storage dtype -> fp32 in VMEM; int8 multiplies its row scales."""
    k = k_ref[0].astype(jnp.float32)            # [bs, g, d]
    if scale_ref is not None:
        k = k * scale_ref[0][..., None]         # [bs, g] row scales
    return k


def _decode_kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                   scale: float, block_size: int, hpg: int,
                   has_scales: bool):
    if has_scales:
        ks_ref, vs_ref, o_ref, m_sc, l_sc, acc_sc = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_sc, l_sc, acc_sc = rest
    i = pl.program_id(0)
    j = pl.program_id(1)
    num_blocks = pl.num_programs(1)
    length = len_ref[i]

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(j * block_size < length)
    def _body():
        q = q_ref[0].astype(jnp.float32)            # [n, d]
        # in-kernel dequant: storage dtype (bf16/int8 cache) -> fp32
        k = _dequant(k_ref, ks_ref)                 # [bs, g, d]
        v = _dequant(v_ref, vs_ref)
        if hpg > 1:                                  # GQA broadcast in VMEM
            k = jnp.repeat(k, hpg, axis=1)           # [bs, n, d]
            v = jnp.repeat(v, hpg, axis=1)
        s = jnp.einsum("nd,tnd->nt", q, k) * scale   # [n, bs]
        cols = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        s = jnp.where(cols < length, s, NEG_INF)

        m = m_sc[:, 0]
        l = l_sc[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        # all-masked-row guard (flash convention): exp against a NEG_INF
        # max must yield 0 mass, not exp(0)=1 per masked entry
        m_safe = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
        p = jnp.exp(s - m_safe[:, None])
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc_sc[...] * alpha[:, None] + jnp.einsum(
            "nt,tnd->nd", p, v)
        m_sc[...] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new[:, None], l_sc.shape)
        acc_sc[...] = acc_new

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l_fin = l_sc[:, 0]
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_sc[...] / l_safe[:, None]).astype(o_ref.dtype)


def _check_arena(q_d, k_arena, n, g, k_scales, v_scales):
    if k_arena.shape[-1] != q_d:
        raise ValueError(
            f"head_dim mismatch: q {q_d}, arena {k_arena.shape[-1]}")
    if n % g:
        raise ValueError(f"n_heads ({n}) not a multiple of kv_heads ({g})")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales or neither")
    if k_scales is not None and k_scales.shape != k_arena.shape[:-1]:
        raise ValueError(
            f"scale arena shape {k_scales.shape} != arena rows "
            f"{k_arena.shape[:-1]}")


def paged_attention_decode(q, k_arena, v_arena, block_tables, lengths, *,
                           limits=None, k_scales=None, v_scales=None,
                           block_size: Optional[int] = None,
                           scale: Optional[float] = None):
    """One fused gather+dequant+attention pass over the paged cache.

    See the module docstring for layouts.  ``block_tables`` entries are
    clamped into the live range, so unused table columns may hold any
    value (the scheduler leaves them 0); a slot with ``lengths == 0``
    produces a zero output row.  ``k_scales``/``v_scales`` (int8 cache)
    are the per-row fp32 scale arenas.

    **Speculative k+1 verify** (ISSUE 13): with ``q`` of shape
    ``[batch, k+1, n, d]`` and per-position ``limits [batch, k+1]``
    (token t attends cache positions ``< limits[:, t]``; 0 = padding —
    a slot drafting fewer than k tokens, or none), the call is the
    fused verify step: all k+1 positions of every slot attend in ONE
    block sweep over the same table-indexed scalar-prefetch index maps,
    with ``lengths`` bounding the sweep at the slot's cache length
    *including* the just-scattered draft rows.
    """
    if q.ndim == 4:
        if limits is None:
            raise ValueError(
                "4-D q (the k+1 verify step) needs per-position limits")
        return _multi_query_attention(
            q, k_arena, v_arena, block_tables, lengths, limits,
            k_scales=k_scales, v_scales=v_scales, scale=scale)
    if limits is not None:
        raise ValueError("limits only apply to a 4-D (multi-query) q")
    b, n, d = q.shape
    n_blocks, bs, g, dk = k_arena.shape
    if block_size is not None and block_size != bs:
        raise ValueError(
            f"block_size ({block_size}) != arena block dim ({bs})")
    _check_arena(d, k_arena, n, g, k_scales, v_scales)
    hpg = n // g
    max_blocks = block_tables.shape[1]
    has_scales = k_scales is not None

    def kv_idx(i, j, tab_ref, len_ref):
        # clamp skipped blocks to the last live one: Pallas re-references
        # the previous block and elides the HBM copy (flash's causal
        # skip); length 0 clamps to logical block 0 -> table entry 0.
        live = jnp.maximum((len_ref[i] - 1) // bs, 0)
        return (tab_ref[i, jnp.minimum(j, live)], 0, 0, 0)

    def sc_idx(i, j, tab_ref, len_ref):
        live = jnp.maximum((len_ref[i] - 1) // bs, 0)
        return (tab_ref[i, jnp.minimum(j, live)], 0, 0)

    def q_idx(i, j, tab_ref, len_ref):
        return (i, 0, 0)

    in_specs = [
        pl.BlockSpec((1, n, d), q_idx),
        pl.BlockSpec((1, bs, g, d), kv_idx),
        pl.BlockSpec((1, bs, g, d), kv_idx),
    ]
    operands = [q, k_arena, v_arena]
    if has_scales:
        in_specs += [pl.BlockSpec((1, bs, g), sc_idx),
                     pl.BlockSpec((1, bs, g), sc_idx)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n, d), q_idx),
        scratch_shapes=[
            pltpu.VMEM((n, _LANES), jnp.float32),
            pltpu.VMEM((n, _LANES), jnp.float32),
            pltpu.VMEM((n, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, scale=_resolve(scale, d),
                               block_size=bs, hpg=hpg,
                               has_scales=has_scales)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, d), q.dtype),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)


def _compiler_params():
    """Batch dim is independent (parallel, megacore-splittable); the
    block sweep carries the online-softmax scratch (arbitrary)."""
    params_cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return params_cls(dimension_semantics=("parallel", "arbitrary"))


def _gathered_kv(q, k_arena, v_arena, block_tables, k_scales, v_scales):
    """The unfused twins' shared gather: materialise per-slot K/V (and
    apply int8 row scales) in HBM — the cost the fused kernels avoid."""
    b, n, d = q.shape
    _, bs, g, _ = k_arena.shape
    hpg = n // g
    k = jnp.take(k_arena, block_tables, axis=0).astype(jnp.float32)
    v = jnp.take(v_arena, block_tables, axis=0).astype(jnp.float32)
    if k_scales is not None:
        ks = jnp.take(k_scales, block_tables, axis=0)
        vs = jnp.take(v_scales, block_tables, axis=0)
        k = k * ks[..., None]
        v = v * vs[..., None]
    t = block_tables.shape[1] * bs
    k = k.reshape(b, t, g, d)
    v = v.reshape(b, t, g, d)
    if hpg > 1:
        k = jnp.repeat(k, hpg, axis=2)
        v = jnp.repeat(v, hpg, axis=2)
    return k, v, t


def paged_attention_decode_unfused(q, k_arena, v_arena, block_tables,
                                   lengths, *, limits=None, k_scales=None,
                                   v_scales=None,
                                   scale: Optional[float] = None):
    """The plain-XLA lowering of the same computation — the A/B baseline
    (bench ``serving.vs_unfused``) and the parity reference.

    Materialises the gathered ``[batch, max_blocks*block, heads, d]``
    K/V copies in HBM and lets XLA lower the softmax chain — the
    unfused decode profile the Pallas kernel exists to beat.  A 4-D
    ``q`` + ``limits`` is the unfused k+1 verify (the fused twin's
    contract, lowered through the prefill-shaped gather).
    """
    if q.ndim == 4:
        if limits is None:
            raise ValueError(
                "4-D q (the k+1 verify step) needs per-position limits")
        return paged_prefill_attention_unfused(
            q, k_arena, v_arena, block_tables, lengths, limits,
            k_scales=k_scales, v_scales=v_scales, scale=scale)
    if limits is not None:
        raise ValueError("limits only apply to a 4-D (multi-query) q")
    b, n, d = q.shape
    _check_arena(d, k_arena, n, k_arena.shape[2], k_scales, v_scales)
    k, v, t = _gathered_kv(q, k_arena, v_arena, block_tables,
                           k_scales, v_scales)
    s = jnp.einsum("bnd,btnd->bnt", q.astype(jnp.float32), k)
    s = s * _resolve(scale, d)
    mask = jnp.arange(t)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(m <= NEG_INF * 0.5, 0.0, m)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bnt,btnd->bnd", p, v) / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


# --------------------------------------------------- chunked prefill


def _prefill_kernel(tab_ref, len_ref, q_ref, lim_ref, k_ref, v_ref, *rest,
                    scale: float, block_size: int, hpg: int,
                    has_scales: bool):
    if has_scales:
        ks_ref, vs_ref, o_ref, m_sc, l_sc, acc_sc = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_sc, l_sc, acc_sc = rest
    i = pl.program_id(0)
    j = pl.program_id(1)
    num_blocks = pl.num_programs(1)
    length = len_ref[i]

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(j * block_size < length)
    def _body():
        q = q_ref[0].astype(jnp.float32)            # [T, n, d]
        lim = lim_ref[0]                            # [T] per-token limits
        k = _dequant(k_ref, ks_ref)                 # [bs, g, d]
        v = _dequant(v_ref, vs_ref)
        if hpg > 1:
            k = jnp.repeat(k, hpg, axis=1)           # [bs, n, d]
            v = jnp.repeat(v, hpg, axis=1)
        s = jnp.einsum("tnd,snd->tns", q, k) * scale  # [T, n, bs]
        cols = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_size), 2)
        # per-token causal limit: token t sees cache positions < lim[t]
        # (its own row, scattered before the call, is position lim[t]-1)
        s = jnp.where(cols < lim[:, None, None], s, NEG_INF)

        m = m_sc[...]                                # [T, n]
        l = l_sc[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=2))
        m_safe = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = l * alpha + jnp.sum(p, axis=2)
        acc_new = acc_sc[...] * alpha[..., None] + jnp.einsum(
            "tns,snd->tnd", p, v)
        m_sc[...] = m_new
        l_sc[...] = l_new
        acc_sc[...] = acc_new

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l_fin = l_sc[...]
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_sc[...] / l_safe[..., None]).astype(o_ref.dtype)


def paged_prefill_attention(q, k_arena, v_arena, block_tables, lengths,
                            limits, *, k_scales=None, v_scales=None,
                            scale: Optional[float] = None):
    """Fused chunked-prefill attention: each slot's ``[chunk]`` query
    tokens attend over the slot's paged context in one block sweep.

    ``q [batch, chunk, n, d]``; ``lengths [batch]`` — the slot's total
    live cache length INCLUDING the chunk's own just-scattered rows
    (the block-sweep bound); ``limits [batch, chunk]`` — per-token
    causal horizon (token attends positions ``< limit``; 0 = padding
    row, which produces zeros).  History blocks and the chunk's own
    destination blocks are all just table entries — prefix-cache hits,
    earlier chunks, and in-chunk causality need no separate paths.
    """
    return _multi_query_attention(
        q, k_arena, v_arena, block_tables, lengths, limits,
        k_scales=k_scales, v_scales=v_scales, scale=scale)


def _multi_query_attention(q, k_arena, v_arena, block_tables, lengths,
                           limits, *, k_scales=None, v_scales=None,
                           scale: Optional[float] = None):
    """The shared fused multi-query block sweep behind the chunked
    prefill AND the speculative k+1 verify (see the module docstring —
    a verify step is a self-proposed chunk)."""
    b, T, n, d = q.shape
    n_blocks, bs, g, dk = k_arena.shape
    _check_arena(d, k_arena, n, g, k_scales, v_scales)
    hpg = n // g
    max_blocks = block_tables.shape[1]
    has_scales = k_scales is not None

    def kv_idx(i, j, tab_ref, len_ref):
        live = jnp.maximum((len_ref[i] - 1) // bs, 0)
        return (tab_ref[i, jnp.minimum(j, live)], 0, 0, 0)

    def sc_idx(i, j, tab_ref, len_ref):
        live = jnp.maximum((len_ref[i] - 1) // bs, 0)
        return (tab_ref[i, jnp.minimum(j, live)], 0, 0)

    def row_idx(i, j, tab_ref, len_ref):
        return (i, 0)

    def q_idx(i, j, tab_ref, len_ref):
        return (i, 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, T, n, d), q_idx),
        pl.BlockSpec((1, T), row_idx),
        pl.BlockSpec((1, bs, g, d), kv_idx),
        pl.BlockSpec((1, bs, g, d), kv_idx),
    ]
    operands = [q, limits.astype(jnp.int32), k_arena, v_arena]
    if has_scales:
        in_specs += [pl.BlockSpec((1, bs, g), sc_idx),
                     pl.BlockSpec((1, bs, g), sc_idx)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, T, n, d), q_idx),
        scratch_shapes=[
            pltpu.VMEM((T, n), jnp.float32),
            pltpu.VMEM((T, n), jnp.float32),
            pltpu.VMEM((T, n, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_prefill_kernel, scale=_resolve(scale, d),
                               block_size=bs, hpg=hpg,
                               has_scales=has_scales)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, T, n, d), q.dtype),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)


def paged_prefill_attention_unfused(q, k_arena, v_arena, block_tables,
                                    lengths, limits, *, k_scales=None,
                                    v_scales=None,
                                    scale: Optional[float] = None):
    """Plain-XLA chunked-prefill lowering (A/B baseline + parity
    reference): gather each slot's whole table, mask per token."""
    b, T, n, d = q.shape
    _check_arena(d, k_arena, n, k_arena.shape[2], k_scales, v_scales)
    k, v, t = _gathered_kv(q[:, 0], k_arena, v_arena, block_tables,
                           k_scales, v_scales)
    s = jnp.einsum("btnd,bsnd->btns", q.astype(jnp.float32), k)
    s = s * _resolve(scale, d)
    mask = jnp.arange(t)[None, None, None, :] < limits[:, :, None, None]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(m <= NEG_INF * 0.5, 0.0, m)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("btns,bsnd->btnd", p, v) / \
        jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)
