"""Jit-stable sampling policies: temperature / top-k / top-p, per-request
seeds.

The continuous-batching contract extends to sampling: every request can
carry its own policy, but the decode step compiles ONCE — so the
policies are ``[max_batch]`` *data* arrays (temperature, k, p, seed,
step counter), never shapes or Python branches.  A slot's policy
changing between ticks (request churn) re-runs the same executable.

Determinism is load-bearing twice over:

- **greedy** (``temperature == 0``, the default) must be the exact
  ``argmax`` the fleet's failover replay and the smoke's token-identity
  checks rest on — the sampled branch is computed and discarded, the
  ``where`` keeps greedy bit-for-bit;
- **seeded sampling** keys each draw with
  ``fold_in(PRNGKey(seed), step)`` where ``step`` is the request's
  output-token index.  A preempted request replayed through prefill
  resumes at the same counter, so recompute-on-readmit (and the fleet's
  failover replay) reproduces the *same stochastic stream* — sampling
  does not break the bitwise-stitched-stream story, it joins it.

Filter order is the conventional temperature -> top-k -> top-p (p
renormalizes over the k survivors).  ``top_k <= 0`` and
``top_p >= 1`` disable their filters; ``top_k == 1`` degenerates to
greedy by construction (only the argmax survives).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["SamplingParams", "sample_tokens"]

_NEG = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """One request's sampling policy (host-side; packed to device as
    ``[max_batch]`` data by the engine).

    ``temperature == 0`` is exact greedy argmax (the default — and what
    every token-identity contract in the serving stack assumes);
    ``top_k <= 0`` / ``top_p >= 1`` leave those filters off. ``seed``
    plus the request's output-token counter key every draw, so the same
    request replayed (preemption recompute, fleet failover) redraws the
    same stream.

    ``step_offset`` rebases that counter: the engine keys draw i of a
    request at ``fold_in(PRNGKey(seed), step_offset + i)``.  In-process
    it stays 0 — a preempted request keeps its ``output_tokens``, so the
    counter continues by itself.  Across the fleet wire a failover
    replay re-submits ``prompt + emitted`` as a *new* engine request
    whose counter restarts at 0; the router sets ``step_offset`` to the
    emitted count so the survivor redraws the continuation of the SAME
    stream (the stitched sampled stream is bitwise the uninterrupted
    one — pinned in ``tests/test_fleet.py``).

    ``adapter_id`` names the LoRA adapter the request decodes under
    (:mod:`.lora`): ``None`` — the default — gathers the permanent zero
    adapter and is bitwise the bare engine.  It rides the wire inside
    this dataclass, so both transports, failover replay and preemption
    readmit carry it for free; the engine resolves it to an arena slot
    at admission (unknown id -> typed REJECTED) and the slot index is
    per-tick ``[max_batch]`` data, never shape.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    step_offset: int = 0
    adapter_id: Optional[str] = None

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.step_offset < 0:
            raise ValueError(
                f"step_offset must be >= 0, got {self.step_offset}")


def _sample_one(logits, temperature, top_k, top_p, seed, step):
    """One slot's draw; vmapped over the batch."""
    vocab = logits.shape[0]
    x = logits / jnp.maximum(temperature, 1e-6)
    # top-k: threshold at the kth-largest logit (k <= 0 disables)
    sorted_desc = jnp.sort(x)[::-1]
    kth = sorted_desc[jnp.clip(top_k - 1, 0, vocab - 1)]
    x = jnp.where((top_k > 0) & (x < kth), _NEG, x)
    # top-p (nucleus): keep the smallest prefix of the sorted
    # distribution whose mass reaches p; the argmax always survives
    # (cumsum - own prob < p holds for the head token whenever p > 0)
    probs = jax.nn.softmax(x)
    order = jnp.argsort(-x)
    csum = jnp.cumsum(probs[order])
    keep_sorted = (csum - probs[order]) < top_p
    keep = jnp.zeros_like(keep_sorted).at[order].set(keep_sorted)
    x = jnp.where(keep, x, _NEG)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    return jax.random.categorical(key, x).astype(jnp.int32)


def sample_tokens(logits, temperature, top_k, top_p, seeds, steps):
    """Sample one token per slot from ``logits [max_batch, vocab]``.

    All policy arguments are ``[max_batch]`` arrays (data, never
    shape).  Slots with ``temperature == 0`` return the exact fp32
    argmax — the sampled branch is fully masked out by the ``where``,
    so greedy serving stays bitwise deterministic.  The whole drawn
    branch sits under one ``lax.cond`` on ``any(temperature > 0)``
    (a data predicate — still one compile): an all-greedy batch, the
    common production shape and every token-identity contract, pays
    one argmax and zero sort/scatter work per step.  The branch holds
    no collectives (the logits arrive tp-gathered), so the cond is
    APX102-clean by construction.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def drawn(_):
        sampled = jax.vmap(_sample_one)(
            logits, temperature.astype(jnp.float32),
            top_k.astype(jnp.int32), top_p.astype(jnp.float32),
            seeds.astype(jnp.uint32), steps.astype(jnp.int32))
        return jnp.where(temperature <= 0.0, greedy, sampled)

    return jax.lax.cond(jnp.any(temperature > 0.0), drawn,
                        lambda _: greedy, None)
