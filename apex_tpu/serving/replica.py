"""One serving replica = one spawned process hosting a ServingEngine.

The fleet layer (ISSUE 11) multiplies the PR 8 engine: each replica is
a **separate process** with its own mesh, compiled programs, KV arenas
and :class:`~apex_tpu.serving.engine.ServingEngine`, so a replica death
is a process death — exactly the failure the router is built to
survive — and a weight rollout is a process replacement.  The process
lifecycle deliberately mirrors ``data/service.py`` (the one battle-
tested pattern in this repo for a non-daemonic jax child): a startup
handshake carrying replica metadata, error relay with a picklability
pre-test, a ppid orphan watchdog so a SIGKILLed router never leaks
replicas, and an escalating join→terminate→kill teardown through the
shared :func:`~apex_tpu.data._producer.reap_process` ladder.

Wire protocol (multiprocessing queues; every payload is plain
picklable data):

parent → child commands
    ``("submit", frid, prompt, max_new_tokens, eos_id, sampling,
       trace)``
                        — ``sampling`` is the request's per-request
                          :class:`~apex_tpu.serving.sampling.
                          SamplingParams` (or None for greedy): the
                          fleet satellite of ISSUE 13 routes the PR 11
                          engine API over the wire.  Replay stays
                          deterministic by the seeded-counter
                          construction — the router rebases
                          ``step_offset`` by the emitted prefix it
                          re-prefills, so a survivor redraws the SAME
                          stochastic stream.  ``trace`` (ISSUE 15) is
                          the router-minted trace context
                          (``{"trace_id", "attempt"}``, or None when
                          tracing is unarmed): the engine stamps it
                          onto every timeline event of the request, so
                          one fleet-wide id spans every process's
                          spill — including re-dispatches after
                          failover (``attempt`` increments per
                          dispatch).
    ``("submit_many", [(frid, prompt, max_new_tokens, eos_id,
                        sampling, trace), ...])``
                        — batched admission: N requests in ONE queue
                          put/pickle round trip (the router batches a
                          pump's dispatches per replica; at fleet
                          arrival rates the per-command transport
                          overhead was the router's dominant cost)
    ``("drain",)``      — programmatic drain (tests); production
                          rollouts send a real **SIGTERM** instead,
                          through the engine's ``PreemptionGuard``
    ``("stop",)``       — immediate cooperative exit
    ``("load_adapter", adapter_id, payload)``
                        — (ISSUE 17) register a LoRA adapter into the
                          engine's paged adapter arena; ``payload`` may
                          carry ``weights`` (raw per-projection A/B
                          pairs) or a ``seed`` for the deterministic
                          test fixture.  Acked by ``adapter_loaded``.
                          Re-loading a resident id hot-swaps the slot
                          in place — the zero-downtime adapter rollout
                          is this command, per replica, staggered.
    ``("unload_adapter", adapter_id)``
                        — drop the registry reference (the slot frees
                          once the last active request unpins it);
                          acked by ``adapter_unloaded``
    ``("set_knobs", payload)``
                        — (ISSUE 18) live-retune: apply data-only
                          engine knob caps (``prefill_chunk`` /
                          ``spec_k`` — never a shape, never a
                          recompile); ``payload`` also carries the
                          router's ack ``token``.  Acked by
                          ``knobs_set``.

KV-block migration (ISSUE 16 — disaggregated prefill/decode).  The
router relays a request's paged KV from a prefill replica to a decode
replica; each side speaks a handful of extra commands/events, and every
``kv_block`` payload rides its OWN frame on the socket transport so the
session layer's per-frame seq makes the stream resumable at any block
boundary:

parent → child (source / prefill side)
    ``("export_kv", frid)``      — extract the request's block run and
                                   stream it up as events; the request
                                   leaves this engine (its stream
                                   continues on the decode side)
    ``("kv_ack", frid, ok)``     — the migration's outcome: either way
                                   the pinned run releases (full blocks
                                   index into the local prefix cache —
                                   valid KV regardless, and the failed
                                   case's re-prefill then hits it)

parent → child (destination / decode side)
    ``("import_kv", frid, meta)``           — open a pending import
    ``("kv_block", frid, idx, payload)``    — one block's slabs
    ``("import_commit", frid, item, n)``    — all blocks sent: admit
                                              ``item`` (a
                                              ``wire_submit_item``
                                              whose prompt is the full
                                              stream so far) with the
                                              imported KV; ONE batched
                                              device scatter lands the
                                              payload
    ``("kv_abort", frid)``                  — drop a pending import

child → parent (source side)
    ``("kv_meta", frid, meta)``          — export opened; ``meta`` has
                                           ``cache_len``/``n_blocks``/
                                           ``n_out``/``bytes``/shape
    ``("kv_block", frid, idx, payload)`` — one block, in order
    ``("kv_export_done", frid, n)``      — run fully streamed
    ``("kv_export_failed", frid, why)``  — not exportable (router lets
                                           the request keep decoding
                                           here)

child → parent (destination side)
    ``("kv_imported", frid, ok, why)``   — commit verdict; ``ok`` means
                                           the request is RUNNING here
                                           as if prefilled locally

child → parent events
    ``("ready", meta)``        — engine built; ``meta`` has ``pid``,
                                 ``ckpt_step`` (None for seed init),
                                 ``max_batch``, ``n_blocks``,
                                 ``debug_port`` (``/healthz`` etc.)
    ``("state", snapshot)``    — rate-limited heartbeat: the engine's
                                 ``introspect()`` dict + ``hb`` stamp
                                 (**monotonic**, replica-local — one
                                 clock domain with the worker loop; an
                                 NTP wall-clock step can never skew a
                                 heartbeat age, and the router never
                                 compares it cross-host: liveness runs
                                 on event *arrival* times);
                                 the router's liveness AND admission
                                 signal (free blocks, queue depth,
                                 draining)
    ``("token", frid, token)`` — one generated token, in order
    ``("batch", [event, ...])``— one relay turn's whole event backlog
                                 in ONE queue put (ISSUE 15 satellite —
                                 the socket transport's lesson applied
                                 to the mp queue: one pickled payload
                                 per turn instead of one per feeder
                                 wakeup; :meth:`ReplicaProcess.poll`
                                 unpacks transparently and counts
                                 ``relay_batches`` /
                                 ``relay_batched_events`` for the
                                 router's ``fleet/relay_batch`` mirror)
    ``("finished", frid)`` / ``("cancelled", frid)`` /
    ``("rejected", frid, why)`` — terminal transitions; ``cancelled``
                                 means drained-out-of-queue (the router
                                 reschedules it), ``rejected`` means
                                 refused at submit
    ``("drained", delivered)`` — the SIGTERM drain completed: every
                                 in-flight request delivered; the child
                                 exits 0 right after
    ``("adapter_loaded", adapter_id, ok, info)`` /
    ``("adapter_unloaded", adapter_id, ok, info)``
                               — (ISSUE 17) adapter command verdicts:
                                 ``info`` is ``{"slot": int,
                                 "evicted": id-or-None}`` on success,
                                 the repr'd error otherwise.  The
                                 router's ``load_adapter`` broadcast
                                 and staggered ``swap_adapter`` both
                                 pump on these acks.
    ``("knobs_set", token, ok, info)``
                               — (ISSUE 18) retune verdict: ``info``
                                 is the engine's applied knob dict on
                                 success, the repr'd error otherwise;
                                 the router's ``set_knobs`` broadcast
                                 pumps on these, keyed by ``token``.
    ``("error", exc)``         — relayed fatal; the child exits

A SIGKILLed child never sends ``drained`` — the router sees the dead
process/pipe, drains whatever events DID flush (tokens generated before
the kill are real and kept), and replays the remainder elsewhere
(``fleet.py``).  Token events are emitted strictly in generation order,
so the router-side stitched stream is a prefix of the true greedy
stream at every instant.
"""

from __future__ import annotations

import dataclasses
import logging
import queue as queue_mod
from typing import Any, Optional, Sequence

__all__ = ["ReplicaSpec", "ReplicaProcess", "wire_submit_item"]

logger = logging.getLogger(__name__)


def wire_submit_item(item: Sequence) -> tuple:
    """Normalize one ``submit_many`` entry to the wire tuple ``(frid,
    prompt, max_new_tokens, eos_id, sampling, trace)`` — the ONE
    definition both transports encode with (a 5-tuple from a pre-15
    caller gets ``trace=None``), so the mp-queue and socket wires can
    never drift apart on the format."""
    frid, prompt, max_new, eos, samp = item[:5]
    trace = item[5] if len(item) > 5 else None
    return (frid, [int(t) for t in prompt], int(max_new), eos,
            samp, trace)


def _state_snapshot(engine) -> dict:
    """One state-heartbeat payload: ``introspect()`` + an ``hb`` stamp
    on the **monotonic** clock.  The worker loop's cadence and the
    router's probe ladder both run on monotonic time; stamping the
    snapshot from the wall clock (the pre-ISSUE-14 bug) meant an NTP
    step could make heartbeat ages jump by the slew — unified here so
    no clock domain ever mixes wall time into liveness."""
    import time

    snap = engine.introspect()
    snap["hb"] = time.monotonic()
    return snap


@dataclasses.dataclass(frozen=True)
class ReplicaSpec:
    """Everything a child needs to stand up one engine (picklable —
    crosses the spawn boundary).

    ``ckpt_dir`` set: params come from the newest VERIFIED checkpoint
    via :func:`~apex_tpu.serving.loader.restore_gpt_for_serving`
    (corrupt-newest falls back; the restored step is reported in the
    ready handshake).  ``ckpt_dir`` None: deterministic seed init — two
    replicas with the same spec serve identical weights.
    """

    config: Any                      # TransformerConfig
    serving: Any                     # ServingConfig
    tp: int = 1
    ckpt_dir: Optional[str] = None
    seed: int = 0
    # fleet role (ISSUE 16): "prefill" replicas take admission +
    # chunked prefill and hand their KV off; "decode" replicas receive
    # migrated KV and run the paged-decode step undisturbed; "both"
    # (the default) is the pre-disaggregation behavior, byte-for-byte.
    # The role is ROUTER policy — the engine underneath is identical;
    # a "prefill" replica that never migrates still decodes correctly.
    role: str = "both"               # "prefill" | "decode" | "both"
    heartbeat_every_s: float = 0.05  # state-event rate limit
    idle_sleep_s: float = 0.005      # loop sleep when no work is queued
    debug_server: bool = True        # /metrics /statusz /healthz
    warmup: bool = True              # pay the prefill/decode compiles
    #                                  BEFORE the ready handshake, so the
    #                                  router's heartbeat timeout never
    #                                  has to cover an XLA compile
    # distributed tracing (ISSUE 15): when set, the child arms its own
    # FlightRecorder spilling to
    # ``<timeline_dir>/timeline.replica.<name>.<pid>.jsonl`` (process
    # identity in the filename AND the run_begin meta), so a fleet's N
    # processes leave N stitchable spills.  None = unarmed (the
    # zero-cost default — every instrumentation point is a None check).
    timeline_dir: Optional[str] = None
    timeline_tick_every: int = 8     # decode_tick sampling (1 = every
    #                                  token: the trace smoke's precise
    #                                  hop boundaries)
    # longitudinal history (ISSUE 20): > 0 arms a child-side
    # MetricHistory sampled every this-many seconds; each completed
    # ring bucket ships to the router as a compacted delta riding the
    # EXISTING state heartbeat (no new command, no new wire frame —
    # ``snap["history"]``), where it merges under ``replica/<name>/``.
    # 0.0 (the default) = disarmed: the heartbeat payload is
    # byte-for-byte the PR 19 shape.
    history_every_s: float = 0.0

    def __post_init__(self):
        if self.role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill' | 'decode' | 'both', "
                f"got {self.role!r}")


def _build_engine(spec: ReplicaSpec, registry, guard):
    """Child-side engine construction; returns (engine, ckpt_step)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import parallel
    from apex_tpu.serving.engine import ServingEngine
    from apex_tpu.serving.loader import restore_gpt_for_serving
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=spec.tp,
        devices=jax.devices()[:max(spec.tp, 1)])
    step = None
    if spec.ckpt_dir is not None:
        params, _, step = restore_gpt_for_serving(
            spec.ckpt_dir, spec.config, mesh=mesh, with_step=True)
    else:
        init_fn, _, _ = build_gpt_3d(
            spec.config, num_chunks=spec.config.num_layers,
            num_microbatches=1, mesh=mesh)
        params, _ = init_fn(jax.random.PRNGKey(spec.seed),
                            jnp.zeros((2, 2), jnp.int32))
    engine = ServingEngine(spec.config, spec.serving, params, mesh=mesh,
                           registry=registry, guard=guard,
                           timeline_tick_every=spec.timeline_tick_every)
    return engine, step


def _replica_worker(spec: ReplicaSpec, name: str, cmd_q, evt_q,
                    parent_pid: int) -> None:
    """Replica-process main: build the engine, serve the command stream,
    relay tokens/state; drain-and-exit on SIGTERM; die on orphanhood."""
    import os
    import time

    from apex_tpu.resilience import PreemptionGuard

    def orphaned() -> bool:
        return os.getppid() != parent_pid

    # the guard installs the real SIGTERM handler (child main thread):
    # the rollout path is the PR 8 drain, not a new mechanism
    guard = PreemptionGuard()
    server = None
    recorder = None
    try:
        from apex_tpu.observability.metrics import MetricRegistry

        if spec.timeline_dir is not None:
            # per-process spill, armed BEFORE the engine builds so the
            # whole request lifecycle lands in it; the filename and the
            # run_begin meta both carry the process identity the trace
            # merger keys on (observability/trace.py)
            from apex_tpu.observability.trace import arm_process

            recorder = arm_process(spec.timeline_dir, "replica", name)
        registry = MetricRegistry(rank=0, world=1)
        engine, ckpt_step = _build_engine(spec, registry, guard)
        if spec.warmup:
            # throwaway tokens: the jitted prefill + decode programs
            # compile HERE, inside the wait_ready window, so once this
            # replica reports ready its step time is steady state and
            # the router's missed-heartbeat detector sees no compile
            # stall it could mistake for a wedge.  max_new=3, not 1:
            # the first token comes out of PREFILL — a 1-token warmup
            # never runs the decode program, deferring its compile to
            # the first live request (exactly the stall this exists to
            # prevent)
            engine.submit([1], 3)
            for _ in range(64):
                if engine.scheduler.idle:
                    break
                engine.step()
        debug_port = None
        if spec.debug_server:
            from apex_tpu.observability.debug_server import DebugServer

            server = DebugServer(registry=registry, engine=engine).start()
            debug_port = server.port
        evt_q.put(("ready", {
            "pid": os.getpid(), "name": name, "ckpt_step": ckpt_step,
            "max_batch": spec.serving.max_batch,
            "n_blocks": engine.cache.n_blocks,
            # context limit: the router needs this to recognize a
            # stream the engine finished at the context cap during
            # failover replay.  prefill_len is None since chunked
            # prefill (ISSUE 12): any prefix short of max_seq can be
            # re-prefilled — the chunk width is a tick-latency knob,
            # not an admission limit
            "max_seq": engine.cache.max_seq,
            "prefill_len": None,
            "debug_port": debug_port,
            "role": spec.role,
            # ISSUE 17: whether this engine has a LoRA adapter arena —
            # the router refuses to broadcast adapters at a bare fleet
            # instead of failing one replica at a time mid-load
            "lora": engine.lora is not None,
        }))

        reqs = {}          # frid -> engine Request
        reported = {}      # frid -> tokens already relayed
        exported = {}      # frid -> engine rid, pinned until kv_ack
        imports = {}       # frid -> {"meta", "blocks": {idx: payload}}
        last_state = 0.0
        history = None
        last_hist = [0.0]
        if spec.history_every_s > 0:
            from apex_tpu.observability.timeseries import MetricHistory

            history = MetricHistory(registry)

        def flush() -> None:
            # one queue put per relay turn (ISSUE 15 satellite): the
            # socket server batches a whole event backlog into each
            # send, while mp.Queue's feeder thread pickles one payload
            # per wakeup — batching here closes that gap for the
            # in-process transport (the wire_vs_inproc lesson).  A
            # single event skips the wrapper; order is preserved (one
            # producer thread, one queue).
            out = []
            for frid in list(reqs):
                req = reqs[frid]
                toks = req.output_tokens
                for tok in toks[reported[frid]:]:
                    out.append(("token", frid, int(tok)))
                reported[frid] = len(toks)
                if req.done:
                    state = req.state.value
                    if state == "finished":
                        out.append(("finished", frid))
                    elif state == "cancelled":
                        out.append(("cancelled", frid))
                    else:
                        out.append(("rejected", frid, state))
                    del reqs[frid], reported[frid]
            if len(out) == 1:
                evt_q.put(out[0])
            elif out:
                evt_q.put(("batch", out))

        def heartbeat(now: float, force: bool = False) -> float:
            if force or now - last_state >= spec.heartbeat_every_s:
                snap = _state_snapshot(engine)
                # migration backlog (ISSUE 16): pending imports not yet
                # committed + exports pinned awaiting ack — the
                # /fleet/statusz backlog signal
                snap["kv_pending_imports"] = len(imports)
                snap["kv_exports_pinned"] = len(exported)
                # history delta (ISSUE 20): sample the local registry on
                # its own cadence and piggyback completed ring buckets on
                # this very heartbeat — the router rebases the bucket
                # stamps onto its own clock at ingest, so the two hosts'
                # monotonic epochs never have to agree
                if history is not None and (
                        now - last_hist[0] >= spec.history_every_s):
                    last_hist[0] = now
                    history.sample(now)
                    delta = history.export_delta(now)
                    if delta is not None:
                        snap["history"] = delta
                evt_q.put(("state", snap))
                return now
            return last_state

        def admit_one(frid, prompt, max_new, eos, sampling=None,
                      trace=None) -> None:
            try:
                req = engine.submit(prompt, max_new, eos,
                                    sampling=sampling, trace=trace)
            except ValueError as e:
                # unserviceable here (too long for this replica's
                # pool) — typed refusal, the router decides what to
                # do with it
                evt_q.put(("rejected", frid, repr(e)))
            else:
                if req.done:   # rejected in the drain window
                    evt_q.put(("rejected", frid, req.state.value))
                else:
                    reqs[frid] = req
                    reported[frid] = 0

        def export_one(frid) -> None:
            """Source side of a migration: relay any still-unreported
            tokens FIRST (so every token of the stream precedes its
            kv_meta on the wire), then stream the block run up as
            one-frame-per-block events.  The pinned run is released by
            the router's later ``kv_ack``."""
            req = reqs.get(frid)
            if req is None or req.done:
                evt_q.put(("kv_export_failed", frid, "not running here"))
                return
            for tok in req.output_tokens[reported[frid]:]:
                evt_q.put(("token", frid, int(tok)))
            reported[frid] = len(req.output_tokens)
            try:
                meta, payloads = engine.export_request(req)
            except ValueError as e:
                evt_q.put(("kv_export_failed", frid, repr(e)))
                return
            del reqs[frid], reported[frid]
            exported[frid] = req.rid
            evt_q.put(("kv_meta", frid, meta))
            for idx, payload in enumerate(payloads):
                evt_q.put(("kv_block", frid, idx, payload))
            evt_q.put(("kv_export_done", frid, len(payloads)))

        def import_commit(frid, item, n_blocks) -> None:
            """Destination side: every block landed — admit the request
            with the imported KV through ONE batched scatter.  Any
            failure is a typed verdict; the router degrades to
            re-prefill and this engine's arena is untouched."""
            pending = imports.pop(frid, None)
            if pending is None:
                evt_q.put(("kv_imported", frid, False, "no pending import"))
                return
            blocks = pending["blocks"]
            missing = [i for i in range(n_blocks) if i not in blocks]
            if missing:
                evt_q.put(("kv_imported", frid, False,
                           f"missing blocks {missing[:4]}"))
                return
            _, prompt, max_new, eos, sampling, trace = \
                wire_submit_item(item)
            try:
                import numpy as _np

                req = engine.import_request(
                    _np.asarray(prompt, _np.int32), max_new, eos,
                    sampling, trace,
                    cache_len=int(pending["meta"]["cache_len"]),
                    payloads=[blocks[i] for i in range(n_blocks)])
            except ValueError as e:
                evt_q.put(("kv_imported", frid, False, repr(e)))
                return
            if req.done:
                evt_q.put(("kv_imported", frid, False, req.state.value))
                return
            reqs[frid] = req
            reported[frid] = 0
            evt_q.put(("kv_imported", frid, True, None))

        while not orphaned():
            try:
                while True:
                    cmd = cmd_q.get_nowait()
                    if cmd[0] == "submit":
                        admit_one(*cmd[1:])
                    elif cmd[0] == "submit_many":
                        for item in cmd[1]:
                            admit_one(*item)
                    elif cmd[0] == "export_kv":
                        export_one(cmd[1])
                    elif cmd[0] == "kv_ack":
                        rid = exported.pop(cmd[1], None)
                        if rid is not None:
                            engine.release_export(rid, ok=bool(cmd[2]))
                    elif cmd[0] == "import_kv":
                        imports[cmd[1]] = {"meta": cmd[2], "blocks": {}}
                    elif cmd[0] == "kv_block":
                        pend = imports.get(cmd[1])
                        if pend is not None:
                            pend["blocks"][int(cmd[2])] = cmd[3]
                    elif cmd[0] == "import_commit":
                        import_commit(cmd[1], cmd[2], cmd[3])
                    elif cmd[0] == "kv_abort":
                        imports.pop(cmd[1], None)
                    elif cmd[0] == "load_adapter":
                        aid, payload = cmd[1], (cmd[2] or {})
                        try:
                            evicted = engine.adapter_arena.residents() \
                                if engine.adapter_arena else []
                            slot = engine.register_adapter(
                                aid, weights=payload.get("weights"),
                                seed=payload.get("seed"))
                        except Exception as e:  # noqa: BLE001 — verdict
                            evt_q.put(("adapter_loaded", aid, False,
                                       repr(e)))
                        else:
                            gone = [a for a in evicted
                                    if a != aid and
                                    not engine.adapter_arena.resident(a)]
                            evt_q.put(("adapter_loaded", aid, True,
                                       {"slot": int(slot),
                                        "evicted": gone[0] if gone
                                        else None}))
                    elif cmd[0] == "unload_adapter":
                        aid = cmd[1]
                        try:
                            engine.unregister_adapter(aid)
                        except Exception as e:  # noqa: BLE001 — verdict
                            evt_q.put(("adapter_unloaded", aid, False,
                                       repr(e)))
                        else:
                            evt_q.put(("adapter_unloaded", aid, True,
                                       None))
                    elif cmd[0] == "set_knobs":
                        # (ISSUE 18) live retune: apply and ack with the
                        # engine's resulting knob state — the router
                        # pump-waits this verdict (adapter-ack
                        # discipline); a refused payload acks False
                        payload = dict(cmd[1] or {})
                        token = payload.pop("token", None)
                        try:
                            applied = engine.set_knobs(payload)
                        except Exception as e:  # noqa: BLE001 — verdict
                            evt_q.put(("knobs_set", token, False,
                                       repr(e)))
                        else:
                            evt_q.put(("knobs_set", token, True,
                                       applied))
                    elif cmd[0] == "drain":
                        guard.trigger()
                    elif cmd[0] == "stop":
                        flush()
                        return
            except queue_mod.Empty:
                pass
            if not engine.scheduler.idle:
                engine.step()      # drains itself once guard trips
            elif guard.triggered:
                # drain complete: everything delivered, queue empty
                if not engine.draining:
                    engine.drain()
                flush()
                heartbeat(time.monotonic(), force=True)
                evt_q.put(("drained", None))
                return
            else:
                time.sleep(spec.idle_sleep_s)
            flush()
            last_state = heartbeat(time.monotonic())
    except BaseException as e:  # noqa: BLE001 — relayed, not eaten
        import pickle

        try:
            pickle.dumps(e)
        except Exception:
            e = RuntimeError(repr(e))
        try:
            evt_q.put(("error", e))
        except Exception:
            pass
    finally:
        if recorder is not None:
            from apex_tpu.observability import timeline as _tl

            _tl.disarm()
            try:
                recorder.flush()      # run_end on the clean-exit paths
            except Exception:         # a SIGKILL never reaches here —
                pass                  # its spill ends at the torn tail
        if server is not None:
            server.close()
        guard.uninstall()


def _shutdown_replica(cmd_q, proc) -> None:
    """GC/exit finalizer teardown (the data-service pattern: the child
    is non-daemonic, so an unreaped replica would deadlock interpreter
    exit under multiprocessing's own atexit join)."""
    from apex_tpu.data._producer import reap_process

    try:
        cmd_q.put_nowait(("stop",))
    except Exception:
        pass
    reap_process(proc, 10.0, what="serving replica")


class ReplicaProcess:
    """Router-side handle on one replica child — the process transport
    behind the :mod:`~apex_tpu.serving.fleet` client duck-type.

    The router talks to this through five methods (``alive``, ``poll``,
    ``submit``, ``begin_drain``, ``close``) plus ``kill`` for fault
    injection; ``tests/test_fleet.py`` substitutes an in-memory fake
    with the same surface, which is what keeps the router's policy
    logic testable without a single process spawn.
    """

    def __init__(self, spec: ReplicaSpec, name: str, *,
                 start_method: str = "spawn"):
        import multiprocessing as mp
        import os
        import weakref

        self.name = name
        self.meta: Optional[dict] = None
        # batched-relay accounting (ISSUE 15 satellite): how many
        # ("batch", ...) payloads poll() unpacked and how many events
        # rode them — the router mirrors these into fleet/relay_batch*
        self.relay_batches = 0
        self.relay_batched_events = 0
        self._ctx = mp.get_context(start_method)
        self._cmd = self._ctx.Queue()
        self._evt = self._ctx.Queue()
        self._closed = False
        # NON-daemonic + ppid watchdog, exactly like DataService: the
        # child owns compiled XLA programs and a debug server thread; a
        # daemonic child could not be debugged by spawning helpers, and
        # orphan safety comes from the watchdog, not daemonism.
        self._proc = self._ctx.Process(
            target=_replica_worker,
            args=(spec, name, self._cmd, self._evt, os.getpid()),
            daemon=False, name=f"apex-replica-{name}")
        self._proc.start()
        self._finalizer = weakref.finalize(
            self, _shutdown_replica, self._cmd, self._proc)

    # ------------------------------------------------------------ liveness

    def alive(self) -> bool:
        return self._proc.is_alive()

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid

    @property
    def exitcode(self) -> Optional[int]:
        return self._proc.exitcode

    # ------------------------------------------------------------ commands

    def submit(self, frid, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None, sampling=None,
               trace=None) -> None:
        """``sampling``: the request's
        :class:`~apex_tpu.serving.sampling.SamplingParams` (picklable,
        crosses the wire as data) or None for greedy.  ``trace``: the
        router-minted trace context dict, or None when unarmed."""
        self._cmd.put(("submit", frid, [int(t) for t in prompt],
                       int(max_new_tokens), eos_id, sampling, trace))

    def submit_many(self, items: Sequence[tuple]) -> None:
        """Batched admission: ``items`` of ``(frid, prompt,
        max_new_tokens, eos_id, sampling[, trace])`` cross the
        transport as ONE command (one queue put, one pickle) instead of
        N — the router batches each pump's dispatches per replica
        through this."""
        self._cmd.put(("submit_many",
                       [wire_submit_item(it) for it in items]))

    # ------------------------------------------------- KV migration cmds
    # (ISSUE 16) Thin wire wrappers; the router drives the handoff state
    # machine.  On the socket transport each of these is its own frame —
    # which is what makes a torn migration resumable at block
    # granularity via the session layer's per-frame seq.

    def export_kv(self, frid) -> None:
        self._cmd.put(("export_kv", frid))

    def kv_ack(self, frid, ok: bool) -> None:
        self._cmd.put(("kv_ack", frid, bool(ok)))

    def import_kv(self, frid, meta: dict) -> None:
        self._cmd.put(("import_kv", frid, meta))

    def kv_block(self, frid, idx: int, payload) -> None:
        self._cmd.put(("kv_block", frid, int(idx), payload))

    def import_commit(self, frid, item, n_blocks: int) -> None:
        self._cmd.put(("import_commit", frid, wire_submit_item(item),
                       int(n_blocks)))

    def kv_abort(self, frid) -> None:
        self._cmd.put(("kv_abort", frid))

    # ------------------------------------------------- adapter cmds
    # (ISSUE 17) Thin wire wrappers over the engine's adapter registry;
    # the router's broadcast/hot-swap drives these and pumps on the
    # ``adapter_loaded`` / ``adapter_unloaded`` ack events.

    def load_adapter(self, adapter_id, payload: Optional[dict] = None
                     ) -> None:
        self._cmd.put(("load_adapter", adapter_id, dict(payload or {})))

    def unload_adapter(self, adapter_id) -> None:
        self._cmd.put(("unload_adapter", adapter_id))

    def set_knobs(self, payload: dict) -> None:
        """(ISSUE 18) Live-retune: ship the knob payload (plus the
        router's ack token) to the worker; the ``knobs_set`` verdict
        rides the ordinary event stream like the adapter acks."""
        self._cmd.put(("set_knobs", dict(payload or {})))

    def begin_drain(self, *, sigterm: bool = True) -> None:
        """Start the drain: a real SIGTERM (the production rollout
        path — same signal a preempted host gets) or the programmatic
        command when signals are unavailable."""
        import os
        import signal as _signal

        if sigterm and self._proc.pid is not None and self.alive():
            try:
                os.kill(self._proc.pid, _signal.SIGTERM)
                return
            except ProcessLookupError:
                pass
        self._cmd.put(("drain",))

    def kill(self) -> None:
        """SIGKILL — fault injection only (the smoke's dead-replica
        leg).  No drain, no goodbye: the router must cope."""
        import os
        import signal as _signal

        if self._proc.pid is not None:
            try:
                os.kill(self._proc.pid, _signal.SIGKILL)
            except ProcessLookupError:
                pass

    # -------------------------------------------------------------- events

    def poll(self) -> list:
        """Drain every event the child has flushed (non-blocking).
        Readable even after a SIGKILL — whatever reached the pipe
        before death is real and must be consumed before failover."""
        events = []
        while True:
            try:
                ev = self._evt.get_nowait()
            except queue_mod.Empty:
                break
            except (EOFError, OSError):
                break
            if ev and ev[0] == "batch":
                # the worker's one-put-per-relay-turn payload: unpack
                # transparently (order preserved) and count it, so the
                # router can surface fleet/relay_batch without touching
                # the wire format
                self.relay_batches += 1
                self.relay_batched_events += len(ev[1])
                events.extend(ev[1])
            else:
                events.append(ev)
        return events

    def wait_ready(self, timeout: float = 300.0) -> dict:
        """Block until the startup handshake (engine built); relays a
        child-side construction error.  Returns (and caches) ``meta``;
        any events read past the handshake are re-deliverable via
        :meth:`poll` order — ready is always the FIRST event, so
        nothing can precede it."""
        if self.meta is not None:
            return self.meta
        try:
            kind, payload = self._evt.get(timeout=timeout)
        except queue_mod.Empty:
            alive = self.alive()
            raise RuntimeError(
                f"replica {self.name}: no ready handshake in "
                f"{timeout:.0f}s (alive={alive}, "
                f"exitcode={self.exitcode})") from None
        if kind == "error":
            raise payload
        if kind != "ready":
            raise RuntimeError(
                f"replica {self.name}: handshake got {kind!r} before "
                "ready")
        self.meta = payload
        return payload

    # ------------------------------------------------------------ teardown

    def close(self, timeout: float = 10.0) -> None:
        """Cooperative stop + escalating reap (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._finalizer.detach()
        try:
            self._cmd.put_nowait(("stop",))
        except Exception:
            pass
        # drain events so a child blocked on a full pipe can exit
        self.poll()
        from apex_tpu.data._producer import reap_process

        reap_process(self._proc, timeout, what="serving replica")
        for q in (self._cmd, self._evt):
            try:
                q.close()
            except Exception:
                pass

    def __enter__(self) -> "ReplicaProcess":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
